"""Self-healing elastic training: the health plane closes its loop.

Both halves of the DETECTION story exist elsewhere — collectives raise
typed ``CollectiveError`` naming suspect ranks, the health plane emits
``StallEvent``s from per-rank progress beacons, and actor death surfaces
as ``ActorDiedError`` — but until this module nothing *reacted*: a dead
or lagging worker killed the whole ``fit()``. The ElasticCoordinator
subscribes to those events for one gang and drives a remediation state
machine with no operator in the loop:

    monitor ──suspect──▶ quarantine ──▶ shrink/refill ──▶ re-form
       ▲                 (hold slot)     (gang demand     collectives
       │                                  on shortfall)   (@g<N> name)
       └──────── resume from latest orbax checkpoint ◀── rebuild mesh

Event sources folded by the monitor, every ``poll_interval_s``:

* **actor death** — every rank is polled (not just rank 0); a poll that
  raises a death error marks that rank suspect, bundle freed for reuse.
* **CollectiveError suspect ranks** — a failed ``run()`` whose TaskError
  cause is a CollectiveError contributes ``cause.suspect_ranks``;
  suspects quarantined (their slot held, refill lands elsewhere).
* **StallEvents** — the GCS health report's ``train:r<N>`` stalls are
  matched to this gang via the run tag the session stamps into its
  beacon context; a stalled rank is quarantined. A stall of this gang's
  collective group without a named rank forces a full-gang rebuild.
* **straggler verdicts** — per-rank EWMA over the ``compute_s`` metric
  when loops report one (the honest signal in a synchronous gang, where
  everyone's *report cadence* collapses to the straggler's), else over
  inter-report cadence; a rank beyond ``straggler_k`` x the median of
  its peers is demoted and its slot quarantined.

The reverse direction: a gang below target reports its shortfall as
gang demand through the GCS (the same reporter-keyed, staleness-aged
``report_load`` shape the serve controller uses — PAPER.md L2's
infeasible-queue → autoscaler reporting), and every
``grow_check_interval_s`` probes cluster capacity; when a worker-sized
hole appears it rebuilds the gang larger, resuming from the latest
checkpoint. Remediations are reported to the GCS as ``remediation``
health events (timeline instants + ``cli doctor`` context).

Re-meshing rides ``ray_tpu.parallel.presets``: ``session.get_mesh()`` in
each (re)spawned worker rebinds the process-default mesh, so user steps
decorated with ``sharded_jit`` recompile against the new topology with
sharding config at one site.
"""

from __future__ import annotations

import os
import statistics
import time
import uuid
from typing import Any, Dict, List, Optional, Set, Tuple

import ray_tpu
from ray_tpu.collective.errors import CollectiveError
from ray_tpu.core.status import (ActorDiedError, ActorUnavailableError,
                                 NodeDiedError, TaskError,
                                 WorkerCrashedError)
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.worker_group import WorkerGroup

_DEATH_ERRORS = (ActorDiedError, ActorUnavailableError, WorkerCrashedError,
                 NodeDiedError)


def _cluster_cfg():
    from ray_tpu.core import runtime as _rt
    from ray_tpu.core.config import GLOBAL_CONFIG

    rt = _rt.current_runtime_or_none()
    return rt.cfg if rt is not None else GLOBAL_CONFIG


# --------------------------------------------------------------------------
# decision logic (pure; unit-testable without a cluster)
# --------------------------------------------------------------------------

class RemediationPolicy:
    """Folds one attempt's health signals into suspect ranks + reasons.

    Reasons drive the quarantine decision downstream: a ``died`` rank's
    bundle is freed for reuse (the process is gone; the slot is fine),
    while ``straggler``/``stall``/``collective`` slots are quarantined —
    still reserved, never refilled, so the replacement cannot land back
    on the suspect host/process."""

    def __init__(self, world: int, *, run_tag: str = "",
                 collective_group: Optional[str] = None,
                 straggler_k: float = 3.0,
                 straggler_min_reports: int = 4,
                 quarantine_stragglers: bool = True):
        self.world = world
        self.run_tag = run_tag
        self.collective_group = collective_group
        self.straggler_k = float(straggler_k)
        self.straggler_min_reports = int(straggler_min_reports)
        self.quarantine_stragglers = quarantine_stragglers
        self.suspects: Dict[int, str] = {}     # rank -> reason
        self.gang_stall = False                # unattributed: rebuild all
        # rank -> (ewma_seconds, n_observations, last_report_ts)
        self._cadence: Dict[int, Tuple[float, int, float]] = {}

    # -- event intake ------------------------------------------------------

    def observe_death(self, rank: int) -> None:
        self.suspects.setdefault(rank, "died")

    def observe_task_error(self, exc: BaseException) -> str:
        """Classify a failed run(): 'remediate' for infrastructure
        failures (collective suspects folded in), 'user_error' for
        anything the loop itself raised."""
        cause = exc.cause if isinstance(exc, TaskError) else exc
        if isinstance(cause, CollectiveError):
            ranks = getattr(cause, "suspect_ranks", None) or []
            for r in ranks:
                if 0 <= int(r) < self.world:
                    self.suspects.setdefault(int(r), "collective")
            if not ranks:
                self.gang_stall = True      # timeout with no attribution
            return "remediate"
        if isinstance(cause, _DEATH_ERRORS):
            self.gang_stall = True
            return "remediate"
        return "user_error"

    def observe_health_events(self, events: List[dict],
                              after_ts: float) -> None:
        """Fold GCS health events: per-rank train beacon stalls matched
        by run tag, plus unattributed stalls of this gang's collective
        group."""
        for ev in events:
            if ev.get("kind") != "stall" or float(ev.get("ts", 0)) < after_ts:
                continue
            comp = str(ev.get("component", ""))
            ctx = ev.get("context") or {}
            if (comp.startswith("train:r")
                    and ctx.get("run") == self.run_tag and self.run_tag):
                try:
                    rank = int(comp[len("train:r"):])
                except ValueError:
                    continue
                if 0 <= rank < self.world:
                    self.suspects.setdefault(rank, "stall")
            elif (self.collective_group
                    and comp.startswith(
                        f"collective:{self.collective_group}:r")):
                # the stalled component is the WAITING rank (the victim);
                # without a named culprit the whole gang rebuilds
                self.gang_stall = True

    def observe_report(self, rank: int, ts: float,
                       compute_s: Optional[float] = None) -> None:
        """One session.report() from `rank`. Prefers the loop-reported
        per-step compute time; falls back to inter-report cadence (only
        meaningful for uncoupled gangs — a synchronous collective drags
        every rank's cadence down to the straggler's)."""
        ewma, n, last = self._cadence.get(rank, (0.0, 0, 0.0))
        sample = None
        if compute_s is not None:
            sample = float(compute_s)
        elif n > 0:
            sample = max(0.0, ts - last)
        if sample is not None:
            ewma = sample if n <= 1 else 0.5 * ewma + 0.5 * sample
        self._cadence[rank] = (ewma, n + 1, ts)

    # -- verdicts ------------------------------------------------------------

    def straggler_verdict(self) -> Optional[int]:
        """The single worst rank whose EWMA exceeds straggler_k x the
        median of its peers, once every live rank has warmed up; None
        while healthy."""
        if not self.quarantine_stragglers or self.world < 2:
            return None
        live = [r for r in range(self.world) if r not in self.suspects]
        stats = {r: self._cadence.get(r) for r in live}
        if any(s is None or s[1] < self.straggler_min_reports
               for s in stats.values()):
            return None
        worst, worst_ratio = None, 0.0
        for r in live:
            peers = [stats[p][0] for p in live if p != r and stats[p][0] > 0]
            if not peers:
                continue
            base = statistics.median(peers)
            if base <= 0:
                continue
            ratio = stats[r][0] / base
            if ratio > self.straggler_k and ratio > worst_ratio:
                worst, worst_ratio = r, ratio
        return worst

    def flag_straggler(self, rank: int) -> None:
        self.suspects.setdefault(rank, "straggler")

    def wants_remediation(self) -> bool:
        return bool(self.suspects) or self.gang_stall

    def summary(self) -> dict:
        return {"suspects": {r: why for r, why in
                             sorted(self.suspects.items())},
                "gang_stall": self.gang_stall}


# --------------------------------------------------------------------------
# the coordinator
# --------------------------------------------------------------------------

class ElasticCoordinator:
    """Runs a JaxTrainer's fit() as a remediation loop (see module
    docstring). Constructed by ``JaxTrainer.fit()`` whenever
    ``ScalingConfig.elastic`` is set."""

    def __init__(self, trainer):
        self.trainer = trainer
        self.el = trainer.scaling.elastic
        cfg = _cluster_cfg()
        e = self.el
        # every elastic_* cluster knob is the default the per-run
        # ElasticConfig override falls back to
        self.poll_interval = (e.poll_interval_s
                              if e.poll_interval_s is not None
                              else cfg.elastic_poll_interval_s)
        self.health_poll_interval = (
            e.health_poll_interval_s if e.health_poll_interval_s is not None
            else cfg.elastic_health_poll_interval_s)
        self.straggler_k = (e.straggler_k if e.straggler_k is not None
                            else cfg.elastic_straggler_k)
        self.straggler_min_reports = (
            e.straggler_min_reports if e.straggler_min_reports is not None
            else cfg.elastic_straggler_min_reports)
        self.grow_check_interval = (
            e.grow_check_interval_s if e.grow_check_interval_s is not None
            else cfg.elastic_grow_check_interval_s)
        self.reserve_timeout = (e.reserve_timeout_s
                                if e.reserve_timeout_s is not None
                                else cfg.elastic_reserve_timeout_s)
        self.drain_grace = (e.drain_grace_s if e.drain_grace_s is not None
                            else cfg.elastic_drain_grace_s)
        self.target = trainer.scaling.num_workers
        self.max_workers = min(e.max_workers or self.target,
                               max(self.target, e.max_workers or 0))
        self.min_workers = max(1, e.min_workers)
        self.worker_res = trainer.scaling.worker_resources()
        self.run_tag = ""
        self.summary: Dict[str, Any] = {}
        # Monotonic per-reporter sequence on gang-demand reports: the
        # GCS drops any report whose seq is <= the last one applied, so
        # a delayed/duplicated stale report (network reordering, chaos
        # plane) cannot resurrect demand a newer count=0 cleared.
        self._gang_seq = 0

    # -- GCS plumbing (all best-effort: the gang must survive a GCS blip) --

    def _gcs_call(self, method: str, **kw):
        from ray_tpu.core import runtime as _rt

        rt = _rt.current_runtime_or_none()
        if rt is None:
            return None
        try:
            return rt.gcs_call(method, **kw)
        except Exception:
            return None

    def _emit_event(self, action: str, **fields) -> None:
        ev = {"kind": "remediation", "component": f"train:{self.run_tag}",
              "action": action, "ts": time.time(), **fields}
        self.summary.setdefault("remediations", []).append(ev)
        self._gcs_call("report_remediation", event=ev)

    def _report_gang_demand(self, group: WorkerGroup) -> None:
        """Fold this gang's shortfall into autoscaler-visible unmet
        demand (reporter-keyed + staleness-aged at the GCS, the serve
        report_load shape); count=0 clears the row once whole."""
        shortfall = max(0, min(self.target, self.max_workers)
                        - group.num_workers)
        self._gang_seq += 1
        self._gcs_call("report_gang_demand", name=f"train:{self.run_tag}",
                       reporter=self.run_tag,
                       resources=dict(self.worker_res), count=shortfall,
                       seq=self._gang_seq)

    def _capacity_available(self) -> bool:
        """Cheap pre-gate for a grow attempt: some node's available
        vector fits one worker (the add itself still reserves through a
        PG, so a race here only wastes one short reservation wait)."""
        avail = self._gcs_call("get_available_resources")
        if not avail:
            return False
        for q in avail.values():
            if all(q.get(k, 0.0) >= v for k, v in self.worker_res.items()):
                return True
        return False

    # -- gang construction --------------------------------------------------

    def _build_group(self) -> WorkerGroup:
        """Reserve the target gang, degrading toward min_workers when
        the cluster can't fit it (the shortfall is reported as gang
        demand and the grow path finishes the job later)."""
        n = self.target
        last_err: Optional[BaseException] = None
        while n >= self.min_workers:
            try:
                return WorkerGroup(n, self.worker_res,
                                   pg_timeout_s=self.reserve_timeout)
            except ray_tpu.exceptions.PlacementGroupUnavailableError as e:
                last_err = e
                n -= 1
        raise last_err  # type: ignore[misc]

    # -- the remediation loop -------------------------------------------------

    def fit(self):
        from ray_tpu.train.trainer import Result, _latest_checkpoint

        trainer = self.trainer
        run_dir = trainer._run_dir()
        self.run_tag = (f"{os.path.basename(run_dir.rstrip('/'))}"
                        f"-{uuid.uuid4().hex[:6]}")
        result = Result()
        self.summary = {"run_tag": self.run_tag, "remediations": [],
                        "world_sizes": [], "generations": 0}
        result.elastic = self.summary
        checkpoint: Optional[Checkpoint] = trainer.resume_from
        group = self._build_group()
        self._report_gang_demand(group)
        if group.num_workers < self.target:
            self._emit_event("degraded_start", world=group.num_workers,
                             target=self.target)
        generation = 0
        remediations = 0
        try:
            while True:
                generation += 1
                self.summary["generations"] = generation
                self.summary["world_sizes"].append(group.num_workers)
                col_group = None
                if self.el.host_collective:
                    from ray_tpu import collective as col

                    col_group = col.reform_collective_group(
                        f"elastic:{self.run_tag}", generation)
                verdict, data = self._run_attempt(
                    group, run_dir, checkpoint, col_group, generation, result)
                if verdict == "finished":
                    if result.metrics.get("_checkpoint"):
                        result.checkpoint = Checkpoint(
                            result.metrics["_checkpoint"],
                            uri=result.metrics.get("_checkpoint_uri"))
                    else:
                        result.checkpoint = _latest_checkpoint(run_dir)
                    return result
                if verdict == "user_error":
                    result.error = data
                    return result
                # verdict in ("remediate", "grow"): rebuild the gang
                remediations += 1
                if remediations > self.el.max_remediations:
                    result.error = (
                        f"elastic: gave up after {self.el.max_remediations} "
                        f"remediations (last: {data.summary() if hasattr(data, 'summary') else data})")
                    return result
                world_before = group.num_workers
                suspects: Dict[int, str] = {}
                if verdict == "remediate":
                    policy: RemediationPolicy = data
                    suspects = {r: why for r, why in policy.suspects.items()
                                if 0 <= r < group.num_workers}
                    # reverse order: each removal re-indexes the tail
                    for r in sorted(suspects, reverse=True):
                        group.remove_workers(
                            [r], quarantine=suspects[r] != "died")
                # survivors respawn as fresh processes: a user loop
                # thread can't be preempted, and its jax/collective
                # state is bound to the dead topology
                group.respawn_workers()
                # resolve the resume checkpoint only AFTER the respawn
                # killed the survivors: until then rank 0 is still
                # saving and evicting (num_to_keep), so a scan can catch
                # every candidate mid-commit or mid-eviction — and a
                # checkpoint picked earlier could be evicted before the
                # next generation loads it. Post-kill the directory is
                # quiescent; a save interrupted by the kill leaves only
                # an uncommitted tmp dir, which _complete() skips.
                checkpoint = _latest_checkpoint(run_dir) or checkpoint
                if self.el.refill or verdict == "grow":
                    want = (min(self.target, self.max_workers)
                            - group.num_workers)
                    if want > 0:
                        group.add_workers(want, timeout=self.reserve_timeout,
                                          partial=True)
                self._report_gang_demand(group)
                self._emit_event(
                    "grow" if verdict == "grow" else "remediate",
                    suspects={str(r): why for r, why in suspects.items()},
                    world_before=world_before, world_after=group.num_workers,
                    quarantined=group.quarantined_count,
                    generation=generation,
                    checkpoint=checkpoint.path if checkpoint else None,
                    checkpoint_procs=(checkpoint.saved_process_count()
                                      if checkpoint else None))
                if group.num_workers < self.min_workers:
                    result.error = (
                        f"elastic: gang at {group.num_workers} worker(s), "
                        f"below min_workers={self.min_workers} and refill "
                        "found no capacity")
                    return result
        finally:
            self._gang_seq += 1
            self._gcs_call("report_gang_demand",
                           name=f"train:{self.run_tag}",
                           reporter=self.run_tag,
                           resources=dict(self.worker_res), count=0,
                           seq=self._gang_seq)
            group.shutdown()

    # -- one generation -------------------------------------------------------

    def _run_attempt(self, group: WorkerGroup, run_dir: str,
                     checkpoint: Optional[Checkpoint],
                     col_group: Optional[str], generation: int,
                     result) -> Tuple[str, Any]:
        """Set up + run one gang incarnation, monitoring every rank.
        Returns (verdict, data): ("finished", None), ("user_error", msg),
        ("remediate", policy), or ("grow", target_world)."""
        from ray_tpu.train.trainer import _latest_checkpoint, _split_datasets

        trainer = self.trainer
        world = group.num_workers
        policy = RemediationPolicy(
            world, run_tag=self.run_tag, collective_group=col_group,
            straggler_k=self.straggler_k,
            straggler_min_reports=self.straggler_min_reports,
            quarantine_stragglers=self.el.quarantine_stragglers)
        attempt_start = time.time()
        elastic_meta: Dict[str, Any] = {"run_tag": self.run_tag,
                                        "generation": generation}
        if col_group:
            elastic_meta["collective_group"] = col_group
        if self.el.step_deadline_s:
            elastic_meta["step_deadline_s"] = self.el.step_deadline_s
        shards = _split_datasets(trainer.datasets, world)
        try:
            coordinator = None
            if world > 1 or trainer.backend.needs_coordinator:
                if getattr(trainer.backend, "needs_worker_addresses", False):
                    infos = ray_tpu.get(
                        [w.host_info.remote() for w in group.workers])
                    trainer.backend.worker_addresses = [
                        f"{i['hostname']}:{i['free_port']}" for i in infos]
                    coordinator = trainer.backend.worker_addresses[0]
                else:
                    info = ray_tpu.get(group.workers[0].host_info.remote())
                    coordinator = f"{info['hostname']}:{info['free_port']}"
            ray_tpu.get([
                w.setup.remote(
                    trainer.config, run_dir, trainer.scaling,
                    checkpoint, shards[i], coordinator,
                    trainer.run_config.checkpoint_config.num_to_keep,
                    trainer.backend, elastic_meta)
                for i, w in enumerate(group.workers)])
            if col_group:
                group.init_host_collective(group_name=col_group)
        except _DEATH_ERRORS:
            # a rank died during bootstrap: rebuild everyone (the dead
            # rank shows up as unreachable in the next incarnation's
            # probe; its bundle is reused since the death freed it)
            policy.gang_stall = True
            return "remediate", policy
        run_refs = [w.run.remote(trainer.loop, trainer.config)
                    for w in group.workers]
        seen = [0] * world

        def drain0() -> None:
            # Final polls of rank 0 before this generation is torn down.
            # Two jobs: (1) reports produced after the last monitor poll
            # would vanish when respawn kills the actor — a gap in the
            # loss curve even though the steps ran; (2) a report entry
            # appends only AFTER its checkpoint save commits, so waiting
            # for one fresh report (up to drain_grace_s) guarantees a
            # complete checkpoint exists — without it, a peer death
            # seconds into a run kills rank 0 mid-first-save and the
            # next generation restarts from scratch.
            if not group.workers or 0 in policy.suspects:
                return
            deadline = time.time() + self.drain_grace
            while True:
                try:
                    p = ray_tpu.get(group.workers[0].poll.remote(seen[0]),
                                    timeout=10)
                except Exception:
                    return
                for r in p["reports"]:
                    result.metrics_history.append(r)
                    result.metrics = r
                seen[0] += len(p["reports"])
                if p["reports"] or p["finished"] or p["error"] \
                        or time.time() >= deadline:
                    return
                time.sleep(min(0.2, self.poll_interval))

        finished = [False] * world
        hang_timeout = trainer.run_config.failure_config.hang_timeout_s
        startup_grace = trainer.run_config.failure_config.startup_grace_s
        last_progress = time.time()
        got_report = False
        last_health_poll = time.time()
        last_grow_probe = time.time()
        while True:
            now = time.time()
            for i, w in enumerate(group.workers):
                if finished[i] or i in policy.suspects:
                    continue
                try:
                    poll = ray_tpu.get(w.poll.remote(seen[i]), timeout=60)
                except _DEATH_ERRORS:
                    policy.observe_death(i)
                    continue
                for r in poll["reports"]:
                    policy.observe_report(i, float(r.get("_ts", now)),
                                          compute_s=r.get("compute_s"))
                    if i == 0:
                        result.metrics_history.append(r)
                        result.metrics = r
                seen[i] += len(poll["reports"])
                if poll["reports"]:
                    last_progress = time.time()
                    got_report = True
                if poll["finished"]:
                    finished[i] = True
                elif poll["error"]:
                    kind = self._classify_run_error(run_refs[i], policy)
                    if kind == "user_error":
                        return "user_error", poll["error"]
                    if not policy.wants_remediation():
                        # classified infrastructure failure but with no
                        # attributable suspect: rebuild the whole gang
                        # rather than re-polling the errored rank forever
                        policy.gang_stall = True
            if all(finished):
                return "finished", None
            if policy.wants_remediation():
                drain0()
                return "remediate", policy
            s = policy.straggler_verdict()
            if s is not None:
                policy.flag_straggler(s)
                drain0()
                return "remediate", policy
            if now - last_health_poll >= self.health_poll_interval:
                last_health_poll = now
                rep = self._gcs_call("health_report")
                if rep:
                    policy.observe_health_events(rep.get("events") or [],
                                                 after_ts=attempt_start)
                    if policy.wants_remediation():
                        drain0()
                        return "remediate", policy
            # trainer-parity hang watchdog: a live-but-hung gang (stuck
            # pjit program) never raises — rebuild everyone
            limit = (hang_timeout if got_report
                     else max(hang_timeout or 0.0, startup_grace))
            if (hang_timeout is not None
                    and time.time() - last_progress > limit):
                policy.gang_stall = True
                return "remediate", policy
            # grow path: shrunken gang + capacity + a checkpoint to
            # restart from (or no progress worth keeping yet)
            if (self.el.grow
                    and world < min(self.target, self.max_workers)
                    and now - last_grow_probe >= self.grow_check_interval):
                last_grow_probe = now
                self._report_gang_demand(group)
                restartable = (not got_report
                               or _latest_checkpoint(run_dir) is not None)
                if restartable and self._capacity_available():
                    drain0()
                    return "grow", min(self.target, self.max_workers)
            time.sleep(self.poll_interval)

    def _classify_run_error(self, ref, policy: RemediationPolicy) -> str:
        """Resolve a failed run() ref into a policy verdict."""
        try:
            ray_tpu.get(ref, timeout=60)
        except TaskError as e:
            return policy.observe_task_error(e)
        except _DEATH_ERRORS:
            policy.gang_stall = True
            return "remediate"
        except Exception as e:
            return policy.observe_task_error(e)
        return "remediate"
