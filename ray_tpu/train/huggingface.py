"""HuggingFace integration trainers: Transformers + Accelerate.

Reference: python/ray/train/huggingface/ —
TransformersTrainer (transformers_trainer.py: a DataParallelTrainer whose
`trainer_init_per_worker` builds a transformers.Trainer on every rank;
torch.distributed is already up, so HF's own DDP engages) and
AccelerateTrainer (accelerate/accelerate_trainer.py:89: the user loop
constructs `accelerate.Accelerator()` which adopts the live process
group — DeepSpeed/FSDP configs pass through the same way).

Both libraries are in the TPU image; these trainers run the host-side
(torch-CPU gloo) migration path, like TorchTrainer. The JAX/TPU path is
JaxTrainer — these exist so reference users' HF loops run unchanged
while they port to the TPU-native stack.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, Optional

from ray_tpu.train.backend import TorchBackend
from ray_tpu.train.trainer import JaxTrainer, TorchTrainer


def shard_to_list(iterator) -> list:
    """Materialize a DataIterator shard into a list of dict rows — a
    valid torch-style dataset (len + indexing) for transformers.Trainer
    (ref: transformers_trainer.py converts ray.data shards to HF
    datasets; list-of-dicts is the minimal equivalent)."""
    rows = []
    for batch in iterator.iter_batches(batch_size=256):
        if isinstance(batch, dict):
            keys = list(batch.keys())
            n = len(batch[keys[0]])
            rows.extend({k: batch[k][i] for k in keys} for i in range(n))
        else:
            rows.extend(batch)
    return rows


class _ReportCallback:
    """transformers.TrainerCallback reporting HF logs through the train
    session (ref: transformers_trainer.py RayTrainReportCallback).
    Duck-typed: Trainer only calls the hooks it finds."""

    def on_log(self, args, state, control, logs=None, **kwargs):
        from ray_tpu.train import session

        if state.is_world_process_zero and logs:
            session.report({"step": state.global_step,
                            **{k: v for k, v in logs.items()
                               if isinstance(v, (int, float))}})


class TransformersTrainer(TorchTrainer):
    """ref: train/huggingface/transformers_trainer.py —
    `trainer_init_per_worker(train_shard, eval_shard, **config)` returns
    a transformers.Trainer; every rank builds one and .train()s inside
    the live gloo group, so HF's accelerate-backed engine does the DDP."""

    def __init__(self, trainer_init_per_worker: Callable,
                 *, trainer_init_config: Optional[dict] = None,
                 **kwargs):
        init_fn = trainer_init_per_worker

        def loop(config):
            import transformers  # noqa: F401  (fail fast if absent)

            from ray_tpu.train import session

            train_shard = eval_shard = None
            try:
                train_shard = session.get_dataset_shard("train")
            except Exception:
                pass
            try:
                eval_shard = session.get_dataset_shard("evaluation")
            except Exception:
                pass
            trainer = init_fn(train_shard, eval_shard, **config)
            cb = _ReportCallback()
            try:
                from transformers import TrainerCallback

                # real subclass keeps newer transformers' isinstance
                # checks happy
                cb = type("_RayReport", (TrainerCallback,),
                          {"on_log": _ReportCallback.on_log})()
            except Exception:
                pass
            trainer.add_callback(cb)
            result = trainer.train()
            if result is not None and getattr(result, "metrics", None):
                session.report({k: v for k, v in result.metrics.items()
                                if isinstance(v, (int, float))})

        super().__init__(loop, train_loop_config=trainer_init_config or {},
                         **kwargs)


class AccelerateBackend(TorchBackend):
    """TorchBackend + the env contract `accelerate.Accelerator()` reads
    (RANK/WORLD_SIZE/MASTER_*, CPU mode) so the user loop's Accelerator
    adopts the group instead of believing it is single-process
    (ref: accelerate_trainer.py's env plumbing)."""

    def on_worker_setup(self, rank, world_size, coordinator):
        host, port = coordinator.rsplit(":", 1)
        os.environ.update({
            "RANK": str(rank), "WORLD_SIZE": str(world_size),
            "LOCAL_RANK": "0", "MASTER_ADDR": host, "MASTER_PORT": port,
            "ACCELERATE_USE_CPU": "true",
        })
        super().on_worker_setup(rank, world_size, coordinator)


class AccelerateTrainer(JaxTrainer):
    """ref: train/huggingface/accelerate/accelerate_trainer.py:89 — the
    user's train_loop_per_worker builds `accelerate.Accelerator()` and
    prepares model/optimizer/dataloaders; the backend guarantees the
    distributed env is visible before the loop starts."""

    backend_cls = AccelerateBackend
