"""Trainer configuration dataclasses.

Reference: python/ray/air/config.py (ScalingConfig / RunConfig /
FailureConfig / CheckpointConfig). TPU-specific: ScalingConfig speaks in
hosts x chips and carries the mesh/rules preset, because on TPU "number of
workers" is the host count of a slice, not an arbitrary GPU count.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from ray_tpu.parallel.mesh import MeshSpec


@dataclass
class ScalingConfig:
    num_workers: int = 1                  # host processes (1 per TPU VM host)
    chips_per_worker: Optional[int] = None  # None => all local chips
    mesh: Optional[MeshSpec] = None       # None => MeshSpec(dp=-1)
    rules: str = "fsdp"                   # ShardingRules preset name
    use_tpu: bool = True
    resources_per_worker: Dict[str, float] = field(default_factory=dict)

    def worker_resources(self) -> Dict[str, float]:
        r = dict(self.resources_per_worker)
        r.setdefault("CPU", 1.0)
        if self.use_tpu and self.chips_per_worker:
            # resolve the logical chip resource name the same way task
            # submission does (cfg.chip_resource; "TPU" by default)
            from ray_tpu.core import runtime as _rt
            from ray_tpu.core.config import GLOBAL_CONFIG

            rt = _rt.current_runtime_or_none()
            cfg = rt.cfg if rt is not None else GLOBAL_CONFIG
            r[cfg.chip_resource] = float(self.chips_per_worker)
        return r


@dataclass
class FailureConfig:
    max_failures: int = 0                 # group restarts from last checkpoint
    # Hang watchdog (SURVEY §7 hard parts: "a single hung chip stalls a
    # whole pjit program; need watchdogs + slice restart"): if no worker
    # reports progress for this many seconds mid-run, the group is killed
    # and restarted from the last checkpoint like a crash. None = off.
    # Only the gap BETWEEN reports is policed: before an attempt's first
    # report the worker is still cold-starting (process spawn, jax
    # import, first-step compile), covered by startup_grace_s below.
    hang_timeout_s: Optional[float] = None
    # Grace window for an attempt's FIRST progress report. Restarted
    # attempts pay the full cold start again, so without this a
    # hang_timeout_s tuned to steady-state step time re-trips the
    # watchdog during every restart's spawn + jax import + compile.
    # The effective first-report deadline is max(hang, grace).
    startup_grace_s: float = 120.0


@dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None
    checkpoint_frequency: int = 0         # trainer-side auto checkpointing off


@dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None    # default: ~/ray_tpu_results
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    # tune LoggerCallback instances (ref: air RunConfig.callbacks →
    # tune/logger/*; see ray_tpu/tune/loggers.py)
    callbacks: Optional[list] = None
