"""Trainer configuration dataclasses.

Reference: python/ray/air/config.py (ScalingConfig / RunConfig /
FailureConfig / CheckpointConfig). TPU-specific: ScalingConfig speaks in
hosts x chips and carries the mesh/rules preset, because on TPU "number of
workers" is the host count of a slice, not an arbitrary GPU count.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from ray_tpu.parallel.mesh import MeshSpec


@dataclass
class ElasticConfig:
    """Self-healing gang policy consumed by `ray_tpu.train.elastic`.

    Setting `ScalingConfig.elastic` turns `fit()` into a remediation
    loop: suspect ranks (death, CollectiveError suspects, health-plane
    stalls, report-cadence stragglers) are quarantined, the gang shrinks
    or re-fills between `min_workers` and the target, collective groups
    re-form on a generation-suffixed name, the default mesh rebinds, and
    training resumes from the latest checkpoint — no operator in the
    loop. The reverse direction reports unmet gang demand to the GCS (the
    same `report_load` shape the serve controller uses) and grows the
    gang back toward the target when capacity appears."""

    # Smallest world size the run may continue at. Below this the run
    # fails instead of limping.
    min_workers: int = 1
    # Ceiling for the grow path; None = ScalingConfig.num_workers (the
    # target). Growing past the original request needs an explicit cap.
    max_workers: Optional[int] = None
    # Refill quarantined/dead slots back toward the target on the next
    # rebuild (False = run shrunken until capacity-probe growth, if any).
    refill: bool = True
    # Probe for capacity and grow a shrunken gang back toward the target
    # mid-run (requires a checkpoint to restart from, or zero progress).
    grow: bool = True
    # Demote ranks whose report cadence lags the gang (see the
    # elastic_straggler_* Config knobs); False = only deaths/stalls/
    # collective suspects trigger remediation.
    quarantine_stragglers: bool = True
    # Give up after this many remediations (death spiral guard).
    max_remediations: int = 8
    # Per-rank report-progress beacon deadline override (None = the
    # session default, 600s). Health-plane stall detection for the gang
    # fires after this long without a session.report() on some rank.
    step_deadline_s: Optional[float] = None
    # Bring up a gang-wide host collective group each generation and
    # expose its (generation-suffixed) name via
    # session.get_collective_group(); re-formed on every rebuild.
    host_collective: bool = True
    # Per-run overrides of the cluster elastic_* Config knobs (None =
    # the cluster default): monitor beat, health-plane poll cadence,
    # straggler demotion threshold/warmup, grow probe cadence, and the
    # placement-group wait for elastic reservations.
    poll_interval_s: Optional[float] = None
    health_poll_interval_s: Optional[float] = None
    straggler_k: Optional[float] = None
    straggler_min_reports: Optional[int] = None
    grow_check_interval_s: Optional[float] = None
    reserve_timeout_s: Optional[float] = None
    drain_grace_s: Optional[float] = None


@dataclass
class ScalingConfig:
    num_workers: int = 1                  # host processes (1 per TPU VM host)
    chips_per_worker: Optional[int] = None  # None => all local chips
    mesh: Optional[MeshSpec] = None       # None => MeshSpec(dp=-1)
    rules: str = "fsdp"                   # ShardingRules preset name
    use_tpu: bool = True
    resources_per_worker: Dict[str, float] = field(default_factory=dict)
    # Self-healing gang policy; None = legacy fixed-size semantics (any
    # failure restarts the whole gang via FailureConfig.max_failures).
    elastic: Optional[ElasticConfig] = None

    def worker_resources(self) -> Dict[str, float]:
        r = dict(self.resources_per_worker)
        r.setdefault("CPU", 1.0)
        if self.use_tpu and self.chips_per_worker:
            # resolve the logical chip resource name the same way task
            # submission does (cfg.chip_resource; "TPU" by default)
            from ray_tpu.core import runtime as _rt
            from ray_tpu.core.config import GLOBAL_CONFIG

            rt = _rt.current_runtime_or_none()
            cfg = rt.cfg if rt is not None else GLOBAL_CONFIG
            r[cfg.chip_resource] = float(self.chips_per_worker)
        return r


@dataclass
class FailureConfig:
    max_failures: int = 0                 # group restarts from last checkpoint
    # Hang watchdog (SURVEY §7 hard parts: "a single hung chip stalls a
    # whole pjit program; need watchdogs + slice restart"): if no worker
    # reports progress for this many seconds mid-run, the group is killed
    # and restarted from the last checkpoint like a crash. None = off.
    # Only the gap BETWEEN reports is policed: before an attempt's first
    # report the worker is still cold-starting (process spawn, jax
    # import, first-step compile), covered by startup_grace_s below.
    hang_timeout_s: Optional[float] = None
    # Grace window for an attempt's FIRST progress report. Restarted
    # attempts pay the full cold start again, so without this a
    # hang_timeout_s tuned to steady-state step time re-trips the
    # watchdog during every restart's spawn + jax import + compile.
    # The effective first-report deadline is max(hang, grace).
    startup_grace_s: float = 120.0


@dataclass
class CheckpointConfig:
    num_to_keep: Optional[int] = None
    checkpoint_frequency: int = 0         # trainer-side auto checkpointing off


@dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None    # default: ~/ray_tpu_results
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    # tune LoggerCallback instances (ref: air RunConfig.callbacks →
    # tune/logger/*; see ray_tpu/tune/loggers.py)
    callbacks: Optional[list] = None
