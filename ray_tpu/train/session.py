"""Per-worker training session.

Reference: python/ray/train/_internal/session.py:84 (_TrainSession;
report:429, get_checkpoint:639, get_dataset_shard:901) and the air session
facade (air/session.py). One module-level context per worker process, set up
by the TrainWorker actor before the user loop runs.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager


class TrainContext:
    def __init__(self, *, world_rank: int, world_size: int, config: dict,
                 run_dir: str, scaling, checkpoint: Optional[Checkpoint],
                 datasets: Optional[Dict[str, Any]] = None,
                 num_to_keep: Optional[int] = None):
        self.world_rank = world_rank
        self.world_size = world_size
        self.config = config
        self.run_dir = run_dir
        self.scaling = scaling
        self.start_checkpoint = checkpoint
        self.datasets = datasets or {}
        self.reports: List[dict] = []
        self.report_lock = threading.Lock()
        self.latest_checkpoint: Optional[Checkpoint] = checkpoint
        self.ckpt_mgr = (CheckpointManager(run_dir, num_to_keep)
                         if world_rank == 0 else None)
        self.finished = False
        self._mesh = None


_ctx: Optional[TrainContext] = None


def _set_context(ctx: Optional[TrainContext]):
    global _ctx
    _ctx = ctx


def get_context() -> TrainContext:
    if _ctx is None:
        raise RuntimeError("not inside a ray_tpu.train worker")
    return _ctx


def world_rank() -> int:
    return get_context().world_rank


def world_size() -> int:
    return get_context().world_size


def get_config() -> dict:
    return get_context().config


def report(metrics: Dict[str, Any], *, state: Any = None) -> None:
    """Report metrics (streamed to the trainer) and optionally checkpoint a
    jax pytree `state` (rank 0 writes; ref: session.report:429)."""
    ctx = get_context()
    entry = dict(metrics)
    entry["_ts"] = time.time()
    entry["_rank"] = ctx.world_rank
    ckpt_path = None
    if state is not None and ctx.ckpt_mgr is not None:
        path = ctx.ckpt_mgr.new_dir()
        ck = Checkpoint.from_state(state, path)
        ctx.ckpt_mgr.register(path)
        ctx.latest_checkpoint = ck
        ckpt_path = ck.path
    if ckpt_path:
        entry["_checkpoint"] = ckpt_path
    with ctx.report_lock:
        ctx.reports.append(entry)


def get_checkpoint() -> Optional[Checkpoint]:
    """The checkpoint to resume from (ref: session.get_checkpoint:639)."""
    return get_context().start_checkpoint


def get_dataset_shard(name: str = "train"):
    """This worker's split of a dataset passed to the trainer
    (ref: session.get_dataset_shard:901 → StreamSplitDataIterator)."""
    ctx = get_context()
    if name not in ctx.datasets:
        raise KeyError(f"no dataset named {name!r} passed to the trainer")
    return ctx.datasets[name]


def get_mesh():
    """The worker's device mesh per ScalingConfig (cached)."""
    ctx = get_context()
    if ctx._mesh is None:
        from ray_tpu.parallel.mesh import MeshSpec, build_mesh

        spec = ctx.scaling.mesh or MeshSpec(dp=-1)
        ctx._mesh = build_mesh(spec)
    return ctx._mesh


def get_rules():
    from ray_tpu.parallel.sharding import ShardingRules

    return getattr(ShardingRules, get_context().scaling.rules)()
