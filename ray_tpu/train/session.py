"""Per-worker training session.

Reference: python/ray/train/_internal/session.py:84 (_TrainSession;
report:429, get_checkpoint:639, get_dataset_shard:901) and the air session
facade (air/session.py). One module-level context per worker process, set up
by the TrainWorker actor before the user loop runs.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.observability import health as _health
from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager

# Step-loop progress beacon deadline: generous — "step" here means
# report() cadence, and big-model steps plus a collective checkpoint
# save can legitimately take minutes.
_STEP_DEADLINE_S = 600.0


class TrainContext:
    def __init__(self, *, world_rank: int, world_size: int, config: dict,
                 run_dir: str, scaling, checkpoint: Optional[Checkpoint],
                 datasets: Optional[Dict[str, Any]] = None,
                 num_to_keep: Optional[int] = None,
                 elastic_meta: Optional[dict] = None):
        self.world_rank = world_rank
        self.world_size = world_size
        self.config = config
        self.run_dir = run_dir
        self.scaling = scaling
        self.start_checkpoint = checkpoint
        self.datasets = datasets or {}
        # Elastic gang metadata (ray_tpu/train/elastic.py): run tag for
        # health-event attribution, the generation-suffixed host
        # collective group name, and the per-rank report beacon deadline.
        self.elastic_meta = elastic_meta or {}
        self.reports: List[dict] = []
        self.report_lock = threading.Lock()
        self.latest_checkpoint: Optional[Checkpoint] = checkpoint
        # Every rank gets a manager over the same run_dir so all ranks
        # resolve the same checkpoint_NNNNNN paths; only rank 0 registers
        # (uploads/evicts). In a multi-host jax runtime the orbax save is
        # collective — every process must enter from_state (each writes its
        # addressable shards), so non-zero ranks need the path too.
        self.ckpt_mgr = CheckpointManager(run_dir, num_to_keep)
        self.finished = False
        self._mesh = None


_ctx: Optional[TrainContext] = None


def _step_deadline(ctx: TrainContext) -> float:
    dl = ctx.elastic_meta.get("step_deadline_s")
    return float(dl) if dl else _STEP_DEADLINE_S


def _set_context(ctx: Optional[TrainContext]):
    global _ctx
    if ctx is None and _ctx is not None:
        _health.drop_beacon(f"train:r{_ctx.world_rank}")
    _ctx = ctx
    if ctx is not None:
        # armed for the whole run: a rank that stops reporting past the
        # deadline (wedged collective, dead peer mid-allreduce) flags as
        # a StallEvent naming the rank. The run tag in the context lets
        # an ElasticCoordinator attribute the event to ITS gang.
        _health.beacon(f"train:r{ctx.world_rank}",
                       _step_deadline(ctx)).arm(
            rank=ctx.world_rank, world=ctx.world_size,
            run=ctx.elastic_meta.get("run_tag", ""))


def get_context() -> TrainContext:
    if _ctx is None:
        raise RuntimeError("not inside a ray_tpu.train worker")
    return _ctx


def world_rank() -> int:
    return get_context().world_rank


def world_size() -> int:
    return get_context().world_size


def get_config() -> dict:
    return get_context().config


def report(metrics: Dict[str, Any], *, state: Any = None) -> None:
    """Report metrics (streamed to the trainer) and optionally checkpoint a
    jax pytree `state` (ref: session.report:429).

    Checkpoint contract (same as the reference's distributed checkpointing:
    every train worker must call `train.report` with a checkpoint): when the
    workers form one multi-host jax runtime, EVERY rank must pass `state` on
    the same reports — the orbax save and its barriers are collective, and a
    rank that skips them hangs the gang. Single-process workers: rank 0's
    state is saved, other ranks' is ignored."""
    ctx = get_context()
    entry = dict(metrics)
    entry["_ts"] = time.time()
    entry["_rank"] = ctx.world_rank
    ckpt_path = None
    if state is not None:
        import jax

        # Collective save: when the workers form one multi-host jax
        # runtime, EVERY process must call from_state (orbax writes each
        # process's addressable shards + a sync barrier). With independent
        # single-process workers (process_count==1), rank 0 saves alone.
        collective = jax.process_count() > 1
        if ctx.world_rank == 0 or collective:
            if collective:
                import numpy as np
                from jax.experimental import multihost_utils

                # all ranks write into rank 0's checkpoint slot — a
                # replacement rank with a fresh staging dir may disagree
                # on the next index
                idx = int(multihost_utils.broadcast_one_to_all(
                    np.int32(ctx.ckpt_mgr._index)))
                path = ctx.ckpt_mgr.new_dir(index=idx)
            else:
                path = ctx.ckpt_mgr.new_dir()
            ck = Checkpoint.from_state(state, path)
            if ctx.world_rank != 0 and collective:
                # mirror this rank's shard files + evict per num_to_keep
                # on this host; no marker, no remote eviction; synchronous
                # so the barrier below really covers the upload
                ctx.ckpt_mgr.register(path, primary=False)
            if collective:
                # the primary's completion marker must land after every
                # rank's shard upload
                multihost_utils.sync_global_devices("ray_tpu_ckpt_mirror")
            if ctx.world_rank == 0:
                # single-process mode mirrors on a background thread so
                # the train loop isn't stalled for the upload
                ctx.ckpt_mgr.register(path, primary=True,
                                      sync=collective)
                ctx.latest_checkpoint = ck
                ckpt_path = ck.path
                if ctx.ckpt_mgr.uri:
                    import os as _os

                    from ray_tpu.train import storage as _storage

                    entry["_checkpoint_uri"] = _storage.join_uri(
                        ctx.ckpt_mgr.uri, _os.path.basename(path))
    if ckpt_path:
        entry["_checkpoint"] = ckpt_path
    with ctx.report_lock:
        ctx.reports.append(entry)
    _health.beacon(f"train:r{ctx.world_rank}", _step_deadline(ctx)).tick()


def get_checkpoint() -> Optional[Checkpoint]:
    """The checkpoint to resume from (ref: session.get_checkpoint:639)."""
    return get_context().start_checkpoint


def get_dataset_shard(name: str = "train"):
    """This worker's split of a dataset passed to the trainer
    (ref: session.get_dataset_shard:901 → StreamSplitDataIterator)."""
    ctx = get_context()
    if name not in ctx.datasets:
        raise KeyError(f"no dataset named {name!r} passed to the trainer")
    return ctx.datasets[name]


def get_mesh():
    """The worker's device mesh per ScalingConfig (cached).

    Also binds the mesh (+ the scaling rules) as the process-default for
    `ray_tpu.parallel.presets.sharded_jit` — a function decorated with
    in/out specs resolves its mesh here at call time, so an elastic
    rebuild re-meshes every decorated step by re-running setup, with no
    per-call-site rewiring."""
    ctx = get_context()
    if ctx._mesh is None:
        from ray_tpu.parallel import presets
        from ray_tpu.parallel.mesh import MeshSpec, build_mesh

        spec = ctx.scaling.mesh or MeshSpec(dp=-1)
        ctx._mesh = build_mesh(spec)
        presets.set_default_mesh(ctx._mesh, rules=get_rules(), spec=spec)
    return ctx._mesh


def get_collective_group() -> Optional[str]:
    """The gang-wide host collective group's CURRENT name, or None.

    Elastic gangs re-form the group under a generation-suffixed name on
    every rebuild (membership is static per incarnation); user loops
    must route collective.* calls through this accessor rather than a
    hard-coded name so they survive a remediation."""
    return get_context().elastic_meta.get("collective_group")


def get_rules():
    from ray_tpu.parallel.sharding import ShardingRules

    return getattr(ShardingRules, get_context().scaling.rules)()
