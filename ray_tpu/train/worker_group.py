"""WorkerGroup: a gang of training actors under one placement group.

Reference: python/ray/train/_internal/worker_group.py:100 and
backend_executor.py:45 (_create_placement_group:164, rank assignment:272).
The backend hook replaces NCCL process groups with jax.distributed + mesh
setup (JaxBackend) — on a TPU slice, worker i is host i of the slice, and
the in-step collectives need no framework plumbing at all.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train.session import TrainContext, _set_context
from ray_tpu.util import (PlacementGroupSchedulingStrategy, placement_group,
                          remove_placement_group)


@ray_tpu.remote
class TrainWorker:
    """Hosts the user's train loop; polled by the trainer for reports.

    max_concurrency=2: one thread runs the loop, the other serves polls
    (the reference streams TrainingResults back through the backend executor
    queue, backend_executor.py:457)."""

    def __init__(self, rank: int, world_size: int):
        self.rank = rank
        self.world_size = world_size
        self.ctx: Optional[TrainContext] = None
        self.error: Optional[str] = None
        self.result: Any = None

    def setup(self, config: dict, run_dir: str, scaling, checkpoint,
              datasets, coordinator: Optional[str] = None,
              num_to_keep=None) -> bool:
        # Multi-host: bring up the jax distributed runtime so all hosts of
        # the slice form one XLA computation domain (replaces
        # _setup_torch_process_group, train/torch/config.py:69).
        if coordinator and self.world_size > 1:
            import jax

            jax.distributed.initialize(coordinator_address=coordinator,
                                       num_processes=self.world_size,
                                       process_id=self.rank)
        self.ctx = TrainContext(
            world_rank=self.rank, world_size=self.world_size, config=config,
            run_dir=run_dir, scaling=scaling, checkpoint=checkpoint,
            datasets=datasets, num_to_keep=num_to_keep)
        _set_context(self.ctx)
        return True

    def run(self, loop_fn: Callable, config: dict) -> Any:
        try:
            self.result = loop_fn(config) if _accepts_arg(loop_fn) else loop_fn()
            return self.result
        except BaseException as e:
            import traceback

            self.error = traceback.format_exc()
            raise
        finally:
            if self.ctx is not None:
                self.ctx.finished = True

    def poll(self, after: int) -> dict:
        ctx = self.ctx
        reports: List[dict] = []
        if ctx is not None:
            with ctx.report_lock:
                reports = ctx.reports[after:]
        return {"reports": reports, "finished": ctx.finished if ctx else False,
                "error": self.error,
                "latest_checkpoint": (ctx.latest_checkpoint.path
                                      if ctx and ctx.latest_checkpoint else None)}

    def host_info(self) -> dict:
        import socket

        return {"hostname": socket.gethostname(), "pid": os.getpid(),
                "rank": self.rank}


def _accepts_arg(fn) -> bool:
    import inspect

    try:
        sig = inspect.signature(fn)
        return len(sig.parameters) >= 1
    except (TypeError, ValueError):
        return False


class WorkerGroup:
    def __init__(self, num_workers: int, resources_per_worker: Dict[str, float],
                 placement_strategy: str = "PACK"):
        self.num_workers = num_workers
        self.resources = resources_per_worker
        bundles = [dict(resources_per_worker) for _ in range(num_workers)]
        self.pg = placement_group(bundles, strategy=placement_strategy)
        if not self.pg.ready(timeout=60):
            remove_placement_group(self.pg)
            raise ray_tpu.exceptions.PlacementGroupUnavailableError(
                f"could not reserve {num_workers} x {resources_per_worker}")
        self.workers = []
        for rank in range(num_workers):
            w = TrainWorker.options(
                num_cpus=0,
                resources={k: v for k, v in resources_per_worker.items()},
                max_concurrency=2,
                scheduling_strategy=PlacementGroupSchedulingStrategy(
                    placement_group=self.pg,
                    placement_group_bundle_index=rank),
            ).remote(rank, num_workers)
            self.workers.append(w)

    def broadcast(self, method: str, *args, **kwargs):
        refs = [getattr(w, method).remote(*args, **kwargs)
                for w in self.workers]
        return ray_tpu.get(refs)

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        try:
            remove_placement_group(self.pg)
        except Exception:
            pass
