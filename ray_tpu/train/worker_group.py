"""WorkerGroup: a gang of training actors under one placement group.

Reference: python/ray/train/_internal/worker_group.py:100 and
backend_executor.py:45 (_create_placement_group:164, rank assignment:272).
The backend hook replaces NCCL process groups with jax.distributed + mesh
setup (JaxBackend) — on a TPU slice, worker i is host i of the slice, and
the in-step collectives need no framework plumbing at all.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train.session import TrainContext, _set_context
from ray_tpu.util import (PlacementGroupSchedulingStrategy, placement_group,
                          remove_placement_group)


@ray_tpu.remote
class TrainWorker:
    """Hosts the user's train loop; polled by the trainer for reports.

    max_concurrency=2: one thread runs the loop, the other serves polls
    (the reference streams TrainingResults back through the backend executor
    queue, backend_executor.py:457)."""

    def __init__(self, rank: int, world_size: int):
        self.rank = rank
        self.world_size = world_size
        self.ctx: Optional[TrainContext] = None
        self.error: Optional[str] = None
        self.result: Any = None

    def setup(self, config: dict, run_dir: str, scaling, checkpoint,
              datasets, coordinator: Optional[str] = None,
              num_to_keep=None, backend=None,
              elastic_meta: Optional[dict] = None) -> bool:
        # Collective bootstrap is a pluggable Backend hook
        # (ref: backend_executor.py Backend.on_start); default JaxBackend.
        from ray_tpu.train.backend import JaxBackend

        # release the rendezvous-port reservation right before the
        # backend binds it (see host_info)
        res = getattr(self, "_port_reservation", None)
        if res is not None:
            res.close()
            self._port_reservation = None
        self.backend = backend or JaxBackend()
        self.backend.on_worker_setup(self.rank, self.world_size, coordinator)
        self.ctx = TrainContext(
            world_rank=self.rank, world_size=self.world_size, config=config,
            run_dir=run_dir, scaling=scaling, checkpoint=checkpoint,
            datasets=datasets, num_to_keep=num_to_keep,
            elastic_meta=elastic_meta)
        _set_context(self.ctx)
        return True

    def run(self, loop_fn: Callable, config: dict) -> Any:
        try:
            self.result = loop_fn(config) if _accepts_arg(loop_fn) else loop_fn()
            return self.result
        except BaseException as e:
            import traceback

            self.error = traceback.format_exc()
            raise
        finally:
            if self.ctx is not None:
                self.ctx.finished = True
                if self.ctx.ckpt_mgr is not None:
                    try:  # commit pending background checkpoint mirrors
                        self.ctx.ckpt_mgr.flush()
                    except Exception:
                        pass
            try:
                self.backend.on_worker_shutdown()
            except Exception:
                pass

    def poll(self, after: int) -> dict:
        ctx = self.ctx
        reports: List[dict] = []
        if ctx is not None:
            with ctx.report_lock:
                reports = ctx.reports[after:]
        return {"reports": reports, "finished": ctx.finished if ctx else False,
                "error": self.error,
                "latest_checkpoint": (ctx.latest_checkpoint.path
                                      if ctx and ctx.latest_checkpoint else None)}

    def set_rank(self, rank: int, world_size: int) -> bool:
        """Rank/world refresh after an elastic resize (the next setup()
        or user-loop restart sees the new topology)."""
        self.rank = rank
        self.world_size = world_size
        if self.ctx is not None:
            self.ctx.world_rank = rank
            self.ctx.world_size = world_size
        return True

    def init_host_collective(self, group_name: str = "train",
                             backend: str = "auto",
                             timeout_s: float = 60.0) -> bool:
        """Join the gang-wide host collective group (ray_tpu.collective):
        rank/world come from the gang, so a user loop can immediately
        call collective.allreduce/barrier for host-side exchanges
        (metric reduction, data-pipeline shuffles) without its own
        rendezvous. Device collectives stay inside the jitted step."""
        from ray_tpu import collective as col

        col.init_collective_group(self.world_size, self.rank, group_name,
                                  backend=backend, timeout_s=timeout_s)
        return True

    def destroy_host_collective(self, group_name: str = "train") -> bool:
        from ray_tpu import collective as col

        col.destroy_collective_group(group_name)
        return True

    def host_info(self) -> dict:
        import socket

        # Reserve a rendezvous port and HOLD the socket open until setup()
        # runs in this same process — concurrent trainers (e.g. Tune
        # trials) probing for ports can't be handed this one while the
        # reservation lives, and the close→rebind window is microseconds
        # inside one process instead of a cross-RPC race.
        s = socket.socket()
        s.bind(("", 0))
        port = s.getsockname()[1]
        self._port_reservation = s
        return {"hostname": socket.gethostname(), "pid": os.getpid(),
                "rank": self.rank, "free_port": port}


def _accepts_arg(fn) -> bool:
    import inspect

    try:
        sig = inspect.signature(fn)
        return len(sig.parameters) >= 1
    except (TypeError, ValueError):
        return False


class WorkerGroup:
    def __init__(self, num_workers: int, resources_per_worker: Dict[str, float],
                 placement_strategy: str = "PACK",
                 pg_timeout_s: float = 60.0):
        self.num_workers = num_workers
        self.resources = resources_per_worker
        self.placement_strategy = placement_strategy
        bundles = [dict(resources_per_worker) for _ in range(num_workers)]
        self.pg = placement_group(bundles, strategy=placement_strategy)
        if not self.pg.ready(timeout=pg_timeout_s):
            remove_placement_group(self.pg)
            raise ray_tpu.exceptions.PlacementGroupUnavailableError(
                f"could not reserve {num_workers} x {resources_per_worker}")
        self._extra_pgs: List[Any] = []
        self._worker_pg: Dict[Any, Any] = {}   # worker -> its pg
        # worker index -> (pg, bundle_index); parallel to self.workers so
        # elastic respawn/refill can reuse the exact reservation a dead
        # worker held (ref: BackendExecutor keeps bundle->worker maps)
        self._placements: List[tuple] = []
        # freed reservations a future add_workers may reuse, and
        # quarantined ones it must NOT (suspect rank's slot held hostage
        # so a refill can't land back on the flapping host/process)
        self._free_bundles: List[tuple] = []
        self._quarantined: set = set()          # {(id(pg), bundle_index)}
        self.workers = []
        for rank in range(num_workers):
            self.workers.append(self._spawn(self.pg, rank, rank, num_workers))
            self._placements.append((self.pg, rank))

    def _spawn(self, pg, bundle_index: int, rank: int, world: int):
        w = TrainWorker.options(
            num_cpus=0,
            resources={k: v for k, v in self.resources.items()},
            max_concurrency=2,
            scheduling_strategy=PlacementGroupSchedulingStrategy(
                placement_group=pg,
                placement_group_bundle_index=bundle_index),
        ).remote(rank, world)
        self._worker_pg[w] = pg
        return w

    def broadcast(self, method: str, *args, **kwargs):
        refs = [getattr(w, method).remote(*args, **kwargs)
                for w in self.workers]
        return ray_tpu.get(refs)

    @property
    def quarantined_count(self) -> int:
        """Reserved-but-unusable bundles held by quarantined ranks."""
        return len(self._quarantined)

    def init_host_collective(self, group_name: str = "train",
                             backend: str = "auto",
                             timeout_s: float = 60.0):
        """Bring up a ray_tpu.collective group spanning the gang (one
        rank per worker) for host-side exchanges outside the jitted
        step. Re-run after an elastic resize to rebuild the group on
        the new topology (destroy first — group membership is static)."""
        return self.broadcast("init_host_collective", group_name=group_name,
                              backend=backend, timeout_s=timeout_s)

    def destroy_host_collective(self, group_name: str = "train"):
        # one worker reaps the named helper actors; the rest only drop
        # their local clients (destroy is idempotent across ranks)
        return self.broadcast("destroy_host_collective",
                              group_name=group_name)

    # ---- elasticity (ref: worker_group.py:318 remove_workers /
    #      :333 add_workers; BackendExecutor resizes then re-ranks) ------

    def remove_workers(self, indices: List[int],
                       quarantine: bool = False) -> None:
        """Drop workers by index (dead or drained); ranks are refreshed
        across the survivors. A freed bundle goes back on the reuse list
        unless `quarantine`d — a quarantined slot stays RESERVED but
        unusable, so an elastic refill cannot land a replacement on the
        suspect host/process. A supplemental PG with no live or
        quarantined workers is removed so its bundles return to the
        cluster; bundles of the ORIGINAL PG stay reserved until shutdown
        (placement groups cannot shrink — same contract as the
        reference)."""
        for i in sorted(set(indices), reverse=True):
            w = self.workers.pop(i)
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
            self._worker_pg.pop(w, None)
            pg, bundle = self._placements.pop(i)
            if quarantine:
                self._quarantined.add((id(pg), bundle))
            else:
                self._free_bundles.append((pg, bundle))
        live_pgs = set(map(id, self._worker_pg.values()))
        held_pgs = live_pgs | {pid for (pid, _b) in self._quarantined}
        for pg in list(self._extra_pgs):
            if id(pg) not in held_pgs:
                self._extra_pgs.remove(pg)
                self._free_bundles = [
                    (p, b) for (p, b) in self._free_bundles if p is not pg]
                try:
                    remove_placement_group(pg)
                except Exception:
                    pass
        self.num_workers = len(self.workers)
        self._reassign_ranks()

    def respawn_workers(self, indices: Optional[List[int]] = None) -> None:
        """Replace workers with FRESH actor processes in the same
        bundles. A user loop thread cannot be preempted in place, and a
        surviving rank's jax/collective state is bound to the dead
        topology — replacing the process is the only reliable reset, and
        its reservation is already held so no scheduling round-trip."""
        idxs = list(range(len(self.workers))) if indices is None else indices
        world = len(self.workers)
        for i in idxs:
            old = self.workers[i]
            try:
                ray_tpu.kill(old)
            except Exception:
                pass
            self._worker_pg.pop(old, None)
            pg, bundle = self._placements[i]
            self.workers[i] = self._spawn(pg, bundle, i, world)
        self._reassign_ranks()

    def add_workers(self, n: int, timeout: float = 60.0,
                    partial: bool = False) -> int:
        """Grow the gang by n workers, reusing freed (non-quarantined)
        bundles first; the remainder reserves a supplemental placement
        group with the group's original strategy (the original PG's
        bundle count is fixed). With `partial`, a failed supplemental
        reservation adds however many workers the freed bundles covered
        (possibly 0) instead of raising — the elastic refill path, which
        reports the shortfall as gang demand and retries later. Returns
        the number of workers actually added."""
        placements: List[tuple] = []
        while self._free_bundles and len(placements) < n:
            placements.append(self._free_bundles.pop())
        rest = n - len(placements)
        pg = None
        if rest > 0:
            bundles = [dict(self.resources) for _ in range(rest)]
            pg = placement_group(bundles, strategy=self.placement_strategy)
            if not pg.ready(timeout=timeout):
                try:
                    remove_placement_group(pg)
                except Exception:
                    pass
                if not partial:
                    self._free_bundles.extend(placements)
                    raise ray_tpu.exceptions.PlacementGroupUnavailableError(
                        f"could not reserve {rest} x {self.resources} to "
                        "grow the worker group")
                pg = None
            else:
                self._extra_pgs.append(pg)
                placements.extend((pg, i) for i in range(rest))
        base = len(self.workers)
        world = base + len(placements)
        for i, (p, b) in enumerate(placements):
            self.workers.append(self._spawn(p, b, base + i, world))
            self._placements.append((p, b))
        self.num_workers = len(self.workers)
        self._reassign_ranks()
        return len(placements)

    def _reassign_ranks(self):
        n = len(self.workers)
        ray_tpu.get([w.set_rank.remote(rank, n)
                     for rank, w in enumerate(self.workers)])

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        for pg in ([self.pg] + self._extra_pgs):
            try:
                remove_placement_group(pg)
            except Exception:
                pass
