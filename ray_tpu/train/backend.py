"""Pluggable collective backends for the WorkerGroup.

Reference: python/ray/train/_internal/backend_executor.py (Backend's
on_start/on_training_start hooks run per framework) and the per-framework
configs: torch (train/torch/config.py:29,69 — init_process_group with a
rank-0 TCP rendezvous), tensorflow (TF_CONFIG), horovod (Gloo rendezvous).

TPU-native inversion: the primary backend is JAX, where collectives live
INSIDE the jitted program (XLA over ICI) and the backend's only job is
bootstrapping jax.distributed across hosts. The TorchBackend exists for
reference-parity workloads (CPU gloo here; a torch/XLA variant would slot
in the same hook) so torch users migrating from the reference keep their
DDP train loops unchanged.
"""

from __future__ import annotations

from typing import Optional


class Backend:
    """Worker-side collective bootstrap hooks. Instances are pickled to
    workers, so keep them stateless/config-only."""

    #: backends that need a rendezvous address even for world_size == 1
    needs_coordinator: bool = False

    def on_worker_setup(self, rank: int, world_size: int,
                        coordinator: Optional[str]) -> None:
        """Runs inside every worker before the train loop."""

    def on_worker_shutdown(self) -> None:
        """Runs inside every worker after the loop (best-effort)."""


class JaxBackend(Backend):
    """Bring up the jax distributed runtime so all hosts of the slice form
    one XLA computation domain (replaces _setup_torch_process_group,
    train/torch/config.py:69 — but collectives themselves come from the
    compiled program, not a process group)."""

    def on_worker_setup(self, rank, world_size, coordinator):
        if coordinator and world_size > 1:
            import jax

            jax.distributed.initialize(coordinator_address=coordinator,
                                       num_processes=world_size,
                                       process_id=rank)


class TensorflowBackend(Backend):
    """TF_CONFIG rendezvous for tf.distribute.MultiWorkerMirroredStrategy
    (ref: train/tensorflow/config.py:21,40 _setup_tensorflow_environment):
    the trainer collects EVERY worker's host:port (TF needs the full
    cluster spec, not just a coordinator) and each worker exports
    TF_CONFIG before tensorflow builds its cluster resolver. The user
    loop constructs MultiWorkerMirroredStrategy itself, exactly like the
    reference's TensorflowTrainer loops."""

    needs_coordinator = True
    #: trainer fills worker_addresses (one host:port per rank) before
    #: pickling this backend out to the workers
    needs_worker_addresses = True

    def __init__(self):
        self.worker_addresses = None

    def on_worker_setup(self, rank, world_size, coordinator):
        import json
        import os

        addrs = self.worker_addresses
        if addrs is None:
            if world_size > 1:
                # a one-entry cluster spec with task index >= 1 would
                # make MWMS hang/raise cryptically — fail loudly instead
                raise RuntimeError(
                    "TensorflowBackend.worker_addresses not populated; "
                    "the trainer must gather one host:port per rank "
                    "before worker setup")
            addrs = [coordinator] if coordinator else []
        os.environ["TF_CONFIG"] = json.dumps({
            "cluster": {"worker": addrs},
            "task": {"type": "worker", "index": rank}})

    def on_worker_shutdown(self):
        import os

        os.environ.pop("TF_CONFIG", None)


class TorchBackend(Backend):
    """torch.distributed gloo process group (ref: train/torch/config.py:69
    _setup_torch_process_group; nccl is GPU-only — on this stack the
    device path is JAX/XLA, torch runs host-side)."""

    needs_coordinator = True

    def __init__(self, backend: str = "gloo", timeout_s: float = 120.0):
        self.backend = backend
        self.timeout_s = timeout_s

    def on_worker_setup(self, rank, world_size, coordinator):
        import datetime

        import torch.distributed as dist

        if dist.is_initialized():
            return
        dist.init_process_group(
            backend=self.backend,
            init_method=f"tcp://{coordinator}",
            rank=rank, world_size=world_size,
            timeout=datetime.timedelta(seconds=self.timeout_s))

    def on_worker_shutdown(self):
        import torch.distributed as dist

        if dist.is_initialized():
            dist.destroy_process_group()


def prepare_model(model):
    """Wrap an nn.Module in DDP when a >1-rank group is live
    (ref: ray.train.torch.prepare_model)."""
    import torch.distributed as dist

    if dist.is_available() and dist.is_initialized() \
            and dist.get_world_size() > 1:
        from torch.nn.parallel import DistributedDataParallel

        return DistributedDataParallel(model)
    return model


def prepare_data_loader(loader):
    """Shard a DataLoader across ranks via DistributedSampler, preserving
    the original loader's shuffle semantics and worker/memory options
    (ref: ray.train.torch.prepare_data_loader, which inspects the existing
    sampler to decide shuffling)."""
    import torch.distributed as dist

    if not (dist.is_available() and dist.is_initialized()
            and dist.get_world_size() > 1):
        return loader
    if loader.batch_size is None:
        raise ValueError(
            "prepare_data_loader supports batch_size-based DataLoaders; "
            "pass your custom batch_sampler a DistributedSampler yourself")
    from torch.utils.data import DataLoader, SequentialSampler
    from torch.utils.data.distributed import DistributedSampler

    shuffle = not isinstance(loader.sampler, SequentialSampler)
    return DataLoader(loader.dataset, batch_size=loader.batch_size,
                      sampler=DistributedSampler(loader.dataset,
                                                 shuffle=shuffle),
                      num_workers=loader.num_workers,
                      pin_memory=loader.pin_memory,
                      collate_fn=loader.collate_fn,
                      drop_last=loader.drop_last,
                      timeout=loader.timeout,
                      worker_init_fn=loader.worker_init_fn)
