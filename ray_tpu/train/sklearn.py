"""SklearnTrainer + gated GBDT trainers.

Reference: python/ray/train/sklearn/sklearn_trainer.py (fit an estimator
in a remote actor with optional cross-validation, parallelized via
joblib) and gbdt_trainer.py (XGBoostTrainer/LightGBMTrainer over
xgboost_ray/lightgbm_ray). CPU-estimator training is a single remote
actor here — the TPU adds nothing to sklearn fits, but the orchestration
surface (fit off-driver, CV fan-out over the cluster, checkpoint to the
run dir) matches the reference. xgboost/lightgbm are not in the TPU
image; their trainers keep the reference API and raise an actionable
ImportError at construction.
"""

from __future__ import annotations

import os
import pickle
import time
from typing import Any, Dict, Optional

import numpy as np

import ray_tpu
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import RunConfig
from ray_tpu.train.trainer import Result

MODEL_FILE = "model.pkl"


@ray_tpu.remote
def _fit_estimator(est_blob: bytes, X, y, fit_params: dict):
    import cloudpickle

    est = cloudpickle.loads(est_blob)
    t0 = time.time()
    est.fit(X, y, **(fit_params or {}))
    out: Dict[str, Any] = {"fit_time": time.time() - t0}
    if hasattr(est, "score"):
        out["train_score"] = float(est.score(X, y))
    return pickle.dumps(est), out


@ray_tpu.remote
def _cv_fold(est_blob: bytes, X, y, train_idx, test_idx, fit_params: dict):
    import cloudpickle

    est = cloudpickle.loads(est_blob)
    est.fit(X[train_idx], y[train_idx], **(fit_params or {}))
    return float(est.score(X[test_idx], y[test_idx]))


class SklearnTrainer:
    """ref: sklearn_trainer.py — estimator + datasets in, fitted model +
    metrics + checkpoint out; cv folds fan out as remote tasks (the
    reference parallelizes CV through joblib-on-ray; here each fold IS a
    task)."""

    def __init__(self, *, estimator: Any,
                 datasets: Dict[str, Any],
                 label_column: str = None,
                 cv: Optional[int] = None,
                 fit_params: Optional[dict] = None,
                 run_config: Optional[RunConfig] = None):
        self.estimator = estimator
        self.datasets = datasets
        self.label_column = label_column
        self.cv = cv
        self.fit_params = fit_params or {}
        self.run_config = run_config or RunConfig()

    def _xy(self, ds):
        """Accept a ray_tpu.data.Dataset, a pandas frame, or (X, y)."""
        from ray_tpu.data.dataset import Dataset

        if isinstance(ds, tuple):
            return np.asarray(ds[0]), np.asarray(ds[1])
        if isinstance(ds, Dataset):
            ds = ds.to_pandas()
        if self.label_column is None:
            raise ValueError("label_column is required for tabular input")
        y = ds[self.label_column].to_numpy()
        X = ds.drop(columns=[self.label_column]).to_numpy()
        return X, y

    def fit(self) -> Result:
        import cloudpickle

        X, y = self._xy(self.datasets["train"])
        blob = cloudpickle.dumps(self.estimator)

        model_blob, metrics = ray_tpu.get(
            _fit_estimator.remote(blob, X, y, self.fit_params))

        if self.cv:
            from sklearn.model_selection import KFold

            folds = KFold(n_splits=self.cv, shuffle=True, random_state=0)
            refs = [_cv_fold.remote(blob, X, y, tr, te, self.fit_params)
                    for tr, te in folds.split(X)]
            scores = ray_tpu.get(refs)
            metrics["cv_scores"] = scores
            metrics["cv_score_mean"] = float(np.mean(scores))
            metrics["cv_score_std"] = float(np.std(scores))

        if "valid" in self.datasets:
            est = pickle.loads(model_blob)
            Xv, yv = self._xy(self.datasets["valid"])
            metrics["valid_score"] = float(est.score(Xv, yv))

        base = self.run_config.storage_path or os.path.expanduser(
            "~/ray_tpu_results")
        run_dir = os.path.join(
            base, self.run_config.name or f"sklearn_{int(time.time())}")
        os.makedirs(run_dir, exist_ok=True)
        with open(os.path.join(run_dir, MODEL_FILE), "wb") as f:
            f.write(model_blob)
        ckpt = Checkpoint.from_directory(run_dir)
        return Result(metrics=metrics, metrics_history=[metrics],
                      checkpoint=ckpt)

    @staticmethod
    def get_model(checkpoint: Checkpoint):
        """Unpickle the fitted estimator from a fit() checkpoint
        (ref: sklearn_trainer.py get_model)."""
        with open(os.path.join(checkpoint.to_directory(), MODEL_FILE),
                  "rb") as f:
            return pickle.load(f)


class _MissingGBDTTrainer:
    _pkg = ""

    def __init__(self, *a, **kw):
        raise ImportError(
            f"{type(self).__name__} needs the '{self._pkg}' package, which "
            "is not in the TPU image (do not pip install; bake it into the "
            "image). The reference equivalent is train/gbdt_trainer.py.")


class XGBoostTrainer(_MissingGBDTTrainer):
    """ref: train/xgboost/xgboost_trainer.py — surface kept, gated on the
    xgboost package."""
    _pkg = "xgboost"


class LightGBMTrainer(_MissingGBDTTrainer):
    """ref: train/lightgbm/lightgbm_trainer.py — surface kept, gated on
    the lightgbm package."""
    _pkg = "lightgbm"


try:  # pragma: no cover - image has no xgboost today
    import xgboost as _xgb  # noqa: F401

    class XGBoostTrainer(SklearnTrainer):  # type: ignore[no-redef]
        """xgboost.XGBModel is sklearn-compatible; the SklearnTrainer
        orchestration (remote fit, CV fan-out, checkpoint) applies."""
except ImportError:
    pass

try:  # pragma: no cover
    import lightgbm as _lgb  # noqa: F401

    class LightGBMTrainer(SklearnTrainer):  # type: ignore[no-redef]
        pass
except ImportError:
    pass
