"""ray_tpu.train: distributed training on TPU slices.

Reference: python/ray/train/ — BaseTrainer.fit (base_trainer.py:570),
DataParallelTrainer (data_parallel_trainer.py:58), BackendExecutor
(backend_executor.py:45), WorkerGroup (worker_group.py:100), _TrainSession
(session.py:84). The architecture carries over — trainer → placement group →
worker-group of actors → per-worker session — but the collective plane is
inverted (SURVEY.md §5.8): instead of `_setup_torch_process_group` wiring
NCCL (torch/config.py:69), the JaxBackend initializes jax.distributed (multi-
host) and builds the device mesh; all collectives live inside the jitted
step. DP/FSDP/TP/PP/SP/EP arrive via ray_tpu.parallel sharding presets, not
separate trainer classes.

    from ray_tpu.train import JaxTrainer, ScalingConfig, RunConfig
    from ray_tpu.train import session

    def train_loop(config):
        mesh = session.get_mesh()
        ...
        session.report({"loss": ...}, checkpoint=...)

    result = JaxTrainer(train_loop, scaling_config=ScalingConfig(...)).fit()
"""

from ray_tpu.train.backend import (Backend, JaxBackend, TensorflowBackend,
                                   TorchBackend, prepare_data_loader,
                                   prepare_model)
from ray_tpu.train.config import (CheckpointConfig, ElasticConfig,
                                  FailureConfig, RunConfig, ScalingConfig)
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.trainer import (JaxTrainer, Result, TensorflowTrainer,
                                   TorchTrainer)
from ray_tpu.train.predictor import (BatchPredictor, JaxPredictor,
                                     Predictor, TorchPredictor,
                                     TransformersPredictor)
from ray_tpu.train.huggingface import (AccelerateBackend,
                                       AccelerateTrainer,
                                       TransformersTrainer, shard_to_list)
from ray_tpu.train.sklearn import (LightGBMTrainer, SklearnTrainer,
                                   XGBoostTrainer)
from ray_tpu.train import session

__all__ = [
    "JaxTrainer", "TorchTrainer", "TensorflowTrainer", "Result",
    "ScalingConfig", "RunConfig", "ElasticConfig",
    "FailureConfig", "CheckpointConfig", "Checkpoint", "session",
    "Predictor", "JaxPredictor", "BatchPredictor", "TorchPredictor",
    "TransformersPredictor",
    "Backend", "JaxBackend", "TensorflowBackend", "TorchBackend",
    "prepare_model", "prepare_data_loader",
    "SklearnTrainer", "XGBoostTrainer", "LightGBMTrainer",
    "TransformersTrainer", "AccelerateTrainer", "AccelerateBackend",
    "shard_to_list",
]
