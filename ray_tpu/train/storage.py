"""Remote checkpoint/artifact storage over fsspec URIs.

Reference: python/ray/air/_internal/remote_storage.py (get_fs_and_path,
upload_to_uri, download_from_uri, list_at_uri, delete_at_uri over pyarrow
fs). Here the implementation rides fsspec instead of pyarrow.fs — fsspec is
in the image, covers file:// and memory:// natively, and loads gs://"s3://
drivers (gcsfs/s3fs) lazily when installed. memory:// makes the cloud path
testable without cloud credentials.
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple


def is_uri(path: Optional[str]) -> bool:
    """True for scheme://... paths (but plain local paths and Windows
    drive letters are not URIs)."""
    if not path:
        return False
    idx = path.find("://")
    return idx > 1  # at least 2 scheme chars; excludes C:/ style


def get_fs_and_path(uri: str) -> Tuple["object", str]:
    """fsspec filesystem + in-fs path for a URI.

    ref: remote_storage.py get_fs_and_path — same contract, fsspec engine.
    Raises a helpful error when a cloud driver (gcsfs/s3fs/...) is not
    installed in the image.
    """
    import fsspec

    scheme, _, rest = uri.partition("://")
    try:
        fs = fsspec.filesystem(scheme)
    except (ImportError, ValueError) as e:
        raise RuntimeError(
            f"no fsspec driver for {scheme}:// ({e}); install the driver "
            f"(e.g. gcsfs for gs://, s3fs for s3://) or use file:// / "
            f"memory:// / a plain local path") from e
    if scheme == "file":
        return fs, rest if rest.startswith("/") else "/" + rest
    return fs, rest


def upload_to_uri(local_dir: str, uri: str) -> None:
    """Recursively copy a local directory's contents to the URI."""
    fs, path = get_fs_and_path(uri)
    fs.makedirs(path, exist_ok=True)
    # trailing slashes select contents-into-dir semantics in fsspec
    fs.put(local_dir.rstrip("/") + "/", path.rstrip("/") + "/",
           recursive=True)


def download_from_uri(uri: str, local_dir: str) -> str:
    """Recursively copy the URI directory into local_dir; returns local_dir.

    The download lands in a temp sibling and renames into place, so a
    crash mid-download never leaves a half-populated local_dir (which a
    resuming CheckpointManager could mistake for a real checkpoint).
    """
    import shutil

    fs, path = get_fs_and_path(uri)
    local_dir = local_dir.rstrip("/")
    # Temp name starts with "." so a crashed download can never be
    # mistaken for a real checkpoint_NNNNNN dir by a resuming manager.
    parent = os.path.dirname(local_dir) or "."
    tmp = os.path.join(parent,
                       f".dl-{os.path.basename(local_dir)}-{os.getpid()}")
    shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(tmp, exist_ok=True)
    fs.get(path.rstrip("/") + "/", tmp + "/", recursive=True)
    shutil.rmtree(local_dir, ignore_errors=True)
    os.rename(tmp, local_dir)
    return local_dir


def list_at_uri(uri: str) -> List[str]:
    """Immediate child names at the URI (empty when absent)."""
    fs, path = get_fs_and_path(uri)
    if not fs.exists(path):
        return []
    out = []
    for entry in fs.ls(path, detail=False):
        name = entry.rstrip("/").rsplit("/", 1)[-1]
        if name:
            out.append(name)
    return sorted(out)


def exists_at_uri(uri: str) -> bool:
    fs, path = get_fs_and_path(uri)
    return bool(fs.exists(path))


def touch_at_uri(uri: str) -> None:
    """Create an empty file at the URI (commit markers)."""
    fs, path = get_fs_and_path(uri)
    parent = path.rstrip("/").rsplit("/", 1)[0]
    if parent:
        fs.makedirs(parent, exist_ok=True)
    fs.pipe_file(path, b"")


def delete_at_uri(uri: str) -> None:
    fs, path = get_fs_and_path(uri)
    if fs.exists(path):
        fs.rm(path, recursive=True)


def join_uri(uri: str, *parts: str) -> str:
    return uri.rstrip("/") + "/" + "/".join(p.strip("/") for p in parts)


def local_staging_dir(uri: str) -> str:
    """Deterministic local staging directory for a remote URI (so a
    restarted process re-finds its own staging)."""
    import hashlib

    h = hashlib.sha1(uri.encode()).hexdigest()[:12]
    d = os.path.join(os.path.expanduser("~/.cache/ray_tpu/staging"), h)
    os.makedirs(d, exist_ok=True)
    return d
