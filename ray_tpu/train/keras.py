"""Keras session callback.

Reference: python/ray/air/integrations/keras.py — ReportCheckpointCallback:
a tf.keras Callback that forwards epoch/batch logs (and optionally a
checkpoint) through the train session so Keras loops running inside a
WorkerGroup report like any other trainer. tensorflow is in the TPU
image (CPU build), so this is live, not gated.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional


def ReportCheckpointCallback(*, metrics: Optional[List[str]] = None,
                             report_on: str = "epoch_end",
                             checkpoint_dir: Optional[str] = None):
    """Build the callback (factory, so importing this module never pulls
    tensorflow; ref: keras.py ReportCheckpointCallback).

    metrics: subset of Keras logs to report (None = all scalars).
    report_on: "epoch_end" (default) or "batch_end".
    checkpoint_dir: when set, saves model weights per epoch and reports
    the path alongside the metrics (the session persists it)."""
    from tensorflow import keras

    class _Report(keras.callbacks.Callback):
        def _report(self, logs: Optional[Dict]):
            from ray_tpu.train import session

            logs = logs or {}
            picked = {k: float(v) for k, v in logs.items()
                      if (metrics is None or k in metrics)
                      and isinstance(v, (int, float))}
            if not picked:
                return
            ckpt = None
            if checkpoint_dir and report_on == "epoch_end":
                os.makedirs(checkpoint_dir, exist_ok=True)
                path = os.path.join(checkpoint_dir, "model.weights.h5")
                try:
                    self.model.save_weights(path)
                    ckpt = path
                except Exception:
                    pass
            if ckpt:
                picked["_keras_weights"] = ckpt
            session.report(picked)

        def on_epoch_end(self, epoch, logs=None):
            if report_on == "epoch_end":
                self._report({"epoch": epoch, **(logs or {})})

        def on_train_batch_end(self, batch, logs=None):
            if report_on == "batch_end":
                self._report({"batch": batch, **(logs or {})})

    return _Report()


__all__ = ["ReportCheckpointCallback"]
