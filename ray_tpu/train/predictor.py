"""Predictors + BatchPredictor: offline inference over Datasets.

Reference: python/ray/train/predictor.py (Predictor.from_checkpoint /
predict) and python/ray/train/batch_predictor.py — BatchPredictor maps a
predictor over dataset blocks with an actor pool
(data/_internal/execution/operators/actor_pool_map_operator.py). Here the
predictor actors hold a jitted apply function resident on device; blocks
stream through the pool.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Type

import numpy as np

from ray_tpu.train.checkpoint import Checkpoint


class Predictor:
    """Base predictor; subclasses implement predict(batch)->batch."""

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, **kwargs) -> "Predictor":
        raise NotImplementedError

    def predict(self, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        raise NotImplementedError


class JaxPredictor(Predictor):
    """Wraps a jitted apply_fn + params pytree (the TPU-native analog of
    TorchPredictor). apply_fn(params, features) -> outputs."""

    def __init__(self, apply_fn: Callable, params: Any,
                 feature_column: str = "features",
                 output_column: str = "predictions"):
        import jax

        self.params = params
        self.apply = jax.jit(apply_fn)
        self.feature_column = feature_column
        self.output_column = output_column

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, *,
                        apply_fn: Callable, **kwargs) -> "JaxPredictor":
        state = checkpoint.load_state()
        params = state.get("params", state) if isinstance(state, dict) \
            else state
        return cls(apply_fn, params, **kwargs)

    def predict(self, batch):
        import jax

        x = batch[self.feature_column]
        out = jax.device_get(self.apply(self.params, x))
        result = dict(batch)
        result[self.output_column] = np.asarray(out)
        return result


class BatchPredictor:
    """Maps a predictor over a Dataset with a fleet of predictor actors
    (ref: batch_predictor.py:predict — actor pool over blocks)."""

    def __init__(self, checkpoint: Checkpoint,
                 predictor_cls: Type[Predictor], **predictor_kwargs):
        self.checkpoint = checkpoint
        self.predictor_cls = predictor_cls
        self.predictor_kwargs = predictor_kwargs

    def predict(self, dataset, *, num_replicas: int = 1,
                resources_per_replica: Optional[dict] = None,
                batch_size: Optional[int] = None):
        """Returns a new Dataset of prediction blocks."""
        import ray_tpu
        from ray_tpu.data.dataset import Dataset, _transform_block
        from ray_tpu.util.actor_pool import ActorPool

        ckpt = self.checkpoint
        pred_cls = self.predictor_cls
        pred_kwargs = self.predictor_kwargs
        ops = dataset._ops

        @ray_tpu.remote
        class _PredActor:
            def __init__(self):
                self.predictor = pred_cls.from_checkpoint(ckpt,
                                                          **pred_kwargs)

            def predict_block(self, idx, block):
                block = _transform_block(block, ops)
                return idx, self.predictor.predict(block)

        opts = {"resources": resources_per_replica} \
            if resources_per_replica else {}
        actors = [_PredActor.options(**opts).remote()
                  for _ in range(num_replicas)]
        pool = ActorPool(actors)
        for i, ref in enumerate(dataset._block_refs):
            pool.submit(lambda a, v: a.predict_block.remote(*v), (i, ref))
        results = []
        while pool.has_next():
            results.append(pool.get_next())
        for a in actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        # pool yields in completion order; restore block order
        results.sort(key=lambda ib: ib[0])
        return Dataset([ray_tpu.put(b) for _, b in results], [])


class TorchPredictor(Predictor):
    """torch nn.Module predictor (ref: train/torch/torch_predictor.py) —
    the host-side migration path; the device path is JaxPredictor."""

    def __init__(self, model, feature_column: str = "features",
                 output_column: str = "predictions"):
        import torch

        self.model = model.eval()
        self.torch = torch
        self.feature_column = feature_column
        self.output_column = output_column

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, *, model=None,
                        **kwargs) -> "TorchPredictor":
        """`model` is the architecture; the checkpoint supplies a
        state_dict under "model" (or IS the state_dict). Non-array
        entries riding in the dict (epoch counters etc.) are ignored."""
        import torch

        if model is None:
            raise ValueError(
                "TorchPredictor.from_checkpoint needs model= (the "
                "architecture to load the checkpoint's state_dict into)")
        state = checkpoint.load_state()
        if isinstance(state, dict):
            sd = state.get("model", state)
            tensors = {k: torch.as_tensor(np.asarray(v))
                       for k, v in sd.items()
                       if hasattr(v, "shape") or torch.is_tensor(v)}
            if not tensors:
                raise ValueError(
                    f"checkpoint holds no array state for the model "
                    f"(keys: {list(sd)[:8]})")
            model.load_state_dict(tensors)
        return cls(model, **kwargs)

    def predict(self, batch):
        import torch

        x = torch.as_tensor(np.asarray(batch[self.feature_column]))
        with torch.no_grad():
            out = self.model(x)
        result = dict(batch)
        result[self.output_column] = out.numpy()
        return result


class TransformersPredictor(Predictor):
    """HF pipeline predictor (ref:
    train/huggingface/transformers_predictor.py — wraps a transformers
    pipeline over text batches)."""

    def __init__(self, pipeline, feature_column: str = "text",
                 output_column: str = "predictions"):
        self.pipeline = pipeline
        self.feature_column = feature_column
        self.output_column = output_column

    @classmethod
    def from_pretrained(cls, task: str, model: str,
                        **kwargs) -> "TransformersPredictor":
        from transformers import pipeline as hf_pipeline

        return cls(hf_pipeline(task, model=model, device=-1), **kwargs)

    def predict(self, batch):
        texts = [str(t) for t in batch[self.feature_column]]
        out = self.pipeline(texts)
        result = dict(batch)
        result[self.output_column] = np.asarray(
            [o.get("label", o) if isinstance(o, dict) else o
             for o in out], dtype=object)
        return result
