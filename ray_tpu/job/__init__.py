"""ray_tpu.job: job submission.

Reference: dashboard/modules/job/ — JobManager/JobSupervisor actor
(job_manager.py:516,140) + SDK (sdk.py) + CLI. A job is an entrypoint shell
command run under a supervisor actor on the cluster; status/logs are queryable.

    from ray_tpu.job import JobSubmissionClient

    client = JobSubmissionClient("127.0.0.1:6379")
    job_id = client.submit_job(entrypoint="python my_script.py")
    client.get_job_status(job_id)   # PENDING/RUNNING/SUCCEEDED/FAILED
    client.get_job_logs(job_id)
"""

from ray_tpu.job.manager import JobStatus, JobSubmissionClient

__all__ = ["JobSubmissionClient", "JobStatus"]
