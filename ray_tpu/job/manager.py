"""Job supervisor + client.

Reference: dashboard/modules/job/job_manager.py — JobSupervisor (:140) is an
actor that runs the entrypoint as a subprocess, polls it, and exposes
status/logs; JobManager (:516) tracks jobs in GCS KV.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional

import ray_tpu


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"


@ray_tpu.remote
class JobSupervisor:
    """One per job; owns the entrypoint subprocess."""

    def __init__(self, job_id: str, entrypoint: str,
                 runtime_env: Optional[dict] = None,
                 working_dir: Optional[str] = None):
        import subprocess
        import tempfile

        self.job_id = job_id
        self.entrypoint = entrypoint
        self.log_path = os.path.join(
            tempfile.gettempdir(), f"ray_tpu_job_{job_id}.log")
        env = dict(os.environ)
        for k, v in (runtime_env or {}).get("env_vars", {}).items():
            env[k] = str(v)
        self.logf = open(self.log_path, "wb")
        self.proc = subprocess.Popen(
            entrypoint, shell=True, stdout=self.logf, stderr=self.logf,
            cwd=working_dir or os.getcwd(), env=env,
            start_new_session=True)
        self.start_time = time.time()
        self.end_time: Optional[float] = None
        self.stopped = False

    def status(self) -> str:
        rc = self.proc.poll()
        if rc is None:
            return JobStatus.RUNNING
        if self.end_time is None:
            self.end_time = time.time()
            self.logf.flush()
        if self.stopped:
            return JobStatus.STOPPED
        return JobStatus.SUCCEEDED if rc == 0 else JobStatus.FAILED

    def logs(self) -> str:
        self.logf.flush()
        try:
            with open(self.log_path, "rb") as f:
                return f.read().decode(errors="replace")
        except OSError:
            return ""

    def stop(self) -> bool:
        if self.proc.poll() is None:
            self.stopped = True
            import signal

            try:
                os.killpg(os.getpgid(self.proc.pid), signal.SIGTERM)
            except Exception:
                self.proc.terminate()
        return True

    def info(self) -> dict:
        return {"job_id": self.job_id, "entrypoint": self.entrypoint,
                "status": self.status(), "start_time": self.start_time,
                "end_time": self.end_time}


class JobSubmissionClient:
    """ref: python/ray/job_submission SDK surface. Two transports, like
    the reference: an `http://host:port` address targets the dashboard
    head's REST module (job_head.py routes); a `host:port` (or None)
    address connects as a driver and supervises actors directly."""

    def __init__(self, address: Optional[str] = None):
        self._http = None
        if address and address.startswith("http"):
            self._http = address.rstrip("/")
            self._n = 0
            return
        if not ray_tpu.is_initialized():
            ray_tpu.init(address=address)
        self._n = 0

    # ---- http transport (ref: job SDK's _do_request) ----

    def _rest(self, method: str, path: str, body: Optional[dict] = None):
        import urllib.request

        req = urllib.request.Request(
            self._http + path, method=method,
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as resp:
            return json.loads(resp.read().decode())

    def submit_job(self, *, entrypoint: str,
                   runtime_env: Optional[dict] = None,
                   working_dir: Optional[str] = None,
                   submission_id: Optional[str] = None) -> str:
        if self._http:
            return self._rest("POST", "/api/jobs/", {
                "entrypoint": entrypoint, "runtime_env": runtime_env,
                "working_dir": working_dir,
                "submission_id": submission_id})["job_id"]
        job_id = submission_id or f"raytpu-job-{int(time.time())}-{self._n}"
        self._n += 1
        sup = JobSupervisor.options(
            name=f"_job_{job_id}", namespace="job",
            num_cpus=0.1, max_concurrency=4).remote(
            job_id, entrypoint, runtime_env, working_dir)
        # register in GCS KV for listing
        from ray_tpu.core import runtime as rt

        rt.get_runtime().kv_put("jobs", job_id.encode(),
                                json.dumps({"entrypoint": entrypoint,
                                            "submitted": time.time()}).encode())
        return job_id

    def _sup(self, job_id: str):
        return ray_tpu.get_actor(f"_job_{job_id}", namespace="job")

    def get_job_status(self, job_id: str) -> str:
        if self._http:
            return self._rest("GET", f"/api/jobs/{job_id}")["status"]
        return ray_tpu.get(self._sup(job_id).status.remote())

    def get_job_logs(self, job_id: str) -> str:
        if self._http:
            return self._rest("GET", f"/api/jobs/{job_id}/logs")["logs"]
        return ray_tpu.get(self._sup(job_id).logs.remote())

    def get_job_info(self, job_id: str) -> dict:
        if self._http:
            return self._rest("GET", f"/api/jobs/{job_id}")
        return ray_tpu.get(self._sup(job_id).info.remote())

    def stop_job(self, job_id: str) -> bool:
        if self._http:
            return self._rest("POST",
                              f"/api/jobs/{job_id}/stop")["stopped"]
        return ray_tpu.get(self._sup(job_id).stop.remote())

    def list_jobs(self) -> List[str]:
        if self._http:
            return [j if isinstance(j, str) else j.get("job_id")
                    for j in self._rest("GET", "/api/jobs/")]
        from ray_tpu.core import runtime as rt

        return [k.decode() for k in
                rt.get_runtime().gcs_call("kv_keys", ns="jobs")]

    def wait_until_finished(self, job_id: str, timeout: float = 300.0) -> str:
        deadline = time.time() + timeout
        while time.time() < deadline:
            st = self.get_job_status(job_id)
            if st in (JobStatus.SUCCEEDED, JobStatus.FAILED, JobStatus.STOPPED):
                return st
            time.sleep(0.5)
        raise TimeoutError(f"job {job_id} still running after {timeout}s")
