// ray_tpu native shared-memory object store ("hbmstore host tier").
//
// TPU-native re-design of the reference's Plasma store
// (reference: src/ray/object_manager/plasma/store.h:55,
//  object_lifecycle_manager.h, eviction_policy.h, dlmalloc.cc).
//
// Key design departure from Plasma: instead of a store *server* process that
// clients talk to over a unix socket with fd-passing (plasma/client.cc,
// plasma/fling.cc), the entire store state — object index, allocator free
// list, refcounts, LRU clock — lives inside one POSIX shared-memory segment
// guarded by a process-shared robust mutex. Every process on the node maps
// the segment once and then performs create/seal/get/release directly in
// shared memory with no IPC round trip on the hot path. This removes the
// socket hop that dominates Plasma's small-object latency and suits TPU
// hosts, where the store's main job is staging host-side buffers for
// jax.device_put / device_get (the HBM tier itself is tracked per-process by
// the Python runtime, since XLA owns device allocations).
//
// Capabilities kept from the reference:
//   - immutable sealed objects addressed by 20-byte ObjectIDs
//     (src/ray/common/id.h)
//   - pin/unpin refcounts and LRU eviction of unpinned sealed objects
//     (plasma/eviction_policy.h)
//   - create -> write -> seal protocol for zero-copy producers
//   - delete + free-space accounting
//
// Concurrency: a single process-shared PTHREAD_MUTEX_ROBUST mutex. Robustness
// matters: a worker killed mid-operation must not deadlock the node
// (the reference survives this because the store is a separate process; we
// survive it via EOWNERDEAD recovery).
//
// Built as a plain C ABI shared library; Python binds via ctypes
// (ray_tpu/core/object_store.py) and maps the same segment with mmap for
// zero-copy numpy views.

#include <errno.h>
#include <fcntl.h>
#include <pthread.h>
#include <stdint.h>
#include <stdio.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <new>

namespace {

// Layout version is baked into the magic: bump the low byte whenever
// Entry/Header change so a process built against a different layout
// fails attach instead of silently corrupting a live segment.
constexpr uint64_t kMagic = 0x5250555453544F02ULL;  // "RPUTSTO" + v2
constexpr uint32_t kIdLen = 20;

enum ObjState : uint32_t {
  kFree = 0,
  kCreating = 1,
  kSealed = 2,
};

struct Entry {
  uint8_t id[kIdLen];
  uint32_t state;
  uint32_t pending_delete;  // delete arrived while pinned; freed on last release
  uint64_t offset;    // into heap
  uint64_t size;      // user payload size
  uint64_t capacity;  // allocated block size (>= size)
  int64_t refcount;   // pin count; evictable iff 0 and sealed
  uint64_t lru_tick;
  uint64_t create_ts;  // wall seconds at kCreating entry; orphan reaping
};

struct FreeBlock {
  uint64_t offset;
  uint64_t size;
  int32_t next;  // index into free block array, -1 end
  int32_t used;  // slot in use
};

struct Header {
  uint64_t magic;
  pthread_mutex_t mutex;
  uint64_t capacity;      // heap bytes
  uint64_t heap_start;    // offset of heap from segment base
  uint64_t bytes_in_use;  // allocated bytes
  uint64_t tick;          // LRU clock
  uint32_t max_objects;
  uint32_t num_objects;
  uint32_t max_free_blocks;
  int32_t free_head;  // free-list head index
  uint64_t num_evictions;
  uint64_t bytes_evicted;
  // Entry[max_objects], FreeBlock[max_free_blocks] follow, then heap.
};

struct Store {
  Header* hdr;
  uint8_t* base;
  uint64_t mapped_size;
  char name[256];
};

inline Entry* entries(Header* h) {
  return reinterpret_cast<Entry*>(reinterpret_cast<uint8_t*>(h) + sizeof(Header));
}
inline FreeBlock* free_blocks(Header* h) {
  return reinterpret_cast<FreeBlock*>(
      reinterpret_cast<uint8_t*>(entries(h)) + sizeof(Entry) * h->max_objects);
}

uint64_t id_hash(const uint8_t* id) {
  // FNV-1a over the 20-byte id.
  uint64_t v = 1469598103934665603ULL;
  for (uint32_t i = 0; i < kIdLen; i++) {
    v ^= id[i];
    v *= 1099511628211ULL;
  }
  return v;
}

// Open-addressed lookup. Returns entry with matching id, or the first free
// slot if absent (insert position), or nullptr if table full and absent.
Entry* find_slot(Header* h, const uint8_t* id, bool for_insert) {
  Entry* tab = entries(h);
  uint64_t mask = h->max_objects - 1;  // max_objects is a power of two
  uint64_t idx = id_hash(id) & mask;
  Entry* first_free = nullptr;
  for (uint32_t probe = 0; probe < h->max_objects; probe++) {
    Entry* e = &tab[(idx + probe) & mask];
    if (e->state == kFree) {
      if (first_free == nullptr) first_free = e;
      // Freed slots keep capacity != 0 and act as tombstones: they do not
      // terminate a probe chain. A never-used slot (capacity == 0) proves the
      // id is absent, bounding both lookups and inserts.
      if (e->capacity == 0) return for_insert ? first_free : nullptr;
      continue;
    }
    if (memcmp(e->id, id, kIdLen) == 0) return e;
  }
  return for_insert ? first_free : nullptr;
}

int lock(Header* h) {
  int rc = pthread_mutex_lock(&h->mutex);
  if (rc == EOWNERDEAD) {
    // Previous holder died. State may be mid-mutation, but all mutations keep
    // the index structurally valid (single-word state transitions last).
    pthread_mutex_consistent(&h->mutex);
    rc = 0;
  }
  return rc;
}
void unlock(Header* h) { pthread_mutex_unlock(&h->mutex); }

// --- allocator: first-fit free list with coalescing -------------------------

int32_t alloc_free_slot(Header* h) {
  FreeBlock* fb = free_blocks(h);
  for (uint32_t i = 0; i < h->max_free_blocks; i++) {
    if (!fb[i].used) return (int32_t)i;
  }
  return -1;
}

// Allocate `size` bytes from the heap; returns offset or 0 on failure.
// Offset 0 is never a valid allocation because heap offsets returned are
// relative to segment base and the heap starts after the header.
uint64_t heap_alloc(Header* h, uint64_t size) {
  size = (size + 63) & ~63ULL;  // 64-byte alignment for numpy/dlpack friendliness
  if (size == 0) size = 64;
  FreeBlock* fb = free_blocks(h);
  int32_t prev = -1;
  for (int32_t cur = h->free_head; cur != -1; prev = cur, cur = fb[cur].next) {
    if (fb[cur].size >= size) {
      uint64_t off = fb[cur].offset;
      if (fb[cur].size == size) {
        if (prev == -1) h->free_head = fb[cur].next;
        else fb[prev].next = fb[cur].next;
        fb[cur].used = 0;
      } else {
        fb[cur].offset += size;
        fb[cur].size -= size;
      }
      h->bytes_in_use += size;
      return off;
    }
  }
  return 0;
}

void heap_free(Header* h, uint64_t offset, uint64_t size) {
  size = (size + 63) & ~63ULL;
  if (size == 0) size = 64;
  h->bytes_in_use -= size;
  FreeBlock* fb = free_blocks(h);
  // Insert sorted by offset, coalescing with neighbors.
  int32_t prev = -1, cur = h->free_head;
  while (cur != -1 && fb[cur].offset < offset) {
    prev = cur;
    cur = fb[cur].next;
  }
  // Try coalesce with prev.
  if (prev != -1 && fb[prev].offset + fb[prev].size == offset) {
    fb[prev].size += size;
    // Coalesce prev with cur too?
    if (cur != -1 && fb[prev].offset + fb[prev].size == fb[cur].offset) {
      fb[prev].size += fb[cur].size;
      fb[prev].next = fb[cur].next;
      fb[cur].used = 0;
    }
    return;
  }
  // Try coalesce with cur.
  if (cur != -1 && offset + size == fb[cur].offset) {
    fb[cur].offset = offset;
    fb[cur].size += size;
    return;
  }
  int32_t slot = alloc_free_slot(h);
  if (slot == -1) {
    // Free-list exhaustion leaks the block until destroy; extremely unlikely
    // with max_free_blocks == max_objects.
    return;
  }
  fb[slot].used = 1;
  fb[slot].offset = offset;
  fb[slot].size = size;
  fb[slot].next = cur;
  if (prev == -1) h->free_head = slot;
  else fb[prev].next = slot;
}

}  // namespace

extern "C" {

// Create a new store segment. capacity = heap bytes; max_objects rounded up
// to a power of two. Returns opaque handle or null.
void* ts_create(const char* name, uint64_t capacity, uint32_t max_objects) {
  uint32_t mo = 1;
  while (mo < max_objects) mo <<= 1;
  uint64_t meta = sizeof(Header) + (uint64_t)mo * sizeof(Entry) +
                  (uint64_t)mo * sizeof(FreeBlock);
  meta = (meta + 4095) & ~4095ULL;
  uint64_t total = meta + capacity;

  shm_unlink(name);
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, (off_t)total) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* base = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) {
    shm_unlink(name);
    return nullptr;
  }
  Header* h = reinterpret_cast<Header*>(base);
  memset(h, 0, sizeof(Header));
  h->capacity = capacity;
  h->heap_start = meta;
  h->max_objects = mo;
  h->max_free_blocks = mo;
  h->free_head = -1;

  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->mutex, &attr);
  pthread_mutexattr_destroy(&attr);

  // One big free block spanning the heap. Heap offsets are relative to
  // segment base; block at heap_start.
  FreeBlock* fb = free_blocks(h);
  fb[0].used = 1;
  fb[0].offset = meta;
  fb[0].size = capacity;
  fb[0].next = -1;
  h->free_head = 0;

  h->magic = kMagic;  // publish last

  Store* s = new (std::nothrow) Store;
  if (!s) return nullptr;
  s->hdr = h;
  s->base = reinterpret_cast<uint8_t*>(base);
  s->mapped_size = total;
  snprintf(s->name, sizeof(s->name), "%s", name);
  return s;
}

void* ts_attach(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* base =
      mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) return nullptr;
  Header* h = reinterpret_cast<Header*>(base);
  if (h->magic != kMagic) {
    munmap(base, (size_t)st.st_size);
    return nullptr;
  }
  Store* s = new (std::nothrow) Store;
  if (!s) return nullptr;
  s->hdr = h;
  s->base = reinterpret_cast<uint8_t*>(base);
  s->mapped_size = (uint64_t)st.st_size;
  snprintf(s->name, sizeof(s->name), "%s", name);
  return s;
}

void ts_detach(void* sp) {
  Store* s = reinterpret_cast<Store*>(sp);
  if (!s) return;
  munmap(s->base, s->mapped_size);
  delete s;
}

void ts_destroy(const char* name) { shm_unlink(name); }

uint64_t ts_total_size(void* sp) {
  return reinterpret_cast<Store*>(sp)->mapped_size;
}

// Reserve a buffer for object `id` of `size` bytes. Returns offset into the
// segment where the caller writes payload, or 0 on failure (-> errno-style
// result via ts_last style omitted; 0 covers exists/full). The object stays
// kCreating (invisible to get) until ts_seal.
uint64_t ts_create_buf(void* sp, const uint8_t* id, uint64_t size) {
  Store* s = reinterpret_cast<Store*>(sp);
  Header* h = s->hdr;
  if (lock(h) != 0) return 0;
  Entry* e = find_slot(h, id, true);
  if (e == nullptr || (e->state != kFree && memcmp(e->id, id, kIdLen) == 0)) {
    unlock(h);
    return 0;  // table full or already exists
  }
  uint64_t off = heap_alloc(h, size);
  if (off == 0) {
    // Evict and retry.
    Entry* tab = entries(h);
    for (;;) {
      Entry* victim = nullptr;
      for (uint32_t i = 0; i < h->max_objects; i++) {
        Entry* ev = &tab[i];
        if (ev->state == kSealed && ev->refcount <= 0) {
          if (victim == nullptr || ev->lru_tick < victim->lru_tick) victim = ev;
        }
      }
      if (victim == nullptr) break;
      heap_free(h, victim->offset, victim->capacity);
      h->num_evictions++;
      h->bytes_evicted += victim->size;
      victim->state = kFree;
      h->num_objects--;
      off = heap_alloc(h, size);
      if (off != 0) break;
    }
    if (off == 0) {
      unlock(h);
      return 0;
    }
    // Eviction may have freed the slot we held (it cannot: victim entries are
    // distinct from the free slot we got), but re-find for safety.
    e = find_slot(h, id, true);
    if (e == nullptr) {
      heap_free(h, off, size);
      unlock(h);
      return 0;
    }
  }
  memcpy(e->id, id, kIdLen);
  e->state = kCreating;
  e->pending_delete = 0;
  e->create_ts = (uint64_t)time(nullptr);
  e->offset = off;
  e->size = size;
  e->capacity = size;
  e->refcount = 1;  // creator holds a pin until seal/abort
  e->lru_tick = ++h->tick;
  h->num_objects++;
  unlock(h);
  return off;
}

int ts_seal(void* sp, const uint8_t* id) {
  Store* s = reinterpret_cast<Store*>(sp);
  Header* h = s->hdr;
  if (lock(h) != 0) return -1;
  Entry* e = find_slot(h, id, false);
  if (e == nullptr || e->state != kCreating) {
    unlock(h);
    return -1;
  }
  if (e->pending_delete) {
    // deleted while still being written: finish as a free, not a seal
    heap_free(h, e->offset, e->capacity);
    e->state = kFree;
    e->pending_delete = 0;
    h->num_objects--;
    unlock(h);
    return 0;
  }
  e->state = kSealed;
  e->refcount = 0;  // creator pin released; caller re-pins via ts_get if needed
  e->lru_tick = ++h->tick;
  unlock(h);
  return 0;
}

int ts_abort(void* sp, const uint8_t* id) {
  Store* s = reinterpret_cast<Store*>(sp);
  Header* h = s->hdr;
  if (lock(h) != 0) return -1;
  Entry* e = find_slot(h, id, false);
  if (e == nullptr || e->state != kCreating) {
    unlock(h);
    return -1;
  }
  heap_free(h, e->offset, e->capacity);
  e->state = kFree;
  h->num_objects--;
  unlock(h);
  return 0;
}

// One-shot put: create + copy + seal.
// Returns 0 ok, -1 exists, -2 out of memory.
int ts_put(void* sp, const uint8_t* id, const void* data, uint64_t size) {
  Store* s = reinterpret_cast<Store*>(sp);
  {
    Header* h = s->hdr;
    if (lock(h) != 0) return -2;
    Entry* e = find_slot(h, id, false);
    if (e != nullptr && e->state != kFree) {
      unlock(h);
      return -1;
    }
    unlock(h);
  }
  uint64_t off = ts_create_buf(sp, id, size);
  if (off == 0) return -2;
  memcpy(s->base + off, data, size);
  return ts_seal(sp, id);
}

// Pin + locate. Returns offset (0 if absent/unsealed); size via out param.
uint64_t ts_get(void* sp, const uint8_t* id, uint64_t* size_out) {
  Store* s = reinterpret_cast<Store*>(sp);
  Header* h = s->hdr;
  if (lock(h) != 0) return 0;
  Entry* e = find_slot(h, id, false);
  if (e == nullptr || e->state != kSealed || e->pending_delete) {
    unlock(h);
    return 0;
  }
  e->refcount++;
  e->lru_tick = ++h->tick;
  uint64_t off = e->offset;
  if (size_out) *size_out = e->size;
  unlock(h);
  return off;
}

int ts_release(void* sp, const uint8_t* id) {
  Store* s = reinterpret_cast<Store*>(sp);
  Header* h = s->hdr;
  if (lock(h) != 0) return -1;
  Entry* e = find_slot(h, id, false);
  if (e == nullptr || e->state != kSealed) {
    unlock(h);
    return -1;
  }
  if (e->refcount > 0) e->refcount--;
  if (e->refcount == 0 && e->pending_delete) {
    heap_free(h, e->offset, e->capacity);
    e->state = kFree;
    e->pending_delete = 0;
    h->num_objects--;
  }
  unlock(h);
  return 0;
}

int ts_contains(void* sp, const uint8_t* id) {
  Store* s = reinterpret_cast<Store*>(sp);
  Header* h = s->hdr;
  if (lock(h) != 0) return 0;
  Entry* e = find_slot(h, id, false);
  int r = (e != nullptr && e->state == kSealed && !e->pending_delete)
              ? 1 : 0;
  unlock(h);
  return r;
}

// Delete an object. If it is pinned (a reader holds a view, or the
// native transfer plane is mid-send), the free is DEFERRED to the last
// ts_release — freeing the heap region under an active reader would let
// a concurrent allocation reuse it and corrupt the bytes in flight.
// Unpinned objects free immediately (LocalObjectManager free semantics).
int ts_delete(void* sp, const uint8_t* id) {
  Store* s = reinterpret_cast<Store*>(sp);
  Header* h = s->hdr;
  if (lock(h) != 0) return -1;
  Entry* e = find_slot(h, id, false);
  if (e == nullptr || e->state == kFree) {
    unlock(h);
    return -1;
  }
  if (e->refcount > 0) {
    e->pending_delete = 1;
    unlock(h);
    return 0;
  }
  heap_free(h, e->offset, e->capacity);
  e->state = kFree;
  e->pending_delete = 0;
  h->num_objects--;
  unlock(h);
  return 0;
}

// Enumerate sealed objects, least-recently-used first (the spill candidate
// order). Fills ids_out (max*20 bytes), sizes_out and pins_out (max each);
// returns the count written. Snapshot under the lock; callers must tolerate
// entries vanishing (eviction) between the snapshot and any follow-up call.
uint32_t ts_list(void* sp, uint8_t* ids_out, uint64_t* sizes_out,
                 int64_t* pins_out, uint32_t max) {
  Store* s = reinterpret_cast<Store*>(sp);
  Header* h = s->hdr;
  // Snapshot under the lock (O(n) copy), sort outside it — keeps the
  // cross-process critical section short even with many sealed objects.
  struct Item {
    uint8_t id[kIdLen];
    uint64_t size;
    int64_t pins;
    uint64_t tick;
  };
  if (lock(h) != 0) return 0;
  uint32_t total = h->num_objects;
  Item* items = new (std::nothrow) Item[total ? total : 1];
  if (items == nullptr) {
    unlock(h);
    return 0;
  }
  Entry* tab = entries(h);
  uint32_t n = 0;
  for (uint32_t i = 0; i < h->max_objects && n < total; i++) {
    Entry* e = &tab[i];
    if (e->state != kSealed) continue;
    memcpy(items[n].id, e->id, kIdLen);
    items[n].size = e->size;
    items[n].pins = e->refcount;
    items[n].tick = e->lru_tick;
    n++;
  }
  unlock(h);
  std::sort(items, items + n,
            [](const Item& a, const Item& b) { return a.tick < b.tick; });
  if (n > max) n = max;
  for (uint32_t i = 0; i < n; i++) {
    memcpy(ids_out + (uint64_t)i * kIdLen, items[i].id, kIdLen);
    sizes_out[i] = items[i].size;
    pins_out[i] = items[i].pins;
  }
  delete[] items;
  return n;
}

// Atomically free a sealed object iff its current pin count is <= max_pins
// (the caller's own pins). Returns 1 freed, 0 still pinned by readers,
// -1 absent/unsealed. This is the safe spill-eviction primitive: the
// decision and the free happen under one lock, so a reader pinning between
// a stale snapshot and the delete can never be invalidated (the bug class
// ts_delete's refcount-ignoring contract would allow).
int ts_evict(void* sp, const uint8_t* id, int64_t max_pins) {
  Store* s = reinterpret_cast<Store*>(sp);
  Header* h = s->hdr;
  if (lock(h) != 0) return -1;
  Entry* e = find_slot(h, id, false);
  if (e == nullptr || e->state != kSealed) {
    unlock(h);
    return -1;
  }
  if (e->refcount > max_pins) {
    unlock(h);
    return 0;
  }
  heap_free(h, e->offset, e->capacity);
  e->state = kFree;
  h->num_objects--;
  unlock(h);
  return 1;
}

uint64_t ts_bytes_in_use(void* sp) {
  Store* s = reinterpret_cast<Store*>(sp);
  return s->hdr->bytes_in_use;
}
uint64_t ts_capacity(void* sp) { return reinterpret_cast<Store*>(sp)->hdr->capacity; }
uint32_t ts_num_objects(void* sp) {
  return reinterpret_cast<Store*>(sp)->hdr->num_objects;
}
uint64_t ts_num_evictions(void* sp) {
  return reinterpret_cast<Store*>(sp)->hdr->num_evictions;
}

// Segment base pointer, for in-process zero-copy consumers of ts_get
// offsets (the native transfer plane in xfer.cc reads/writes the heap
// directly: shm -> socket with no userspace staging buffer).
void* ts_seg_base(void* sp) { return reinterpret_cast<Store*>(sp)->base; }

// Reap kCreating entries older than max_age_s: a producer SIGKILLed
// mid-write leaves its buffer orphaned forever (nothing seals or aborts
// it), making the object permanently unfetchable on this node. Live
// writers are safe at sane ages — local writes finish in seconds and
// the transfer plane's socket timeout (120s) bounds remote ones.
// Returns the number of entries freed.
int ts_reap_creating(void* sp, uint64_t max_age_s) {
  Store* s = reinterpret_cast<Store*>(sp);
  Header* h = s->hdr;
  if (lock(h) != 0) return 0;
  uint64_t now = (uint64_t)time(nullptr);
  Entry* tab = entries(h);
  int n = 0;
  for (uint32_t i = 0; i < h->max_objects; i++) {
    Entry* e = &tab[i];
    if (e->state == kCreating && e->create_ts + max_age_s <= now) {
      heap_free(h, e->offset, e->capacity);
      e->state = kFree;
      e->pending_delete = 0;
      h->num_objects--;
      n++;
    }
  }
  unlock(h);
  return n;
}

// Heartbeat a kCreating entry: a long-running writer (the transfer
// plane's chunked receive) refreshes create_ts so the orphan reaper
// never frees a buffer that is actively receiving bytes.
int ts_touch_creating(void* sp, const uint8_t* id) {
  Store* s = reinterpret_cast<Store*>(sp);
  Header* h = s->hdr;
  if (lock(h) != 0) return -1;
  Entry* e = find_slot(h, id, false);
  int r = -1;
  if (e != nullptr && e->state == kCreating) {
    e->create_ts = (uint64_t)time(nullptr);
    r = 0;
  }
  unlock(h);
  return r;
}

// CRASH-TEST HOOK: acquire the robust mutex, touch `marker_path` to tell
// the test harness the lock is held, then sleep. The harness SIGKILLs
// this process mid-sleep, so the next lock() in any surviving process
// must take the EOWNERDEAD path (tests/test_native_crash.py). Never used
// by production code — it exists because killing a process at exactly
// the right instant is otherwise nondeterministic.
int ts_debug_lock_hold(void* sp, const char* marker_path, uint32_t millis) {
  Store* s = reinterpret_cast<Store*>(sp);
  Header* h = s->hdr;
  if (lock(h) != 0) return -1;
  FILE* f = fopen(marker_path, "w");
  if (f != nullptr) fclose(f);
  struct timespec ts = {millis / 1000, (long)(millis % 1000) * 1000000L};
  nanosleep(&ts, nullptr);
  unlock(h);
  return 0;
}

// Entry state probe: 0 = absent, 1 = creating (a racing producer/puller
// is mid-write), 2 = sealed. Lets the transfer plane distinguish
// "already here / arriving" from "allocation failed".
int ts_state(void* sp, const uint8_t* id) {
  Store* s = reinterpret_cast<Store*>(sp);
  Header* h = s->hdr;
  if (lock(h) != 0) return 0;
  Entry* e = find_slot(h, id, false);
  int r = 0;
  if (e != nullptr && e->state == kCreating) r = 1;
  if (e != nullptr && e->state == kSealed && !e->pending_delete) r = 2;
  unlock(h);
  return r;
}

}  // extern "C"
