// ray_tpu native object-transfer plane.
//
// TPU-native equivalent of the reference's C++ object manager data path
// (reference: src/ray/object_manager/object_manager.cc:338 Push /
// :561 HandlePush — 64MiB-chunk gRPC streams between raylets). Design
// departure: instead of chunked RPC frames through the control-plane
// stack (which costs a pickle + two userspace copies per chunk in the
// Python nodelet), this is a dedicated TCP plane that writes straight
// from the shared-memory heap to the socket and reads straight from the
// socket into a freshly allocated shm buffer — zero userspace staging on
// both ends; the kernel does the only copies. The Python pull path
// (core/nodelet.py rpc_pull_object) uses it when available and falls
// back to the portable chunk RPC for spilled objects or native-disabled
// stores.
//
// Wire protocol (one TCP connection per fetch; requests may be pipelined
// sequentially on a kept-open connection):
//   request:  [20-byte object id]
//   response: [u64 little-endian total] [payload bytes]
//             total == UINT64_MAX -> object not present at the source.
//
// Concurrency: one detached listener thread; one detached thread per
// accepted connection (transfer counts are small — tens of hosts — and
// each transfer is long; thread-per-connection is the simple correct
// shape). The sealed object is pinned (ts_get) for the duration of the
// send so eviction cannot unmap it mid-write.

#include <arpa/inet.h>
#include <errno.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <pthread.h>
#include <stdint.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

// Public C ABI of the store (objstore.cc, linked into the same .so).
extern "C" {
uint64_t ts_get(void* sp, const uint8_t* id, uint64_t* size_out);
int ts_release(void* sp, const uint8_t* id);
uint64_t ts_create_buf(void* sp, const uint8_t* id, uint64_t size);
int ts_seal(void* sp, const uint8_t* id);
int ts_abort(void* sp, const uint8_t* id);
void* ts_seg_base(void* sp);
int ts_state(void* sp, const uint8_t* id);
int ts_touch_creating(void* sp, const uint8_t* id);
}

namespace {

constexpr uint32_t kIdLen = 20;
constexpr uint64_t kAbsent = ~0ULL;
// "source saturated" reply: the puller should retry (possibly against a
// peer that registered a copy in the meantime) instead of queueing here.
// A broadcast fan-in then cascades through fresh holders rather than
// serializing every transfer behind one source NIC/core (ref: pull
// manager fan-out across holders, pull_manager.h:52).
constexpr uint64_t kBusy = ~0ULL - 1;
constexpr int kIoTimeoutSec = 120;

struct ServerState {
  int listen_fd = -1;
  void* store = nullptr;
  std::atomic<bool> stop{false};
  std::atomic<uint64_t> generation{0};  // guards stale listener threads
};

ServerState g_server;

// Outbound-serve throttle: at most g_serve_cap payloads of THE SAME
// object in flight (0 = unlimited). Excess requests get kBusy instead of
// a queue slot. Per-object, not global: a broadcast fan-in of one hot
// object should cascade through peer holders, but pulls of DISTINCT
// objects from one node must keep multiplexing freely.
std::atomic<int> g_serve_cap{0};
std::atomic<uint64_t> g_busy_rejections{0};
std::mutex g_serve_mu;
std::unordered_map<std::string, int> g_active_by_id;

// Connection registry. ts_xfer_serve_stop() MUST NOT return while any
// sender thread can still touch the shm heap or the Store handle: the
// caller's next move is ts_detach (munmap + delete Store), and a sender
// still inside write_exact()/ts_release() would segfault on the unmapped
// segment — the exact delete-race crash the round-3 suite reproduced.
// Every handler thread (and the listener) registers here; stop() shuts
// down all live conn fds (aborting blocked reads/writes immediately) and
// drains the registry before returning.
std::mutex g_conn_mu;
std::condition_variable g_conn_cv;
std::vector<int> g_conn_fds;  // fds whose handler thread is still live
int g_live_threads = 0;       // handler threads + listener thread

void set_timeouts(int fd) {
  struct timeval tv;
  tv.tv_sec = kIoTimeoutSec;
  tv.tv_usec = 0;
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
  // big socket buffers: bulk transfers must not ping-pong on the default
  // ~16KB windows (dominates on single-core hosts where sender and
  // receiver share the CPU)
  int buf = 4 << 20;
  setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &buf, sizeof(buf));
  setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &buf, sizeof(buf));
}

bool read_exact(int fd, void* buf, uint64_t n) {
  uint8_t* p = reinterpret_cast<uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = read(fd, p, n);
    if (r < 0 && errno == EINTR) continue;  // signals must not kill a
    if (r <= 0) return false;               // multi-GB transfer
    p += r;
    n -= (uint64_t)r;
  }
  return true;
}

bool write_exact(int fd, const void* buf, uint64_t n) {
  const uint8_t* p = reinterpret_cast<const uint8_t*>(buf);
  while (n > 0) {
    // cap single write() calls; very large writes can spuriously EINVAL
    // on some stacks and 8MiB keeps send-buffer pressure smooth
    uint64_t chunk = n > (8ULL << 20) ? (8ULL << 20) : n;
    ssize_t w = write(fd, p, chunk);
    if (w < 0 && errno == EINTR) continue;
    if (w <= 0) return false;
    p += w;
    n -= (uint64_t)w;
  }
  return true;
}

void handle_conn(int fd, void* store) {
  set_timeouts(fd);
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  uint8_t id[kIdLen];
  while (read_exact(fd, id, kIdLen)) {
    uint64_t size = 0;
    uint64_t off = ts_get(store, id, &size);
    if (off == 0) {
      uint64_t absent = kAbsent;
      if (!write_exact(fd, &absent, sizeof(absent))) break;
      continue;
    }
    int cap = g_serve_cap.load(std::memory_order_relaxed);
    bool counted = false;
    if (cap > 0) {
      std::string idkey(reinterpret_cast<const char*>(id), kIdLen);
      std::lock_guard<std::mutex> lk(g_serve_mu);
      int& n = g_active_by_id[idkey];
      if (n < cap) {
        ++n;
        counted = true;
      }
    }
    if (cap > 0 && !counted) {
      g_busy_rejections.fetch_add(1);
      ts_release(store, id);
      uint64_t busy = kBusy;
      if (!write_exact(fd, &busy, sizeof(busy))) break;
      continue;
    }
    const uint8_t* payload =
        reinterpret_cast<const uint8_t*>(ts_seg_base(store)) + off;
    bool ok = write_exact(fd, &size, sizeof(size)) &&
              write_exact(fd, payload, size);
    if (counted) {
      std::string idkey(reinterpret_cast<const char*>(id), kIdLen);
      std::lock_guard<std::mutex> lk(g_serve_mu);
      auto it = g_active_by_id.find(idkey);
      if (it != g_active_by_id.end() && --it->second <= 0)
        g_active_by_id.erase(it);
    }
    ts_release(store, id);
    if (!ok) break;
  }
}

// Thread body for one accepted connection: run the handler, then
// deregister BEFORE closing the fd — serve_stop shuts down registered
// fds under g_conn_mu, so the fd number can never be recycled while
// still in the registry.
void conn_main(int fd, void* store) {
  handle_conn(fd, store);
  {
    // notify INSIDE the critical section: once a waiter observes
    // g_live_threads == 0 under the mutex, this thread is provably past
    // its last cv touch — the process may exit and destroy the cv
    // without racing the broadcast. (The fd stays ours until close(), so
    // its number cannot be recycled into the registry meanwhile.)
    std::lock_guard<std::mutex> g(g_conn_mu);
    g_conn_fds.erase(std::find(g_conn_fds.begin(), g_conn_fds.end(), fd));
    g_live_threads--;
    g_conn_cv.notify_all();
  }
  close(fd);
}

}  // namespace

extern "C" {

// Start the transfer server on host:port (port 0 = ephemeral). Returns
// the bound port, or -1. One server per process.
int ts_xfer_serve_start(void* store, const char* host, int port) {
  if (g_server.listen_fd >= 0) return -1;
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)port);
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    close(fd);
    return -1;
  }
  if (bind(fd, (sockaddr*)&addr, sizeof(addr)) != 0 || listen(fd, 64) != 0) {
    close(fd);
    return -1;
  }
  socklen_t len = sizeof(addr);
  if (getsockname(fd, (sockaddr*)&addr, &len) != 0) {
    close(fd);
    return -1;
  }
  g_server.listen_fd = fd;
  g_server.store = store;
  g_server.stop.store(false);
  uint64_t gen = g_server.generation.fetch_add(1) + 1;

  {
    std::lock_guard<std::mutex> g(g_conn_mu);
    g_live_threads++;  // the listener itself
  }
  std::thread([fd, store, gen]() {
    while (!g_server.stop.load() && g_server.generation.load() == gen) {
      int conn = accept(fd, nullptr, nullptr);
      if (conn < 0) {
        if (g_server.stop.load() || g_server.generation.load() != gen)
          break;                        // stale thread after stop/restart
        if (errno == EINTR || errno == ECONNABORTED) continue;
        if (errno == EBADF || errno == EINVAL) break;  // fd closed
        usleep(10000);                  // EMFILE etc.: back off, don't spin
      } else {
        // Register under the lock, re-checking stop/generation there:
        // serve_stop iterates the registry under the same lock, so a
        // handler can neither be spawned after the drain snapshot nor
        // missed by it. (The stale-generation case also lands here: a
        // stale thread that won accept() on a REUSED fd number holds a
        // connection meant for the new server — drop it, the client
        // retries and lands on the live listener.)
        std::lock_guard<std::mutex> g(g_conn_mu);
        if (g_server.stop.load() || g_server.generation.load() != gen) {
          close(conn);
          break;
        }
        g_conn_fds.push_back(conn);
        g_live_threads++;
        std::thread(conn_main, conn, store).detach();
      }
    }
    {
      std::lock_guard<std::mutex> g(g_conn_mu);
      g_live_threads--;
      g_conn_cv.notify_all();  // inside the lock: see conn_main
    }
  }).detach();
  return (int)ntohs(addr.sin_port);
}

// Stop the server and drain every live handler thread. Returns the
// number of threads still live after the drain window — 0 means fully
// drained and the caller may munmap/detach the store; nonzero means a
// handler is wedged (e.g. blocked on the robust store mutex held by a
// crashed peer) and the caller MUST NOT unmap the segment or detach the
// handle, or the wedged thread's next touch is the round-3 SIGSEGV.
int ts_xfer_serve_stop() {
  if (g_server.listen_fd < 0) return 0;
  g_server.stop.store(true);
  g_server.generation.fetch_add(1);  // invalidate the listener thread
  // shutdown unblocks accept() reliably; close alone may not
  shutdown(g_server.listen_fd, SHUT_RDWR);
  close(g_server.listen_fd);
  g_server.listen_fd = -1;
  // Drain: shutdown() aborts any blocked socket read()/write()
  // immediately, and the registry empties as the threads deregister.
  std::unique_lock<std::mutex> lk(g_conn_mu);
  for (int cfd : g_conn_fds) shutdown(cfd, SHUT_RDWR);
  g_conn_cv.wait_for(lk, std::chrono::seconds(10),
                     [] { return g_live_threads == 0; });
  return g_live_threads;
}

// Fetch one object from a remote transfer server into the local store.
// Returns 0 = ok (sealed locally), 1 = absent at source, 2 = connect/io
// error, 3 = local allocation failed (caller should free space + retry
// or fall back), 4 = protocol error (local buffer aborted),
// 5 = already local (sealed, or a racing pull is mid-write — wait, do
// not free space for it), 6 = source at its serve cap (retry, ideally
// against another holder).
int ts_xfer_fetch(void* store, const char* host, int port,
                  const uint8_t* id, uint64_t* total_out) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return 2;
  set_timeouts(fd);
  sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)port);
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1 ||
      connect(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
    close(fd);
    return 2;
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));

  uint64_t total = 0;
  if (!write_exact(fd, id, kIdLen) ||
      !read_exact(fd, &total, sizeof(total))) {
    close(fd);
    return 2;
  }
  if (total == kAbsent) {
    close(fd);
    return 1;
  }
  if (total == kBusy) {
    close(fd);
    return 6;
  }
  if (total_out) *total_out = total;
  uint64_t off = ts_create_buf(store, id, total);
  if (off == 0) {
    close(fd);
    // distinguish "already here / arriving" from a real OOM — a caller
    // reacting to OOM with a spill pass must not evict the store because
    // a concurrent duplicate pull won the create race
    return ts_state(store, id) != 0 ? 5 : 3;
  }
  uint8_t* dst = reinterpret_cast<uint8_t*>(ts_seg_base(store)) + off;
  // Receive with a heartbeat per read() batch (at most once a second),
  // NOT per 64 MiB chunk: a trickling sender can keep one chunk in
  // flight far past the orphan-reaper age (SO_RCVTIMEO bounds each
  // read(), not the chunk), and the reaper would free — and possibly
  // reallocate — the buffer while this loop is still writing into it.
  // With ≤1 s touch granularity a live socket can never age out; a
  // fully stalled socket times out in read() and aborts cleanly.
  uint64_t got = 0;
  uint64_t last_touch = (uint64_t)time(nullptr);
  while (got < total) {
    uint64_t want = total - got;
    if (want > (8ULL << 20)) want = (8ULL << 20);
    ssize_t r = read(fd, dst + got, want);
    if (r < 0 && errno == EINTR) continue;
    if (r <= 0) {
      ts_abort(store, id);
      close(fd);
      return 4;
    }
    got += (uint64_t)r;
    uint64_t now = (uint64_t)time(nullptr);
    if (now != last_touch) {
      last_touch = now;
      if (ts_touch_creating(store, id) != 0) {
        // entry vanished mid-fetch (reaped after a stall, or deleted):
        // the buffer may already be reallocated — stop writing
        // IMMEDIATELY and DO NOT seal a foreign entry
        close(fd);
        return 4;
      }
    }
  }
  close(fd);
  ts_seal(store, id);
  return 0;
}

// Concurrent-outbound-serve cap PER OBJECT for this process's transfer
// server (0 = unlimited). Over-cap requests are answered kBusy.
void ts_xfer_set_serve_cap(int cap) {
  g_serve_cap.store(cap < 0 ? 0 : cap);
}

uint64_t ts_xfer_busy_rejections() { return g_busy_rejections.load(); }

}  // extern "C"
