"""Native (C++) components of ray_tpu.

Currently: the shared-memory object store (objstore.cc — the host tier of
the object plane, reference: src/ray/object_manager/plasma/) and the
zero-staging TCP transfer plane (xfer.cc — reference:
src/ray/object_manager/object_manager.cc push/pull). Compiled lazily on
first import so a fresh checkout needs no separate build step.
"""

import os
import subprocess

_HERE = os.path.dirname(os.path.abspath(__file__))
OBJSTORE_SO = os.path.join(_HERE, "libraytpu_objstore.so")


def ensure_built() -> str:
    """Compile the native library if missing or older than its sources."""
    srcs = [os.path.join(_HERE, "objstore.cc"),
            os.path.join(_HERE, "xfer.cc")]
    if (not os.path.exists(OBJSTORE_SO)
            or os.path.getmtime(OBJSTORE_SO) < max(
                os.path.getmtime(s) for s in srcs)):
        subprocess.run(
            ["make", "-C", _HERE, "all"],
            check=True,
            capture_output=True,
        )
    return OBJSTORE_SO
