"""Compiled execution graphs: static DAGs out of the dispatch path.

Reference: the experimental compiled-DAG layer under python/ray/dag
(`dag.experimental_compile()`): when a DAG's shape is known up front,
compile it ONCE — topologically sort, instantiate every `ClassNode` actor,
pin method bindings, resolve actor routes, and negotiate one standing
channel per node (core/channels.py) with pre-resolved edges to its
consumers. After that, `compiled.execute(x)` is a raw enqueue: pack the
input once, push one frame per entry channel, return a `CompiledDAGRef`
that resolves from the output sink. No per-call task-spec build, no
ObjectID registration, no scheduler round, no mailbox queueing.

Sequencing: every execute() gets a monotonically increasing sequence
number. Channels gather frames per seq and dispatch strictly in seq
order, so in-flight executions pipeline through the graph without
interleaving corruption even when frames race on the wire.

Errors are typed and per-sequence: a method raise travels down the
channel as the exception itself, an actor killed mid-execute surfaces as
`ActorDiedError` at the ref — poisoning only that sequence number; later
sequences fail with their own frames. A GCS DEAD notification is the
fallback for frames lost with a crashed worker: the ref's wait loop
watches the actor-state cache and poisons what can no longer complete.

Restrictions (mirroring the reference's aDAG): actor-method nodes only
(no `FunctionNode`), at most one `InputNode`, `MultiOutputNode` only at
the root, no DAG nodes nested inside container arguments, and `ClassNode`
constructor args must be static. Generator leaves stream item frames to
the ref (iterate the ref) and are only legal at a single-leaf root.
"""

from __future__ import annotations

import logging
import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core import serialization
from ray_tpu.core.channels import (F_DATA, F_END, F_ERR, F_ITEM, ChannelEdge,
                                   ChannelSpec, pack_value)
from ray_tpu.core.runtime import get_runtime
from ray_tpu.core.status import (ActorDiedError, GetTimeoutError,
                                 RayTpuError)
from ray_tpu.dag.dag_node import (ClassMethodNode, ClassNode, DAGNode,
                                  FunctionNode, InputAttributeNode,
                                  InputNode, MultiOutputNode)

logger = logging.getLogger("ray_tpu.dag.compiled")

_WAIT_SLICE_S = 0.05


class _PendingExec:
    """Sink-side state of one in-flight sequence number."""

    __slots__ = ("frames", "error", "items", "stream_ended", "t0",
                 "span_emitted")

    def __init__(self):
        self.frames: Dict[int, bytes] = {}
        self.error: Optional[BaseException] = None
        self.items: deque = deque()
        self.stream_ended = False
        self.t0 = time.time()       # execute() wall clock, for the span
        self.span_emitted = False


class _ChannelSink:
    """Driver-side output endpoint: channel_result frames land here (on
    the runtime loop), refs consume from any thread."""

    def __init__(self, sink_id: str, n_slots: int):
        self.sink_id = sink_id
        self.n_slots = n_slots
        self._cond = threading.Condition()
        self._pending: Dict[int, _PendingExec] = {}
        # set by CompiledDAG: called once per seq when it completes
        # (all slots / error / stream end) — the driver-side execute span
        self.on_complete = None

    def expect(self, seq: int) -> None:
        with self._cond:
            self._pending[seq] = _PendingExec()

    def deliver(self, seq: int, slot: int, kind: str,
                payload: bytes) -> None:
        with self._cond:
            rec = self._pending.get(seq)
            if rec is None:
                return   # resolved or torn down; late frame
            if kind == F_ERR:
                if rec.error is None:
                    try:
                        rec.error = serialization.unpack(payload)
                    except Exception as e:
                        rec.error = RayTpuError(
                            f"undecodable channel error frame: {e!r}")
            elif kind == F_ITEM:
                rec.items.append(payload)
            elif kind == F_END:
                rec.stream_ended = True
            else:
                rec.frames[slot] = payload
            self._maybe_complete(seq, rec)
            self._cond.notify_all()

    def _maybe_complete(self, seq: int, rec: _PendingExec) -> None:
        """Under self._cond: fire on_complete exactly once per seq, when
        its output is fully determined."""
        if rec.span_emitted or self.on_complete is None:
            return
        done = (rec.error is not None or rec.stream_ended
                or len(rec.frames) >= self.n_slots)
        if done:
            rec.span_emitted = True
            try:
                self.on_complete(seq, rec)
            except Exception:
                pass

    def poison(self, seq: int, err: BaseException) -> None:
        with self._cond:
            rec = self._pending.get(seq)
            if rec is not None and rec.error is None:
                rec.error = err
                self._maybe_complete(seq, rec)
                self._cond.notify_all()

    def poison_all(self, err: BaseException) -> None:
        with self._cond:
            for rec in self._pending.values():
                if rec.error is None:
                    rec.error = err
            self._cond.notify_all()

    def pop(self, seq: int) -> None:
        with self._cond:
            self._pending.pop(seq, None)

    def record(self, seq: int) -> Optional[_PendingExec]:
        return self._pending.get(seq)

    @property
    def cond(self) -> threading.Condition:
        return self._cond

    def inflight(self) -> int:
        with self._cond:
            return len(self._pending)


_UNSET = object()


class CompiledDAGRef:
    """Handle to one execution of a CompiledDAG. `get()` resolves the
    output; iterating consumes a streaming leaf's items in order."""

    def __init__(self, dag: "CompiledDAG", seq: int, streaming: bool):
        self._dag = dag
        self._seq = seq
        self._streaming = streaming
        self._result = _UNSET

    @property
    def seq(self) -> int:
        return self._seq

    def done(self) -> bool:
        if self._result is not _UNSET:
            return True
        sink = self._dag._sink
        with sink.cond:
            rec = sink.record(self._seq)
            if rec is None:
                return True
            return (len(rec.frames) >= sink.n_slots
                    or bool(rec.items) or rec.stream_ended
                    or rec.error is not None)

    def get(self, timeout: Optional[float] = None):
        if self._result is not _UNSET:
            if isinstance(self._result, BaseException):
                raise self._result
            return self._result
        sink = self._dag._sink
        deadline = None if timeout is None else time.monotonic() + timeout
        with sink.cond:
            while True:
                rec = sink.record(self._seq)
                if rec is None:
                    raise RuntimeError(
                        f"compiled-dag seq {self._seq} was discarded "
                        "(torn down or already consumed)")
                if len(rec.frames) >= sink.n_slots:
                    # completion wins over poisoning: the frames are here
                    payloads = [rec.frames[i] for i in range(sink.n_slots)]
                    break
                if rec.items or rec.stream_ended:
                    raise TypeError("the compiled leaf returned a "
                                    "generator; iterate the ref instead "
                                    "of calling get()")
                if rec.error is not None:
                    self._result = rec.error
                    sink._pending.pop(self._seq, None)
                    raise rec.error
                if deadline is not None and time.monotonic() >= deadline:
                    raise GetTimeoutError(
                        f"compiled-dag seq {self._seq} not ready after "
                        f"{timeout}s")
                sink.cond.wait(_WAIT_SLICE_S)
                self._dag._poison_dead_actors()
        values = [serialization.unpack(p) for p in payloads]
        self._result = values if self._dag._multi_output else values[0]
        sink.pop(self._seq)
        return self._result

    # ---- streaming consumption (single generator leaf)

    def __iter__(self):
        if not self._streaming:
            raise TypeError("this compiled DAG does not stream; call get()")
        return self

    def __next__(self):
        sink = self._dag._sink
        with sink.cond:
            while True:
                rec = sink.record(self._seq)
                if rec is None:
                    raise StopIteration
                if rec.items:
                    payload = rec.items.popleft()
                    break
                if rec.frames:
                    raise TypeError("the compiled leaf returned a plain "
                                    "value; call get() instead of "
                                    "iterating the ref")
                if rec.error is not None:
                    err = rec.error
                    sink._pending.pop(self._seq, None)
                    raise err
                if rec.stream_ended:
                    sink._pending.pop(self._seq, None)
                    raise StopIteration
                sink.cond.wait(_WAIT_SLICE_S)
                self._dag._poison_dead_actors()
        return serialization.unpack(payload)

    def __repr__(self):
        return f"CompiledDAGRef(seq={self._seq})"


class CompiledDAG:
    """A bound static DAG compiled onto standing channels. Obtain via
    `dag_node.experimental_compile()`."""

    def __init__(self, root: DAGNode, *,
                 resolve_timeout: Optional[float] = 60.0):
        self._rt = get_runtime()
        self._root = root
        self._lock = threading.Lock()
        self._next_seq = 0
        self._torn_down = False
        self._sink_id = uuid.uuid4().hex
        self._multi_output = isinstance(root, MultiOutputNode)
        self._streaming = False
        self._has_input = False
        self._owned: List[Tuple[ClassNode, Any]] = []   # kill at teardown
        self._actor_ids: List[Any] = []
        self._specs: List[Tuple[ChannelSpec, Tuple[str, int]]] = []
        self._entries: List[Tuple[Tuple[str, int], str, int, str]] = []
        self._tick = pack_value(None)
        self._compile(resolve_timeout)

    # ------------------------------------------------------------- compile

    def _compile(self, resolve_timeout: Optional[float]) -> None:
        rt = self._rt
        if rt.address is None:
            raise RuntimeError("ray_tpu.init() must run before "
                               "experimental_compile()")
        order = self._root._topo_order()

        input_node: Optional[InputNode] = None
        for n in order:
            if isinstance(n, FunctionNode):
                raise TypeError(
                    "experimental_compile supports actor-method DAGs only; "
                    f"found {n!r} (FunctionNode)")
            if isinstance(n, InputNode):
                if input_node is not None and n is not input_node:
                    raise TypeError("compiled DAGs accept at most one "
                                    "InputNode")
                input_node = n
            if isinstance(n, MultiOutputNode) and n is not self._root:
                raise TypeError("MultiOutputNode is only legal at the DAG "
                                "root")
        self._has_input = input_node is not None

        if self._multi_output:
            leaves = list(self._root._bound_args[0])
            if not leaves or not all(isinstance(x, ClassMethodNode)
                                     for x in leaves):
                raise TypeError("MultiOutputNode outputs must be "
                                "ClassMethodNodes")
        elif isinstance(self._root, ClassMethodNode):
            leaves = [self._root]
        else:
            raise TypeError(
                f"compiled DAG root must be a ClassMethodNode or "
                f"MultiOutputNode, not {type(self._root).__name__}")

        method_nodes = [n for n in order
                        if isinstance(n, ClassMethodNode)]

        # 1. instantiate every actor up front (lazy nodes become eager);
        #    constructor args must be static — there is no per-execute
        #    resolve pass to feed them
        def static_resolve(v):
            if isinstance(v, DAGNode):
                raise TypeError("ClassNode constructor args must be static "
                                "in compiled DAGs")
            return v

        handles: Dict[int, Any] = {}       # id(ClassNode) -> ActorHandle
        for node in method_nodes:
            cn = node._class_node
            if id(cn) in handles:
                continue
            owned = cn._handle is None and not cn._external
            handle = cn._get_handle(static_resolve)
            handles[id(cn)] = handle
            if owned:
                self._owned.append((cn, handle))

        # 2. pre-resolve actor routes once; subscribe so the GCS pushes
        #    DEAD transitions into the state cache the refs watch
        addr_of: Dict[int, Tuple[str, int]] = {}
        for cn_id, handle in handles.items():
            aid = handle._actor_id
            rt._subscribe_actor(aid)
            addr = rt._run(
                rt._resolve_actor(aid, resolve_timeout),
                timeout=None if resolve_timeout is None
                else resolve_timeout + 5.0)
            addr_of[cn_id] = tuple(addr)
            self._actor_ids.append(aid)

        # 3. build one ChannelSpec per method node, threading edges from
        #    producers to the consumer slots they feed
        states: Dict[int, dict] = {}
        for idx, node in enumerate(method_nodes):
            states[id(node)] = {
                "node": node,
                "channel_id": uuid.uuid4().hex,
                "addr": addr_of[id(node._class_node)],
                "actor_id": handles[id(node._class_node)]._actor_id,
                "args": [], "kwargs": [],
                "n_slots": 0, "input_slot": None,
                "downstream": [],
                "label": f"{node._method}@{idx}",
            }

        def contains_dag_node(v) -> bool:
            if isinstance(v, DAGNode):
                return True
            if isinstance(v, (list, tuple)):
                return any(contains_dag_node(x) for x in v)
            if isinstance(v, dict):
                return any(contains_dag_node(x) for x in v.values())
            return False

        def input_slot(st: dict) -> int:
            if st["input_slot"] is None:
                st["input_slot"] = st["n_slots"]
                st["n_slots"] += 1
            return st["input_slot"]

        def entry_of(st: dict, v) -> Tuple:
            if isinstance(v, ClassMethodNode):
                prod = states.get(id(v))
                if prod is None:
                    raise TypeError("a compiled node consumes a "
                                    "ClassMethodNode outside this DAG")
                slot = st["n_slots"]
                st["n_slots"] += 1
                prod["downstream"].append(ChannelEdge(
                    "push", st["addr"], st["channel_id"], slot,
                    label=st["label"]))
                return ("slot", slot)
            if isinstance(v, InputNode):
                return ("slot", input_slot(st))
            if isinstance(v, InputAttributeNode):
                return ("slot_attr", input_slot(st), v._key)
            if isinstance(v, ClassNode):
                h = handles.get(id(v))
                if h is None:
                    h = v._get_handle(static_resolve)
                return ("const", serialization.pack(h))
            if isinstance(v, DAGNode):
                raise TypeError(f"cannot compile argument node {v!r}")
            if contains_dag_node(v):
                raise TypeError(
                    "compiled DAGs require DAG nodes as top-level "
                    "arguments, not nested inside containers")
            return ("const", serialization.pack(v))

        for node in method_nodes:
            st = states[id(node)]
            for a in node._bound_args:
                st["args"].append(entry_of(st, a))
            for k, v in node._bound_kwargs.items():
                st["kwargs"].append((k, entry_of(st, v)))

        # 4. leaf edges into the driver sink
        driver_addr = rt.address.addr
        for i, leaf in enumerate(leaves):
            states[id(leaf)]["downstream"].append(ChannelEdge(
                "result", driver_addr, self._sink_id, i, label="driver"))
        self._streaming = len(leaves) == 1

        self._sink = _ChannelSink(self._sink_id, n_slots=len(leaves))
        # driver-side span per execute: expect() stamps t0 at execute
        # time, the sink fires once when the seq's output is determined.
        # Unconditional (unlike worker-side dag:: spans, which are
        # tracing-gated): one event per execute is the observability
        # floor compiled graphs otherwise lack.
        self._label = "|".join(f"{leaf._method}" for leaf in leaves)
        self._sink.on_complete = self._record_execute_span
        rt.register_channel_sink(self._sink_id, self._sink)

        # 5. channels with no inbound slots still need one frame per seq
        #    to know when to fire: the driver pushes a tick
        for st in states.values():
            if st["n_slots"] == 0:
                st["tick_slot"] = 0
                st["n_slots"] = 1
            else:
                st["tick_slot"] = None

        specs = []
        for node in method_nodes:
            st = states[id(node)]
            spec = ChannelSpec(
                channel_id=st["channel_id"],
                actor_id=st["actor_id"],
                method=node._method,
                args_template=tuple(st["args"]),
                kwargs_template=tuple(st["kwargs"]),
                n_slots=st["n_slots"],
                downstream=tuple(st["downstream"]),
                streaming_ok=self._streaming and node is leaves[0],
                label=st["label"],
            )
            specs.append((spec, st["addr"]))
            if st["input_slot"] is not None:
                self._entries.append((st["addr"], st["channel_id"],
                                      st["input_slot"], "input"))
            if st["tick_slot"] is not None:
                self._entries.append((st["addr"], st["channel_id"],
                                      st["tick_slot"], "tick"))
        self._specs = specs

        # 6. negotiate: open consumers before their producers so a frame
        #    can never race ahead of its destination channel
        try:
            for spec, addr in reversed(specs):
                r = rt._run(rt.pool.get(addr).call(
                    "channel_open", spec=spec, timeout=30.0), timeout=35.0)
                if not r.get("ok"):
                    raise RuntimeError(
                        f"channel_open for {spec.label} failed: "
                        f"{r.get('error')}")
        except BaseException:
            self.teardown(kill_actors=True)
            raise

    # ------------------------------------------------------------- execute

    def execute(self, *args, **kwargs) -> CompiledDAGRef:
        """One raw enqueue onto the standing channels. Mirrors the lazy
        InputNode calling convention exactly."""
        if self._torn_down:
            raise RuntimeError("CompiledDAG has been torn down")
        if args and kwargs:
            raise TypeError(
                "DAG execute() accepts positional OR keyword inputs, not "
                "both (an InputAttributeNode cannot address a mixed input)")
        if self._has_input:
            if len(args) == 1 and not kwargs:
                value = args[0]
            elif kwargs:
                value = kwargs
            else:
                value = args
            payload = pack_value(value)
        else:
            if args or kwargs:
                raise TypeError("this compiled DAG binds no InputNode; "
                                "execute() takes no arguments")
            payload = self._tick
        with self._lock:
            seq = self._next_seq
            self._next_seq += 1
            self._sink.expect(seq)
        # the enqueue itself is fire-and-forget on the runtime loop: the
        # caller's thread never blocks, frames ride one-way RPC (no reply
        # round-trip), and loop submission order keeps same-thread
        # executes FIFO on the wire
        self._rt._spawn(self._push_all(seq, payload))
        return CompiledDAGRef(self, seq, streaming=self._streaming)

    async def _push_all(self, seq: int, payload: bytes) -> None:
        rt = self._rt
        for addr, channel_id, slot, kind in self._entries:
            try:
                await rt.pool.get(tuple(addr)).oneway(
                    "channel_push", channel_id=channel_id, seq=seq,
                    slot=slot, kind=F_DATA,
                    payload=payload if kind == "input" else self._tick)
            except Exception as e:
                self._sink.poison(seq, RayTpuError(
                    f"compiled-dag input push failed for seq {seq}: "
                    f"{e!r}"))

    def _record_execute_span(self, seq: int, rec: _PendingExec) -> None:
        """Runs under the sink condition on the runtime loop — must stay
        non-blocking (record_event is lock+append)."""
        self._rt.record_span({
            "kind": "span", "name": f"dag::{self._label}",
            "trace_id": f"dag:{self._sink_id[:8]}",
            "span_id": f"{self._sink_id[:8]}:{seq}", "parent_id": None,
            "ts": rec.t0, "dur": max(time.time() - rec.t0, 0.0),
            "attrs": {"seq": seq, "ok": rec.error is None,
                      "streaming": self._streaming}})

    # ------------------------------------------------------------ liveness

    def _poison_dead_actors(self) -> None:
        """Fallback for frames lost with a crashed worker: the GCS DEAD
        notification poisons every seq that can no longer complete."""
        for aid in self._actor_ids:
            st = self._rt._actor_state.get(aid)
            if st is not None and st.get("state") == "DEAD":
                self._sink.poison_all(ActorDiedError(
                    f"compiled-dag actor {aid.hex()[:12]} died: "
                    f"{st.get('death_cause')}", actor_id=aid.hex()))
                return

    def num_inflight(self) -> int:
        return self._sink.inflight()

    # ------------------------------------------------------------ teardown

    def teardown(self, kill_actors: bool = True) -> None:
        """Release the standing channels and (owned) actors. In-flight
        refs fail with a teardown error."""
        with self._lock:
            if self._torn_down:
                return
            self._torn_down = True
        rt = self._rt
        for spec, addr in self._specs:
            try:
                rt._run(rt.pool.get(addr).call(
                    "channel_close", channel_id=spec.channel_id,
                    timeout=5.0), timeout=10.0)
            except Exception:
                pass
        rt.unregister_channel_sink(self._sink_id)
        if getattr(self, "_sink", None) is not None:
            self._sink.poison_all(RuntimeError("CompiledDAG torn down"))
        if kill_actors:
            for cn, handle in self._owned:
                try:
                    rt.kill_actor(handle._actor_id)
                except Exception:
                    pass
                with cn._lock:
                    cn._handle = None   # lazy execute() can re-create
        self._owned = []

    def __del__(self):
        try:
            if not getattr(self, "_torn_down", True):
                self.teardown()
        except Exception:
            pass

    def __repr__(self):
        return (f"CompiledDAG(channels={len(self._specs)}, "
                f"actors={len(self._actor_ids)}, "
                f"streaming={self._streaming})")
