"""DAG node types.

Reference: python/ray/dag/dag_node.py:23 (DAGNode: bound args + traversal +
execute), input_node.py (InputNode context manager + attribute access),
function_node.py / class_node.py (task and actor-method nodes).

Execution model: `execute(*args)` walks the DAG bottom-up once per call,
replacing child nodes with the ObjectRefs of their `.remote()` submissions —
so a diamond DAG runs its independent branches concurrently for free (refs
flow, nothing blocks until the final `ray_tpu.get`). Actor nodes
(`ClassNode`) instantiate their actor lazily on first execute and reuse it
after, matching the reference's stateful-node semantics.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

# Structure generation: bumped on any rebind() anywhere, so cached
# topological orders (kept per root node) invalidate without every node
# needing a back-pointer to the roots that traversed it.
_struct_gen = 0


def _bump_struct_gen() -> None:
    global _struct_gen
    _struct_gen += 1


class DAGNode:
    """Base: immutable bound (args, kwargs); children are nested DAGNodes."""

    def __init__(self, args: Tuple, kwargs: Dict[str, Any]):
        self._bound_args = args
        self._bound_kwargs = kwargs
        self._topo_cache: Optional[Tuple[int, List["DAGNode"]]] = None

    # -- traversal ------------------------------------------------------------

    def _children(self) -> List["DAGNode"]:
        out: List[DAGNode] = []

        def scan(v):
            if isinstance(v, DAGNode):
                out.append(v)
            elif isinstance(v, (list, tuple)):
                for x in v:
                    scan(x)
            elif isinstance(v, dict):
                for x in v.values():
                    scan(x)

        for a in self._bound_args:
            scan(a)
        for a in self._bound_kwargs.values():
            scan(a)
        return out

    def _walk(self, seen: Optional[set] = None) -> List["DAGNode"]:
        """Post-order unique traversal."""
        if seen is None:
            seen = set()
        out = []
        for c in self._children():
            if id(c) not in seen:
                seen.add(id(c))
                out.extend(c._walk(seen))
                out.append(c)
        return out

    def _topo_order(self) -> List["DAGNode"]:
        """Topological order ending at self, cached on this root; the walk
        reruns only after a rebind() somewhere in the graph."""
        cached = self.__dict__.get("_topo_cache")
        if cached is not None and cached[0] == _struct_gen:
            return cached[1]
        order = self._walk() + [self]
        self._topo_cache = (_struct_gen, order)
        return order

    def rebind(self, *args, **kwargs) -> "DAGNode":
        """Replace this node's bound arguments in place. Invalidates every
        cached topological order (structure may have changed)."""
        self._bound_args = args
        self._bound_kwargs = kwargs
        _bump_struct_gen()
        return self

    # -- execution ------------------------------------------------------------

    def execute(self, *input_args, **input_kwargs):
        """Run the DAG; returns ObjectRef(s) for the root node
        (ref: DAGNode.execute)."""
        cache: Dict[int, Any] = {}
        for node in self._topo_order():
            cache[id(node)] = node._execute_impl(
                lambda v: _resolve(v, cache), input_args, input_kwargs)
        return cache[id(self)]

    def experimental_compile(self, *, resolve_timeout: Optional[float] = 60.0):
        """Compile this bound DAG into a CompiledDAG driving standing
        channels — see dag/compiled.py."""
        from ray_tpu.dag.compiled import CompiledDAG

        return CompiledDAG(self, resolve_timeout=resolve_timeout)

    def _execute_impl(self, resolve, input_args, input_kwargs):
        raise NotImplementedError

    def _resolved_args(self, resolve):
        args = tuple(resolve(a) for a in self._bound_args)
        kwargs = {k: resolve(v) for k, v in self._bound_kwargs.items()}
        return args, kwargs


def _resolve(v, cache):
    if isinstance(v, DAGNode):
        return cache[id(v)]
    if isinstance(v, list):
        return [_resolve(x, cache) for x in v]
    if isinstance(v, tuple):
        return tuple(_resolve(x, cache) for x in v)
    if isinstance(v, dict):
        return {k: _resolve(x, cache) for k, x in v.items()}
    return v


class InputNode(DAGNode):
    """DAG input placeholder (ref: dag/input_node.py). Usable as a context
    manager for the `with InputNode() as inp:` authoring idiom."""

    def __init__(self):
        super().__init__((), {})

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def __getitem__(self, key) -> "InputAttributeNode":
        return InputAttributeNode(self, key)

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(
                f"InputNode has no attribute {name!r} (underscore names "
                "never become InputAttributeNodes)")
        return InputAttributeNode(self, name)

    def _execute_impl(self, resolve, input_args, input_kwargs):
        if input_args and input_kwargs:
            raise TypeError(
                "DAG execute() accepts positional OR keyword inputs, not both "
                "(an InputAttributeNode cannot address a mixed input)")
        if len(input_args) == 1 and not input_kwargs:
            return input_args[0]
        if input_kwargs and not input_args:
            return input_kwargs
        return input_args

    def __repr__(self):
        return "InputNode()"


class InputAttributeNode(DAGNode):
    """inp[0] / inp.key access on the DAG input."""

    def __init__(self, parent: InputNode, key):
        super().__init__((parent,), {})
        self._key = key

    def _execute_impl(self, resolve, input_args, input_kwargs):
        base = resolve(self._bound_args[0])
        if isinstance(base, dict):
            return base[self._key]
        if isinstance(self._key, int):
            return base[self._key]
        return getattr(base, self._key)

    def __repr__(self):
        return f"InputAttributeNode({self._key!r})"


class FunctionNode(DAGNode):
    """A bound remote-function invocation (ref: dag/function_node.py)."""

    def __init__(self, remote_fn, args, kwargs):
        super().__init__(args, kwargs)
        self._fn = remote_fn

    def _execute_impl(self, resolve, input_args, input_kwargs):
        args, kwargs = self._resolved_args(resolve)
        return self._fn.remote(*args, **kwargs)

    def __repr__(self):
        name = getattr(getattr(self._fn, "_fn", None), "__name__", "fn")
        return f"FunctionNode({name})"


class ClassNode(DAGNode):
    """A bound actor construction; instantiated once, reused across executes
    (ref: dag/class_node.py)."""

    def __init__(self, actor_cls, args, kwargs):
        super().__init__(args, kwargs)
        self._actor_cls = actor_cls
        self._handle = None
        self._external = False  # bind_actor: caller owns the lifecycle
        self._lock = threading.Lock()

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(
                f"ClassNode has no attribute {name!r} (underscore names "
                "never bind as actor methods)")
        return _UnboundMethod(self, name)

    def _get_handle(self, resolve):
        with self._lock:
            if self._handle is None:
                args, kwargs = self._resolved_args(resolve)
                self._handle = self._actor_cls.remote(*args, **kwargs)
            return self._handle

    def _execute_impl(self, resolve, input_args, input_kwargs):
        return self._get_handle(resolve)

    def __repr__(self):
        name = getattr(getattr(self._actor_cls, "_cls", None), "__name__", "Actor")
        return f"ClassNode({name})"


class _UnboundMethod:
    def __init__(self, class_node: ClassNode, name: str):
        self._class_node = class_node
        self._name = name

    def bind(self, *args, **kwargs) -> "ClassMethodNode":
        return ClassMethodNode(self._class_node, self._name, args, kwargs)


class ClassMethodNode(DAGNode):
    """actor_node.method.bind(...) — method call on a ClassNode's actor."""

    def __init__(self, class_node: ClassNode, method: str, args, kwargs):
        super().__init__(args, kwargs)
        self._class_node = class_node
        self._method = method

    def _children(self):
        return [self._class_node] + super()._children()

    def _execute_impl(self, resolve, input_args, input_kwargs):
        handle = resolve(self._class_node)
        args, kwargs = self._resolved_args(resolve)
        return getattr(handle, self._method).remote(*args, **kwargs)

    def __repr__(self):
        return f"ClassMethodNode(.{self._method})"


def bind_actor(handle) -> ClassNode:
    """Wrap an already-running actor's handle as a ClassNode, so a graph
    can route through externally-owned actors (e.g. serve replicas). The
    compiled layer never kills these at teardown."""
    node = ClassNode(None, (), {})
    node._handle = handle
    node._external = True
    return node


class MultiOutputNode(DAGNode):
    """Aggregates several leaves into one execute() result
    (ref: dag/output_node.py)."""

    def __init__(self, outputs: List[DAGNode]):
        super().__init__((list(outputs),), {})

    def _execute_impl(self, resolve, input_args, input_kwargs):
        return resolve(self._bound_args[0])

    def __repr__(self):
        return f"MultiOutputNode({len(self._bound_args[0])})"
