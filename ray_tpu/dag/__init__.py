"""Lazy task/actor DAGs: `.bind()` builds, `.execute()` runs — and
`.experimental_compile()` takes a static DAG out of the dispatch path.

Reference: python/ray/dag/ (DAGNode at dag/dag_node.py:23, InputNode,
function_node.py, class_node.py; compiled graphs per the aDAG layer).
Used by Serve deployment graphs the same way the reference's
pre-compiled-graph era DAGs are; compiled graphs drive the LLM router's
stream-frame hop and the data executor's fixed operator chains.
"""

from ray_tpu.dag.dag_node import (ClassMethodNode, ClassNode, DAGNode,
                                  FunctionNode, InputAttributeNode, InputNode,
                                  MultiOutputNode, bind_actor)


def __getattr__(name):
    # compiled pulls in core.runtime; import lazily so `import ray_tpu.dag`
    # stays cheap for authoring-only users
    if name in ("CompiledDAG", "CompiledDAGRef"):
        from ray_tpu.dag import compiled

        return getattr(compiled, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "DAGNode", "FunctionNode", "ClassNode", "ClassMethodNode", "InputNode",
    "InputAttributeNode", "MultiOutputNode", "bind_actor", "CompiledDAG",
    "CompiledDAGRef",
]
