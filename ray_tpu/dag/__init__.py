"""Lazy task/actor DAGs: `.bind()` builds, `.execute()` runs.

Reference: python/ray/dag/ (DAGNode at dag/dag_node.py:23, InputNode,
function_node.py, class_node.py). Used by Serve deployment graphs the same
way the reference's pre-compiled-graph era DAGs are.
"""

from ray_tpu.dag.dag_node import (ClassMethodNode, ClassNode, DAGNode,
                                  FunctionNode, InputAttributeNode, InputNode,
                                  MultiOutputNode)

__all__ = [
    "DAGNode", "FunctionNode", "ClassNode", "ClassMethodNode", "InputNode",
    "InputAttributeNode", "MultiOutputNode",
]
