"""Placement groups: gang reservation of resource bundles.

Reference: python/ray/util/placement_group.py:34,139. TPU-specific: a bundle
that requests {"TPU": n} is a slice-gang building block — STRICT_SPREAD over
hosts of one slice reserves the whole ICI domain for an SPMD job
(SURVEY.md §7 "slice-aware gang scheduling").
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu.core.common import ResourceSet
from ray_tpu.core.ids import PlacementGroupID
from ray_tpu.core import runtime as rt


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]]):
        self.id = pg_id
        self.bundle_specs = bundles

    def ready(self, timeout: float = 30.0) -> bool:
        r = rt.get_runtime().gcs_call("wait_placement_group", pg_id=self.id,
                                      wait_timeout=timeout,
                                      rpc_timeout=timeout + 10.0,
                                      clamp_attempt=False)  # long-poll
        return bool(r.get("ok"))

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        return self.ready(timeout_seconds)

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)

    def table(self) -> Optional[dict]:
        return rt.get_runtime().gcs_call("get_placement_group", pg_id=self.id)

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundle_specs))


def placement_group(bundles: List[Dict[str, float]], strategy: str = "PACK",
                    name: str = "") -> PlacementGroup:
    if strategy not in ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD"):
        raise ValueError(f"unknown placement strategy {strategy!r}")
    if not bundles:
        raise ValueError("placement group needs at least one bundle")
    pg_id = PlacementGroupID.from_random()
    rt.get_runtime().gcs_call(
        "create_placement_group", pg_id=pg_id,
        bundles=[ResourceSet({k: float(v) for k, v in b.items()}) for b in bundles],
        strategy=strategy, name=name)
    return PlacementGroup(pg_id, bundles)


def remove_placement_group(pg: PlacementGroup) -> None:
    rt.get_runtime().gcs_call("remove_placement_group", pg_id=pg.id)
