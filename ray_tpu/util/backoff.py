"""Exponential backoff with full jitter, deadline-capped.

Reference: the AWS architecture-blog "full jitter" result — for N
contending retriers, sleeping ``uniform(0, min(cap, base * 2**attempt))``
minimizes total work AND completion time versus equal or decorrelated
jitter. Fixed-interval retry loops (the 0.5s sleeps this replaces in the
nodelet's durable GCS report loop and the driver's GCS reconnect)
synchronize retriers into thundering herds against a just-restarted GCS;
jittered exponential spreads them out while still probing fast at first.
"""

from __future__ import annotations

import random
import time
from typing import Iterator, Optional


class Backoff:
    """One retry loop's backoff state.

    >>> bo = Backoff(base_s=0.05, cap_s=2.0, deadline_s=time.time() + 30)
    >>> while not attempt():
    ...     if not bo.sleep():
    ...         raise TimeoutError("deadline exhausted")
    """

    def __init__(self, base_s: float = 0.05, cap_s: float = 5.0,
                 factor: float = 2.0, deadline_s: Optional[float] = None,
                 rng: Optional[random.Random] = None):
        self.base_s = base_s
        self.cap_s = cap_s
        self.factor = factor
        self.deadline_s = deadline_s    # absolute time.time() deadline
        self.attempt = 0
        self._rng = rng or random

    def next_delay(self) -> float:
        """The next sleep: full jitter over the exponential envelope,
        never sleeping past the deadline."""
        # clamp the exponent: factor ** attempt overflows float for
        # long-lived loops (thousands of attempts), and 64 doublings
        # already exceed any sane cap
        envelope = min(self.cap_s,
                       self.base_s * (self.factor ** min(self.attempt, 64)))
        self.attempt += 1
        delay = self._rng.uniform(0.0, envelope)
        if self.deadline_s is not None:
            delay = min(delay, max(self.deadline_s - time.time(), 0.0))
        return delay

    def expired(self) -> bool:
        return self.deadline_s is not None and time.time() >= self.deadline_s

    def sleep(self) -> bool:
        """Blocking sleep; False once the deadline has passed (callers
        stop retrying). Async loops use ``asyncio.sleep(bo.next_delay())``
        with an explicit ``bo.expired()`` check instead."""
        if self.expired():
            return False
        time.sleep(self.next_delay())
        return True


def delays(base_s: float = 0.05, cap_s: float = 5.0, factor: float = 2.0,
           deadline_s: Optional[float] = None,
           rng: Optional[random.Random] = None) -> Iterator[float]:
    """Generator form: yields jittered delays until the deadline passes
    (infinite when no deadline — pair with an attempt cap)."""
    bo = Backoff(base_s, cap_s, factor, deadline_s, rng)
    while not bo.expired():
        yield bo.next_delay()
