"""User-defined metrics: Counter / Gauge / Histogram.

Reference: python/ray/util/metrics.py:150,215,290 — metrics flow to the
node agent and Prometheus. Here they aggregate in the GCS KV (namespace
"metrics"); `ray_tpu.cli status`/state API expose them, and
`prometheus_text()` renders the exposition format for scraping.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional, Tuple

from ray_tpu.core import runtime as rt


class _Metric:
    def __init__(self, name: str, description: str = "",
                 tag_keys: Tuple[str, ...] = ()):
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}
        self._lock = threading.Lock()
        self._values: Dict[Tuple, float] = {}
        self._counts: Dict[Tuple, int] = {}

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _key(self, tags: Optional[Dict[str, str]]) -> Tuple:
        merged = dict(self._default_tags)
        if tags:
            merged.update(tags)
        return tuple(sorted(merged.items()))

    def _flush(self, kind: str):
        runtime = rt.current_runtime_or_none()
        if runtime is None:
            return
        with self._lock:
            payload = {
                "kind": kind, "description": self.description,
                "series": [{"tags": dict(k), "value": v,
                            "count": self._counts.get(k, 0)}
                           for k, v in self._values.items()],
                "ts": time.time(),
            }
        try:
            runtime.kv_put("metrics", self.name.encode(),
                           json.dumps(payload).encode())
        except Exception:
            pass


class Counter(_Metric):
    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        k = self._key(tags)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + value
            self._counts[k] = self._counts.get(k, 0) + 1
        self._flush("counter")


class Gauge(_Metric):
    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        k = self._key(tags)
        with self._lock:
            self._values[k] = value
        self._flush("gauge")


class Histogram(_Metric):
    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[List[float]] = None,
                 tag_keys: Tuple[str, ...] = ()):
        super().__init__(name, description, tag_keys)
        self.boundaries = boundaries or [0.01, 0.05, 0.1, 0.5, 1, 5, 10]
        self._sums: Dict[Tuple, float] = {}
        self._buckets: Dict[Tuple, List[int]] = {}

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None):
        k = self._key(tags)
        with self._lock:
            self._sums[k] = self._sums.get(k, 0.0) + value
            self._counts[k] = self._counts.get(k, 0) + 1
            b = self._buckets.setdefault(k, [0] * (len(self.boundaries) + 1))
            for i, bound in enumerate(self.boundaries):
                if value <= bound:
                    b[i] += 1
                    break
            else:
                b[-1] += 1
            self._values[k] = self._sums[k] / self._counts[k]  # mean
        self._flush("histogram")


def render_prometheus(name: str, data: dict) -> List[str]:
    """Exposition lines for one metric's KV payload (shared by
    prometheus_text and the dashboard /metrics endpoint)."""
    lines = []
    if data.get("description"):
        lines.append(f"# HELP {name} {data['description']}")
    lines.append(f"# TYPE {name} {data.get('kind', 'gauge')}")
    for s in data.get("series", []):
        tags = ",".join(f'{k}="{v}"' for k, v in sorted(s["tags"].items()))
        label = f"{{{tags}}}" if tags else ""
        lines.append(f"{name}{label} {s['value']}")
    return lines


def prometheus_text() -> str:
    """Render all reported metrics in Prometheus exposition format
    (ref: metrics_agent.py Prometheus export)."""
    runtime = rt.get_runtime()
    lines = []
    for key in runtime.gcs_call("kv_keys", ns="metrics"):
        raw = runtime.kv_get("metrics", key)
        if raw is None:
            continue
        lines.extend(render_prometheus(key.decode(), json.loads(raw)))
    return "\n".join(lines) + "\n"
