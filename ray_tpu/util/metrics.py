"""User-defined metrics: Counter / Gauge / Histogram.

Reference: python/ray/util/metrics.py:150,215,290 + metrics_agent.py —
recording is a local lock + dict update with ZERO synchronous RPCs; the
per-process TelemetryAgent (ray_tpu/observability/agent.py) collects the
accumulated deltas and ships them to the GCS in one batched report per
`telemetry_report_interval_s`. The GCS merges deltas across processes
into KV namespace "metrics" (merge_payload below: counters sum, gauges
last-write, histograms add sum/count/buckets), so `ray_tpu.cli status`,
the state API, the dashboard /metrics endpoint, and `prometheus_text()`
all read one cluster-wide view. Histograms keep per-series buckets and
render valid Prometheus `_bucket{le=...}`/`_sum`/`_count` exposition
with a `+Inf` bound; `quantile(q)` estimates percentiles from them.

Metric objects are tracked by weak reference — hold the instrument for
as long as you record into it (module/engine-level, like the reference's
instruments); deltas pending on a garbage-collected metric are lost.
"""

from __future__ import annotations

import bisect
import json
import threading
import time
import weakref
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core import runtime as rt

_registry_lock = threading.Lock()
_registry: "weakref.WeakSet[_Metric]" = weakref.WeakSet()


class _Metric:
    kind = "gauge"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Tuple[str, ...] = ()):
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}
        self._lock = threading.Lock()
        self._values: Dict[Tuple, float] = {}
        self._counts: Dict[Tuple, int] = {}
        # un-reported deltas, swapped out by the TelemetryAgent
        self._pending: Dict[Tuple, Dict[str, Any]] = {}
        with _registry_lock:
            _registry.add(self)

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _key(self, tags: Optional[Dict[str, str]]) -> Tuple:
        merged = dict(self._default_tags)
        if tags:
            merged.update(tags)
        return tuple(sorted(merged.items()))

    def _collect(self) -> Optional[dict]:
        """Swap out pending deltas as one report payload (agent-side)."""
        with self._lock:
            if not self._pending:
                return None
            pending, self._pending = self._pending, {}
        return {"name": self.name, "kind": self.kind,
                "description": self.description,
                "series": [dict(d, tags=dict(k)) for k, d in pending.items()]}


class Counter(_Metric):
    kind = "counter"

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        k = self._key(tags)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + value
            self._counts[k] = self._counts.get(k, 0) + 1
            d = self._pending.setdefault(k, {"value": 0.0, "count": 0})
            d["value"] += value
            d["count"] += 1


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        k = self._key(tags)
        with self._lock:
            self._values[k] = value
            self._pending[k] = {"value": value}


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[List[float]] = None,
                 tag_keys: Tuple[str, ...] = ()):
        super().__init__(name, description, tag_keys)
        self.boundaries = sorted(boundaries or [0.01, 0.05, 0.1, 0.5, 1, 5, 10])
        self._sums: Dict[Tuple, float] = {}
        self._buckets: Dict[Tuple, List[int]] = {}

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None):
        k = self._key(tags)
        # first bound >= value == Prometheus `value <= le`; past-the-end
        # lands in the overflow (+Inf) slot
        idx = bisect.bisect_left(self.boundaries, value)
        with self._lock:
            self._sums[k] = self._sums.get(k, 0.0) + value
            self._counts[k] = self._counts.get(k, 0) + 1
            b = self._buckets.setdefault(k, [0] * (len(self.boundaries) + 1))
            b[idx] += 1
            self._values[k] = self._sums[k] / self._counts[k]  # mean
            d = self._pending.setdefault(
                k, {"sum": 0.0, "count": 0,
                    "buckets": [0] * (len(self.boundaries) + 1)})
            d["sum"] += value
            d["count"] += 1
            d["buckets"][idx] += 1

    def _collect(self) -> Optional[dict]:
        p = super()._collect()
        if p:
            p["boundaries"] = self.boundaries
        return p

    def quantile(self, q: float,
                 tags: Optional[Dict[str, str]] = None) -> Optional[float]:
        """Estimate the q-th quantile (0..1) from THIS process's buckets;
        pass tags to restrict to one series, omit to aggregate all. For
        the cluster-wide estimate use the merged GCS payload with
        quantile_from_buckets()."""
        with self._lock:
            if tags is None:
                rows = list(self._buckets.values())
            else:
                row = self._buckets.get(self._key(tags))
                rows = [row] if row else []
            if not rows:
                return None
            agg = [sum(col) for col in zip(*rows)]
        return quantile_from_buckets(self.boundaries, agg, q)


def quantile_from_buckets(boundaries: List[float], bucket_counts: List[int],
                          q: float) -> Optional[float]:
    """histogram_quantile: walk cumulative counts to the target rank,
    linear-interpolate within the containing bucket. The +Inf bucket
    clamps to the highest finite bound (as Prometheus does)."""
    total = sum(bucket_counts)
    if total <= 0 or not boundaries:
        return None
    rank = max(0.0, min(1.0, q)) * total
    cum = 0
    for i, c in enumerate(bucket_counts):
        cum += c
        if cum >= rank and c > 0:
            if i >= len(boundaries):
                return float(boundaries[-1])
            lo = boundaries[i - 1] if i >= 1 else 0.0
            frac = (rank - (cum - c)) / c
            return lo + (boundaries[i] - lo) * frac
    return float(boundaries[-1])


def collect_deltas() -> List[dict]:
    """Drain pending deltas from every live metric (TelemetryAgent)."""
    with _registry_lock:
        metrics = list(_registry)
    out = []
    for m in metrics:
        p = m._collect()
        if p:
            out.append(p)
    return out


def merge_payload(base: Optional[dict], delta: dict) -> dict:
    """Merge one delta payload into the stored KV payload (GCS-side):
    counter series sum value/count, gauges take the last write,
    histograms add sum/count/bucket-wise (`value` kept as the mean so
    pre-batching readers of the payload still work)."""
    kind = delta.get("kind", "gauge")
    if base is None or base.get("kind") != kind:
        base = {"kind": kind, "description": delta.get("description", ""),
                "series": []}
    if delta.get("description"):
        base["description"] = delta["description"]
    if delta.get("boundaries"):
        base["boundaries"] = delta["boundaries"]
    index = {tuple(sorted(s.get("tags", {}).items())): s
             for s in base["series"]}
    for s in delta.get("series", []):
        key = tuple(sorted(s.get("tags", {}).items()))
        cur = index.get(key)
        if cur is None:
            cur = {"tags": dict(s.get("tags", {})), "value": 0.0, "count": 0}
            if kind == "histogram":
                cur["sum"] = 0.0
                cur["buckets"] = []
            base["series"].append(cur)
            index[key] = cur
        if kind == "counter":
            cur["value"] += s.get("value", 0.0)
            cur["count"] += s.get("count", 0)
        elif kind == "histogram":
            cur["sum"] += s.get("sum", 0.0)
            cur["count"] += s.get("count", 0)
            db = s.get("buckets", [])
            if len(cur["buckets"]) < len(db):
                cur["buckets"] += [0] * (len(db) - len(cur["buckets"]))
            for i, c in enumerate(db):
                cur["buckets"][i] += c
            cur["value"] = cur["sum"] / cur["count"] if cur["count"] else 0.0
        else:  # gauge: last write wins
            cur["value"] = s.get("value", 0.0)
    base["ts"] = time.time()
    return base


def _labels(tags: Dict[str, str],
            extra: Optional[Tuple[str, str]] = None) -> str:
    items = sorted(tags.items())
    if extra:
        items.append(extra)
    if not items:
        return ""
    return "{" + ",".join(f'{k}="{v}"' for k, v in items) + "}"


def render_prometheus(name: str, data: dict) -> List[str]:
    """Exposition lines for one metric's KV payload (shared by
    prometheus_text and the dashboard /metrics endpoint). Histograms
    emit conformant cumulative `_bucket{le=...}` series ending at +Inf
    plus `_sum`/`_count`."""
    lines = []
    kind = data.get("kind", "gauge")
    if data.get("description"):
        lines.append(f"# HELP {name} {data['description']}")
    lines.append(f"# TYPE {name} {kind}")
    bounds = data.get("boundaries", [])
    for s in data.get("series", []):
        tags = s.get("tags", {})
        if kind == "histogram" and s.get("buckets"):
            cum = 0
            for i, c in enumerate(s["buckets"]):
                cum += c
                le = ("%g" % bounds[i]) if i < len(bounds) else "+Inf"
                lines.append(f'{name}_bucket{_labels(tags, ("le", le))} {cum}')
            lines.append(f"{name}_sum{_labels(tags)} {s.get('sum', 0.0)}")
            lines.append(f"{name}_count{_labels(tags)} {s.get('count', 0)}")
        else:
            lines.append(f"{name}{_labels(tags)} {s['value']}")
    return lines


def prometheus_text() -> str:
    """Render all reported metrics in Prometheus exposition format
    (ref: metrics_agent.py Prometheus export). Flushes this process's
    TelemetryAgent first so just-recorded values are visible
    (read-your-writes)."""
    runtime = rt.get_runtime()
    agent = getattr(runtime, "telemetry", None)
    if agent is not None:
        agent.flush(wait=True)
    lines = []
    for key in sorted(runtime.gcs_call("kv_keys", ns="metrics")):
        raw = runtime.kv_get("metrics", key)
        if raw is None:
            continue
        lines.extend(render_prometheus(key.decode(), json.loads(raw)))
    return "\n".join(lines) + "\n"
