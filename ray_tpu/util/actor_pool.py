"""ActorPool: work distribution over a fixed actor fleet.

Reference: python/ray/util/actor_pool.py — `get_next` returns results in
SUBMISSION order (:241), `get_next_unordered` in completion order (:282);
`map`/`map_unordered` stream over each. Indices are assigned at dispatch
time and pending submits drain FIFO, so dispatch order == submit order
and the ordered cursor always points at a dispatched task.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List


class ActorPool:
    def __init__(self, actors: List[Any]):
        import ray_tpu

        self._ray = ray_tpu
        self._idle = list(actors)
        self._future_to_actor = {}   # ref -> (submission index, actor)
        self._index_to_future = {}   # submission index -> ref
        self._next_task_index = 0
        self._next_return_index = 0  # ordered-get cursor
        self._pending = []           # (fn, value) waiting for an idle actor

    def submit(self, fn: Callable, value: Any) -> None:
        if self._idle:
            actor = self._idle.pop()
            ref = fn(actor, value)
            self._future_to_actor[ref] = (self._next_task_index, actor)
            self._index_to_future[self._next_task_index] = ref
            self._next_task_index += 1
        else:
            self._pending.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._future_to_actor) or bool(self._pending)

    def has_free(self) -> bool:
        """True when an actor is idle (ref: actor_pool.py has_free)."""
        return bool(self._idle) and not self._pending

    def push(self, actor: Any) -> None:
        """Grow the pool with an idle actor (ref: actor_pool.py push)."""
        self._return_actor(actor)

    def pop_idle(self) -> Any:
        """Remove and return an idle actor, or None (ref: pop_idle)."""
        return self._idle.pop() if self._idle else None

    def _return_actor(self, actor: Any) -> None:
        self._idle.append(actor)
        while self._pending and self._idle:
            fn, value = self._pending.pop(0)
            self.submit(fn, value)

    def get_next(self, timeout=None):
        """Next result in SUBMISSION order (ref: actor_pool.py:241); a
        later task finishing first waits its turn. TimeoutError if the
        next-in-order result isn't ready in `timeout` seconds."""
        if not self.has_next():
            raise StopIteration("no pending results")
        i = self._next_return_index
        # skip indices already consumed by get_next_unordered
        while i < self._next_task_index and i not in self._index_to_future:
            i += 1
        self._next_return_index = i
        ref = self._index_to_future.get(i)
        if ref is None:
            # every dispatched task was consumed unordered; only pending
            # (undispatched) submits remain — impossible with an idle
            # actor, so this means the pool was built with zero actors
            raise RuntimeError("ActorPool has queued work but no actors")
        ready, _ = self._ray.wait([ref], num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("next ordered result not ready in time")
        del self._index_to_future[i]
        self._next_return_index = i + 1
        _, actor = self._future_to_actor.pop(ref)
        self._return_actor(actor)
        return self._ray.get(ref)

    def get_next_unordered(self, timeout=None):
        """Next result in COMPLETION order (ref: actor_pool.py:282) —
        the fastest task wins, block order is the caller's problem."""
        if not self.has_next():
            raise StopIteration("no pending results")
        ready, _ = self._ray.wait(list(self._future_to_actor),
                                  num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("no result ready in time")
        ref = ready[0]
        index, actor = self._future_to_actor.pop(ref)
        del self._index_to_future[index]
        self._return_actor(actor)
        return self._ray.get(ref)

    def map(self, fn: Callable, values: Iterable[Any]):
        """Results in submission order (ref: actor_pool.py map)."""
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable[Any]):
        """Results in completion order (ref: map_unordered)."""
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()
