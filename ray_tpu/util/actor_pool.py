"""ActorPool: round-robin work distribution over a fixed actor fleet.

Reference: python/ray/util/actor_pool.py.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List


class ActorPool:
    def __init__(self, actors: List[Any]):
        import ray_tpu

        self._ray = ray_tpu
        self._idle = list(actors)
        self._future_to_actor = {}
        self._pending = []           # (fn, value) waiting for an idle actor
        self._result_queue = []

    def submit(self, fn: Callable, value: Any) -> None:
        if self._idle:
            actor = self._idle.pop()
            ref = fn(actor, value)
            self._future_to_actor[ref] = actor
        else:
            self._pending.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._future_to_actor) or bool(self._pending)

    def get_next(self, timeout=None):
        if not self.has_next():
            raise StopIteration("no pending results")
        ready, _ = self._ray.wait(list(self._future_to_actor), num_returns=1,
                                  timeout=timeout)
        if not ready:
            raise TimeoutError("no result ready in time")
        ref = ready[0]
        actor = self._future_to_actor.pop(ref)
        self._idle.append(actor)
        while self._pending and self._idle:
            fn, value = self._pending.pop(0)
            a = self._idle.pop()
            self._future_to_actor[fn(a, value)] = a
        return self._ray.get(ref)

    def map(self, fn: Callable, values: Iterable[Any]):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()
