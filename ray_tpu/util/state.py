"""State/observability API.

Reference: python/ray/util/state/api.py:109 (StateApiClient; list_actors:782,
list_tasks:1009) backed by the GCS task/actor/node tables.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu.core import runtime as rt


def list_nodes() -> List[dict]:
    out = []
    for n in rt.get_runtime().gcs_call("get_nodes"):
        out.append({"node_id": n.node_id.hex(), "alive": n.alive,
                    "resources": n.resources_total.quantities,
                    "labels": n.labels, "address": n.nodelet_addr})
    return out


def list_actors(state: Optional[str] = None) -> List[dict]:
    out = []
    for a in rt.get_runtime().gcs_call("list_actors"):
        if state and a["state"] != state:
            continue
        out.append({"actor_id": a["actor_id"].hex(), "state": a["state"],
                    "class_name": a["class_name"], "name": a["name"],
                    "namespace": a["namespace"],
                    "num_restarts": a["num_restarts"],
                    "address": a["address"]})
    return out


def list_tasks(limit: int = 1000) -> List[dict]:
    return rt.get_runtime().gcs_call("list_task_events", limit=limit)


def list_jobs() -> List[dict]:
    out = []
    for j in rt.get_runtime().gcs_call("list_jobs"):
        out.append({"job_id": j["job_id"].hex(), "driver": j["driver"],
                    "start": j["start"], "end": j["end"], "meta": j["meta"]})
    return out


def edge_stats() -> Dict[str, dict]:
    """Measured per-edge transfer model, keyed "src_node->dst_node":
    EWMA latency/bandwidth plus totals, learned from object-store pulls
    and collective transport rounds (ray_tpu.observability.edges)."""
    from ray_tpu.observability.edges import edge_stats as _edge_stats

    return _edge_stats()


def list_placement_groups() -> List[dict]:
    # round-1: PGs are queried per-id; a GCS listing lands with the
    # observability milestone
    return []


def summarize_tasks(limit: int = 5000) -> Dict[str, Dict[str, int]]:
    """ref: `ray summary tasks` (state_cli.py)."""
    summary: Dict[str, Dict[str, int]] = {}
    for ev in list_tasks(limit):
        name = ev.get("name", "?")
        state = ev.get("state", "?")
        summary.setdefault(name, {})
        summary[name][state] = summary[name].get(state, 0) + 1
    return summary


def cluster_summary() -> dict:
    """ref: `ray status` output."""
    import ray_tpu

    nodes = list_nodes()
    actors = list_actors()
    return {
        "nodes_alive": sum(1 for n in nodes if n["alive"]),
        "nodes_dead": sum(1 for n in nodes if not n["alive"]),
        "total_resources": ray_tpu.cluster_resources(),
        "available_resources": ray_tpu.available_resources(),
        "actors_alive": sum(1 for a in actors if a["state"] == "ALIVE"),
        "actors_total": len(actors),
    }


def memory_summary() -> dict:
    """Owner-side refcount stats (ref: `ray memory` scripts.py:1900)."""
    runtime = rt.get_runtime()
    stats = runtime.refs.stats()
    stats["store_bytes_in_use"] = runtime.store.bytes_in_use()
    stats["store_capacity"] = runtime.store.capacity()
    stats["store_objects"] = runtime.store.num_objects()
    stats["store_evictions"] = runtime.store.num_evictions()
    return stats
