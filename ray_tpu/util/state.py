"""State/observability API.

Reference: python/ray/util/state/api.py:109 (StateApiClient; list_actors:782,
list_tasks:1009) backed by the GCS task/actor/node tables.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu.core import runtime as rt


def list_nodes() -> List[dict]:
    out = []
    for n in rt.get_runtime().gcs_call("get_nodes"):
        out.append({"node_id": n.node_id.hex(), "alive": n.alive,
                    "resources": n.resources_total.quantities,
                    "labels": n.labels, "address": n.nodelet_addr})
    return out


def list_actors(state: Optional[str] = None) -> List[dict]:
    out = []
    for a in rt.get_runtime().gcs_call("list_actors"):
        if state and a["state"] != state:
            continue
        out.append({"actor_id": a["actor_id"].hex(), "state": a["state"],
                    "class_name": a["class_name"], "name": a["name"],
                    "namespace": a["namespace"],
                    "num_restarts": a["num_restarts"],
                    "address": a["address"]})
    return out


def list_tasks(limit: int = 1000) -> List[dict]:
    return rt.get_runtime().gcs_call("list_task_events", limit=limit)


def list_jobs() -> List[dict]:
    out = []
    for j in rt.get_runtime().gcs_call("list_jobs"):
        out.append({"job_id": j["job_id"].hex(), "driver": j["driver"],
                    "start": j["start"], "end": j["end"], "meta": j["meta"]})
    return out


def edge_stats() -> Dict[str, dict]:
    """Measured per-edge transfer model, keyed "src_node->dst_node":
    EWMA latency/bandwidth plus totals, learned from object-store pulls
    and collective transport rounds (ray_tpu.observability.edges)."""
    from ray_tpu.observability.edges import edge_stats as _edge_stats

    return _edge_stats()


def list_placement_groups() -> List[dict]:
    """ref: `ray list placement-groups` — the GCS PG table in the same
    view shape as PlacementGroup.table()."""
    out = []
    for pg in rt.get_runtime().gcs_call("list_placement_groups"):
        out.append({"pg_id": pg["pg_id"].hex(), "state": pg["state"],
                    "strategy": pg["strategy"], "name": pg["name"],
                    "bundles": [{"index": b["index"],
                                 "node_id": (b["node_id"].hex()
                                             if b["node_id"] is not None
                                             else None),
                                 "resources": b["resources"]}
                                for b in pg["bundles"]]})
    return out


def health_report() -> dict:
    """The health plane's view (observability/health.py): every
    registered progress beacon with its freshness, recent stall /
    straggler events, telemetry drop counters, node liveness."""
    return rt.get_runtime().gcs_call("health_report")


def summarize_tasks(limit: int = 5000) -> Dict[str, Dict[str, int]]:
    """ref: `ray summary tasks` (state_cli.py)."""
    summary: Dict[str, Dict[str, int]] = {}
    for ev in list_tasks(limit):
        name = ev.get("name", "?")
        state = ev.get("state", "?")
        summary.setdefault(name, {})
        summary[name][state] = summary[name].get(state, 0) + 1
    return summary


def cluster_summary() -> dict:
    """ref: `ray status` output."""
    import ray_tpu

    nodes = list_nodes()
    actors = list_actors()
    return {
        "nodes_alive": sum(1 for n in nodes if n["alive"]),
        "nodes_dead": sum(1 for n in nodes if not n["alive"]),
        "total_resources": ray_tpu.cluster_resources(),
        "available_resources": ray_tpu.available_resources(),
        "actors_alive": sum(1 for a in actors if a["state"] == "ALIVE"),
        "actors_total": len(actors),
        # telemetry-plane integrity: nonzero means the observability
        # story has holes (events dropped at the buffer, or whole
        # reports that never reached the GCS)
        "task_events_dropped": _metric_total("ray_tpu_task_events_dropped"),
        "telemetry_reports_dropped": _metric_total(
            "ray_tpu_telemetry_reports_dropped"),
    }


def _metric_total(name: str) -> float:
    """Cluster-wide total of one merged counter from GCS KV
    ns="metrics" (0.0 when never incremented)."""
    import json

    raw = rt.get_runtime().gcs_call("kv_get", ns="metrics",
                                    key=name.encode())
    if not raw:
        return 0.0
    try:
        payload = json.loads(raw)
        return sum(s.get("value", 0.0) for s in payload.get("series", []))
    except Exception:
        return 0.0


def memory_report(top_n: int = 20) -> dict:
    """Cluster memory attribution (observability/memory.py): per-
    subsystem bytes, top holders with owner/pins/temperature, per-node
    store coverage, the spill-candidate list (unpinned AND cold) and
    leak suspects (pinned with no live owner ref past
    `memory_leak_suspect_s`)."""
    return rt.get_runtime().gcs_call("memory_report", top_n=top_n)


def list_objects(limit: int = 100) -> List[dict]:
    """Attributed resident objects, largest first (ref: `ray memory`'s
    object table) — from the same aggregated view as memory_report()."""
    rep = memory_report(top_n=limit)
    return rep.get("top_holders", [])


def memory_summary() -> dict:
    """Owner-side refcount stats (ref: `ray memory` scripts.py:1900)
    plus spilling-readiness gauges: local store occupancy / pinned bytes
    / pin-count distribution, and the same per node from the stats every
    nodelet agent pushes to GCS KV ns="node_stats"."""
    runtime = rt.get_runtime()
    stats = runtime.refs.stats()
    stats["store_bytes_in_use"] = runtime.store.bytes_in_use()
    stats["store_capacity"] = runtime.store.capacity()
    stats["store_objects"] = runtime.store.num_objects()
    stats["store_evictions"] = runtime.store.num_evictions()
    stats.update({f"store_{k}": v
                  for k, v in runtime.store.pin_summary().items()})
    # per-node store view (spilling readiness across the cluster)
    import json

    nodes: Dict[str, dict] = {}
    try:
        for key in runtime.gcs_call("kv_keys", ns="node_stats"):
            raw = runtime.gcs_call("kv_get", ns="node_stats", key=key)
            if not raw:
                continue
            try:
                s = json.loads(raw)
            except Exception:
                continue
            nodes[key.hex()[:12]] = {
                k: s.get(k) for k in
                ("store_bytes", "store_capacity", "store_occupancy",
                 "store_pinned_bytes", "store_pinned_objects",
                 "store_pin_count_distribution", "spilled_bytes",
                 "spilled_objects", "spilled_then_dropped",
                 "restored_objects", "spill_bytes_total",
                 "restore_bytes_total") if k in s}
    except Exception:
        pass
    stats["nodes"] = nodes
    return stats
