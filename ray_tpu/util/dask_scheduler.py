"""Dask-on-ray_tpu scheduler: execute dask task graphs as remote tasks.

Reference: python/ray/util/dask/ (ray_dask_get in scheduler.py — walks the
dask graph, submits one Ray task per dask task, passes ObjectRefs as
dependencies so the object store carries intermediates). The scheduler
implements dask's documented get(dsk, keys) protocol on plain dicts, so it
needs no dask import itself (dask is not in the TPU image; when present,
use `dask.compute(..., scheduler=ray_tpu_dask_get)`).

Graph spec (dask.core): dsk maps key -> computation, where a computation
is either a literal, a key reference, or a task tuple
(callable, *args) whose args may nest lists/tuples/subtasks.
"""

from __future__ import annotations

from typing import Any, Dict, Hashable, List

import ray_tpu

_REMOTE_EXEC = None


def _ishashable(x) -> bool:
    try:
        hash(x)
        return True
    except TypeError:
        return False


def _istask(x) -> bool:
    return isinstance(x, tuple) and bool(x) and callable(x[0])


def _execute_task(func, args):
    """Runs inside the worker. Dependency refs arrive nested inside the
    args list (only TOP-level task args auto-resolve, like the
    reference), so materialize them here via the borrower protocol;
    nested task tuples evaluate inline (dask semantics — nested tasks
    are not graph nodes)."""
    return func(*[_eval_inline(a) for a in args])


def _eval_inline(a):
    if isinstance(a, ray_tpu.ObjectRef):
        return ray_tpu.get(a)
    if _istask(a):
        return _execute_task(a[0], a[1:])
    if isinstance(a, list):
        return [_eval_inline(x) for x in a]
    if isinstance(a, tuple):
        return tuple(_eval_inline(x) for x in a)
    return a


def _remote_exec():
    global _REMOTE_EXEC
    if _REMOTE_EXEC is None:
        _REMOTE_EXEC = ray_tpu.remote(_execute_task)
    return _REMOTE_EXEC


def ray_tpu_dask_get(dsk: Dict[Hashable, Any], keys, **kwargs):
    """The dask scheduler entry point (ref: scheduler.py ray_dask_get).
    Topologically submits one remote task per graph node; dependencies
    flow as ObjectRefs resolved by the runtime, intermediates live in the
    object store. `keys` may be a single key or (nested) lists of keys,
    mirroring dask.get."""
    refs: Dict[Hashable, Any] = {}

    def submit(key):
        if key in refs:
            return refs[key]
        comp = dsk[key]
        refs[key] = _submit_computation(comp)
        return refs[key]

    def _resolve_arg(a):
        # a graph-key reference becomes that node's ObjectRef
        if _ishashable(a) and not _istask(a) and a in dsk:
            return submit(a)
        if _istask(a):
            # nested task: keep as data, evaluated inline in the worker,
            # but its key references must resolve first
            return (a[0],) + tuple(_resolve_arg(x) for x in a[1:])
        if isinstance(a, list):
            return [_resolve_arg(x) for x in a]
        return a

    def _submit_computation(comp):
        if _istask(comp):
            func, args = comp[0], [_resolve_arg(a) for a in comp[1:]]
            return _remote_exec().remote(func, args)
        if _ishashable(comp) and comp in dsk:
            return submit(comp)  # alias key
        return comp  # literal

    def _gather(ks):
        if isinstance(ks, list):
            return [_gather(k) for k in ks]
        ref = submit(ks)
        return ray_tpu.get(ref) if isinstance(ref, ray_tpu.ObjectRef) else ref

    if isinstance(keys, list):
        return [_gather(k) for k in keys]
    return _gather(keys)


# alias matching the reference's public name
ray_dask_get = ray_tpu_dask_get
