"""Host-level collective ops among actors/tasks — compatibility shim.

The implementation moved to the ``ray_tpu.collective`` package
(topology-aware backends: legacy ``gather`` coordinator, bandwidth-
optimal ``ring``, hierarchical ``hier``; async variants; member-failure
detection). This module re-exports the same surface the reference's
``python/ray/util/collective/collective.py`` offered so existing
callers keep working unchanged; new code should import
``ray_tpu.collective`` directly.

TPU-native position (SURVEY.md §5.8): *device* collectives live inside
jitted programs (psum/all_gather over ICI emitted by XLA — see
ray_tpu.parallel), so this surface only covers the HOST-side use case:
exchanging CPU arrays between actors (rollout fleets, data pipelines).
"""

from __future__ import annotations

from ray_tpu.collective import (CollectiveError, CollectiveTimeoutError,
                                allgather, allgather_async, allreduce,
                                allreduce_async, barrier, barrier_async,
                                broadcast, broadcast_async,
                                destroy_collective_group,
                                get_collective_group_size, get_rank,
                                init_collective_group, reducescatter,
                                reducescatter_async, transfer_stats)

__all__ = [
    "init_collective_group", "destroy_collective_group",
    "allreduce", "allgather", "broadcast", "reducescatter", "barrier",
    "allreduce_async", "allgather_async", "broadcast_async",
    "reducescatter_async", "barrier_async",
    "get_rank", "get_collective_group_size", "transfer_stats",
    "CollectiveError", "CollectiveTimeoutError",
]
