"""Host-level collective ops among actors/tasks.

Reference: python/ray/util/collective/collective.py (GroupManager:40,
init_collective_group:120, allreduce:258, broadcast:373, allgather:423,
reducescatter:472, barrier:298) with NCCL/Gloo backends.

TPU-native position (SURVEY.md §5.8): *device* collectives live inside
jitted programs (psum/all_gather over ICI emitted by XLA — see
ray_tpu.parallel), so this module only covers the reference's HOST-side
use case: exchanging CPU arrays between actors (rollout fleets, data
pipelines). Backend: a per-group coordinator actor doing gather+broadcast —
O(world) through the object store, no extra native deps.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu

#: Keyed by (calling actor id, group name), NOT group name alone: the
#: reference keys per-process because one actor == one process there —
#: with lane-packed fractional-CPU actors sharing a worker process,
#: per-process group state would let rank N's init clobber rank M's
#: (their allreduce then deadlocks waiting for ranks that can never
#: arrive — found by the suite's collective test once its members
#: became lane-packed).
_groups: Dict[tuple, "_GroupClient"] = {}


def _ctx() -> Optional[str]:
    try:
        return ray_tpu.get_runtime_context().get_actor_id()
    except Exception:
        return None


def _on_actor_teardown(actor_id_hex: str) -> None:
    """Lane actors die without their process dying: drop their group
    clients so a churning fleet cannot grow _groups unboundedly."""
    for key in [k for k in _groups if k[0] == actor_id_hex]:
        _groups.pop(key, None)


from ray_tpu.core.runtime import actor_teardown_hooks as _hooks  # noqa: E402

_hooks.append(_on_actor_teardown)


@ray_tpu.remote
class _Coordinator:
    def __init__(self, world_size: int):
        import asyncio

        self.world = world_size
        self.rounds: Dict[tuple, dict] = {}
        self.cv = asyncio.Condition()

    async def exchange(self, op: str, seq: int, rank: int, data):
        """All ranks call with their contribution; returns the combined
        result once everyone arrived."""
        import asyncio

        key = (op, seq)
        async with self.cv:
            slot = self.rounds.setdefault(key, {"parts": {}, "result": None})
            slot["parts"][rank] = data
            if len(slot["parts"]) == self.world:
                parts = [slot["parts"][r] for r in range(self.world)]
                if op == "allreduce_sum":
                    out = parts[0]
                    for p in parts[1:]:
                        out = out + p
                    slot["result"] = [out] * self.world
                elif op == "allgather":
                    slot["result"] = [list(parts)] * self.world
                elif op == "barrier":
                    slot["result"] = [True] * self.world
                elif op == "broadcast":
                    src = next(p for p in parts if p is not None)
                    slot["result"] = [src] * self.world
                elif op == "reducescatter":
                    total = parts[0]
                    for p in parts[1:]:
                        total = total + p
                    chunks = np.array_split(total, self.world)
                    slot["result"] = chunks
                else:
                    raise ValueError(op)
                self.cv.notify_all()
            else:
                while self.rounds[key]["result"] is None:
                    await self.cv.wait()
        result = self.rounds[key]["result"][rank]
        slot["parts"].pop(rank, None)
        if not slot["parts"]:
            self.rounds.pop(key, None)
        return result


class _GroupClient:
    def __init__(self, name: str, world_size: int, rank: int):
        self.name = name
        self.world = world_size
        self.rank = rank
        self.seq = 0
        actor_name = f"_collective_{name}"
        if rank == 0:
            try:
                self.coord = _Coordinator.options(
                    name=actor_name, max_concurrency=max(world_size * 2, 4),
                    num_cpus=0).remote(world_size)
            except ValueError:
                self.coord = ray_tpu.get_actor(actor_name)
        else:
            import time

            deadline = time.time() + 30
            while True:
                try:
                    self.coord = ray_tpu.get_actor(actor_name)
                    break
                except ValueError:
                    if time.time() > deadline:
                        raise
                    time.sleep(0.1)

    def _x(self, op: str, data):
        self.seq += 1
        return ray_tpu.get(self.coord.exchange.remote(op, self.seq,
                                                      self.rank, data))


def init_collective_group(world_size: int, rank: int,
                          group_name: str = "default") -> None:
    """ref: collective.py:120."""
    _groups[(_ctx(), group_name)] = _GroupClient(group_name, world_size,
                                                 rank)


def destroy_collective_group(group_name: str = "default") -> None:
    g = _groups.pop((_ctx(), group_name), None)
    if g and g.rank == 0:
        try:
            ray_tpu.kill(g.coord)
        except Exception:
            pass


def _group(name: str) -> _GroupClient:
    key = (_ctx(), name)
    g = _groups.get(key)
    if g is not None:
        return g
    # Helper threads an actor spawns itself start with a fresh context
    # (no actor id). If exactly ONE client for this group name lives in
    # the process, that use is unambiguous — honor it (the per-process
    # reference semantics). Multiple same-name clients (lane-packed
    # ranks) make a context-less call genuinely ambiguous.
    candidates = [g for (a, n), g in _groups.items() if n == name]
    if len(candidates) == 1:
        return candidates[0]
    if candidates:
        raise RuntimeError(
            f"collective group {name!r}: ambiguous caller — "
            f"{len(candidates)} lane-packed actors initialized this "
            "group in one process, and this call carries no actor "
            "context (e.g. a self-spawned thread). Call from an actor "
            "method, or propagate contextvars into the thread")
    raise RuntimeError(f"collective group {name!r} not initialized")


def allreduce(tensor: np.ndarray, group_name: str = "default") -> np.ndarray:
    """SUM allreduce (ref: collective.py:258)."""
    return np.asarray(_group(group_name)._x("allreduce_sum", np.asarray(tensor)))


def allgather(tensor: np.ndarray, group_name: str = "default") -> List[np.ndarray]:
    return _group(group_name)._x("allgather", np.asarray(tensor))


def broadcast(tensor: Optional[np.ndarray], src_rank: int = 0,
              group_name: str = "default") -> np.ndarray:
    g = _group(group_name)
    data = np.asarray(tensor) if g.rank == src_rank else None
    return np.asarray(g._x("broadcast", data))


def reducescatter(tensor: np.ndarray, group_name: str = "default") -> np.ndarray:
    return np.asarray(_group(group_name)._x("reducescatter", np.asarray(tensor)))


def barrier(group_name: str = "default") -> None:
    _group(group_name)._x("barrier", None)


def get_rank(group_name: str = "default") -> int:
    return _group(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _group(group_name).world
