"""At-most-once execution for non-idempotent RPC handlers.

A duplicated request frame (retry after a dropped response, or injected
duplication from the chaos plane) reaches the handler twice. For
idempotent handlers that's harmless; for actor creation / lease grants
it double-spends resources. The fix is the classic idempotency-token
dedupe: the *caller* mints a token stable across its retries, the
handler runs the side effect once per token and replays the recorded
result to every duplicate.

Two properties matter and are easy to get wrong:

- **Only successes are cached.** A failed attempt must NOT be replayed:
  the caller's retry carries the same token precisely because it wants
  the side effect attempted again (e.g. "no worker available" is a
  transient verdict, not a durable one). Failures evict the token.
- **In-flight duplicates coalesce.** The second delivery of a frame
  whose handler is still running must wait for — and share — the first
  attempt's outcome, not start a concurrent second side effect.
"""

from __future__ import annotations

import asyncio
from collections import OrderedDict
from typing import Any, Awaitable, Callable, Dict, Optional


class IdemCache:
    """Per-handler token → outcome cache (asyncio, single-loop).

    ``run(token, thunk)`` executes ``thunk()`` at most once per token:
    concurrent duplicates await the in-flight attempt, later duplicates
    replay the cached success. ``token=None`` bypasses dedupe entirely
    (callers that predate tokens keep their old semantics).
    """

    def __init__(self, capacity: int = 4096):
        self.capacity = capacity
        self._done: "OrderedDict[str, Any]" = OrderedDict()
        self._inflight: Dict[str, asyncio.Future] = {}
        self.hits = 0          # duplicates absorbed (replayed or joined)

    async def run(self, token: Optional[str],
                  thunk: Callable[[], Awaitable[Any]],
                  cache_if: Optional[Callable[[Any], bool]] = None) -> Any:
        """``cache_if``: predicate over the result deciding whether it is
        a *durable* success worth replaying. Handlers that report failure
        in-band (``{"ok": False, "retryable": True}``) must not have that
        verdict replayed to a stable-token retry — the retry exists to
        re-attempt the side effect — so they pass
        ``cache_if=lambda r: r.get("ok")``."""
        if token is None:
            return await thunk()
        if token in self._done:
            self.hits += 1
            self._done.move_to_end(token)
            return self._done[token]
        fut = self._inflight.get(token)
        if fut is not None:
            self.hits += 1
            # shield: a cancelled duplicate must not cancel the original
            return await asyncio.shield(fut)
        fut = asyncio.get_running_loop().create_future()
        self._inflight[token] = fut
        try:
            result = await thunk()
        except BaseException as e:
            # failure: evict so the caller's retry re-attempts the side
            # effect; joined duplicates see the same failure
            self._inflight.pop(token, None)
            if not fut.done():
                fut.set_exception(e)
                # consume it if nobody joined, else "exception was never
                # retrieved" is logged at gc time
                fut.exception()
            raise
        self._inflight.pop(token, None)
        if not fut.done():
            fut.set_result(result)
        if cache_if is None or cache_if(result):
            self._done[token] = result
            while len(self._done) > self.capacity:
                self._done.popitem(last=False)
        return result

    def forget(self, token: str) -> None:
        """Drop a recorded success (e.g. the created actor died and its
        id will be reused for a restart with a new token anyway)."""
        self._done.pop(token, None)

    def stats(self) -> dict:
        return {"done": len(self._done), "inflight": len(self._inflight),
                "hits": self.hits}
