"""joblib backend running batches on ray_tpu actors.

Reference: python/ray/util/joblib/ — `register_ray()` +
`with joblib.parallel_backend("ray_tpu"):` routes scikit-learn style
joblib.Parallel work onto the cluster.
"""

from __future__ import annotations

from joblib.parallel import ParallelBackendBase, register_parallel_backend

import ray_tpu


class RayTpuBackend(ParallelBackendBase):
    supports_timeout = True
    uses_threads = False
    supports_sharedmem = False

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._pool = None

    def configure(self, n_jobs=1, parallel=None, **backend_args):
        from ray_tpu.util.multiprocessing import Pool

        n_jobs = self.effective_n_jobs(n_jobs)
        self._pool = Pool(processes=n_jobs)
        self.parallel = parallel
        return n_jobs

    def effective_n_jobs(self, n_jobs):
        if n_jobs == 0:
            raise ValueError("n_jobs == 0 has no meaning")
        if n_jobs is None:
            n_jobs = 1
        if n_jobs < 0:
            if not ray_tpu.is_initialized():
                ray_tpu.init()
            total = int(ray_tpu.cluster_resources().get("CPU", 1))
            n_jobs = max(total + 1 + n_jobs, 1)
        return n_jobs

    def apply_async(self, func, callback=None):
        return self._pool.apply_async(func, callback=callback)

    def terminate(self):
        if self._pool is not None:
            self._pool.terminate()
            self._pool = None

    def abort_everything(self, ensure_ready=True):
        self.terminate()
        if ensure_ready:
            self.configure(n_jobs=self.parallel.n_jobs,
                           parallel=self.parallel)

    def get_nested_backend(self):
        from joblib._parallel_backends import SequentialBackend

        return SequentialBackend(nesting_level=self.nesting_level + 1), None


def register_ray() -> None:
    """ref: ray.util.joblib.register_ray."""
    register_parallel_backend("ray_tpu", RayTpuBackend)
