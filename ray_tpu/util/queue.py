"""Distributed FIFO queue (ref: python/ray/util/queue.py) — actor-backed."""

from __future__ import annotations

from typing import Any, List, Optional

import ray_tpu


@ray_tpu.remote
class _QueueActor:
    def __init__(self, maxsize: int = 0):
        import asyncio

        self.maxsize = maxsize
        self.q = asyncio.Queue(maxsize=maxsize)

    async def put(self, item) -> bool:
        await self.q.put(item)
        return True

    async def get(self, timeout: Optional[float] = None):
        import asyncio

        if timeout is None:
            return await self.q.get()
        return await asyncio.wait_for(self.q.get(), timeout)

    def qsize(self) -> int:
        return self.q.qsize()

    def empty(self) -> bool:
        return self.q.empty()


class Queue:
    def __init__(self, maxsize: int = 0, actor_options: Optional[dict] = None):
        opts = dict(actor_options or {"num_cpus": 0.1})
        opts["max_concurrency"] = 16
        self.actor = _QueueActor.options(**opts).remote(maxsize)

    def put(self, item, block: bool = True) -> None:
        ray_tpu.get(self.actor.put.remote(item))

    def put_async(self, item):
        return self.actor.put.remote(item)

    def get(self, block: bool = True, timeout: Optional[float] = None):
        return ray_tpu.get(self.actor.get.remote(timeout))

    def get_async(self, timeout: Optional[float] = None):
        return self.actor.get.remote(timeout)

    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote())

    def empty(self) -> bool:
        return ray_tpu.get(self.actor.empty.remote())

    def shutdown(self):
        ray_tpu.kill(self.actor)
