"""Distributed tracing: spans propagated through task/actor calls.

Reference: python/ray/util/tracing/tracing_helper.py — opt-in tracing
that wraps task/actor invocation in spans
(_inject_tracing_into_function:322, _inject_tracing_into_class:447) and
serializes the span context into task metadata
(_function_hydrate_span_args:195) so remote execution continues the
caller's trace.

TPU-shaped re-design: no OpenTelemetry SDK dependency (not in-image).
Spans are plain dicts {trace_id, span_id, parent_id, name, ts, dur, attrs}
riding the existing task-event channel to the GCS (task_event_buffer.h:199
analog), so one store serves task states AND spans, and `ray_tpu.timeline()`
/ the CLI export both as one Chrome trace. Context propagation is a
contextvar here + a `trace_ctx` field on TaskSpec there.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import time
from typing import Any, Dict, Optional

_ctx: contextvars.ContextVar[Optional[Dict[str, str]]] = \
    contextvars.ContextVar("ray_tpu_trace_ctx", default=None)

_enabled: Optional[bool] = None


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def is_enabled() -> bool:
    """Opt-in like the reference (`ray.init(_tracing_startup_hook=...)`):
    enable() in-process or RAY_TPU_TRACING=1 fleet-wide."""
    if _enabled is not None:
        return _enabled
    return os.environ.get("RAY_TPU_TRACING", "0") in ("1", "true")


def _new_id(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


def current_context() -> Optional[Dict[str, str]]:
    """The {trace_id, span_id} to stamp onto outgoing TaskSpecs."""
    return _ctx.get()


def _record(span: Dict[str, Any]) -> None:
    try:
        from ray_tpu import _rt

        rt = _rt.get_runtime()
    except Exception:
        return
    rt.record_span(span)


@contextlib.contextmanager
def span(name: str, attributes: Optional[Dict[str, Any]] = None):
    """User-facing span (ref: custom spans via util/debug profiling).
    Nested spans chain; spans created inside a task continue the
    submitting caller's trace (a live parent context counts as opt-in
    even when this process never called enable() — that's how worker
    processes participate). No-op when tracing is off."""
    parent = _ctx.get()
    if not (is_enabled() or parent is not None):
        yield None
        return
    rec = {
        "kind": "span",
        "name": name,
        "trace_id": parent["trace_id"] if parent else _new_id(16),
        "span_id": _new_id(8),
        "parent_id": parent["span_id"] if parent else None,
        "ts": time.time(),
        "attrs": dict(attributes or {}),
    }
    token = _ctx.set({"trace_id": rec["trace_id"],
                      "span_id": rec["span_id"]})
    try:
        yield rec
    except BaseException as e:
        rec["attrs"]["error"] = repr(e)
        raise
    finally:
        _ctx.reset(token)
        rec["dur"] = time.time() - rec["ts"]
        _record(rec)


def emit_span(name: str, ts: float, dur: float,
              attributes: Optional[Dict[str, Any]] = None) -> Optional[dict]:
    """Record a synthetic complete span for a phase measured elsewhere
    (streaming-executor op lifetimes, replayed timings). Same opt-in
    rule as span(): a live parent context counts as opt-in, and the
    span chains under it."""
    parent = _ctx.get()
    if not (is_enabled() or parent is not None):
        return None
    rec = {
        "kind": "span",
        "name": name,
        "trace_id": parent["trace_id"] if parent else _new_id(16),
        "span_id": _new_id(8),
        "parent_id": parent["span_id"] if parent else None,
        "ts": float(ts),
        "dur": float(dur),
        "attrs": dict(attributes or {}),
    }
    _record(rec)
    return rec


@contextlib.contextmanager
def continue_trace(trace_ctx: Optional[Dict[str, str]], name: str,
                   attributes: Optional[Dict[str, Any]] = None):
    """Worker-side: wrap a task execution in a span parented to the
    submitted context (ref: _function_span_consumer_name — the remote
    half of the trace). No-op when tracing is off AND no context came."""
    if not (is_enabled() or trace_ctx):
        yield None
        return
    if trace_ctx:
        token = _ctx.set(dict(trace_ctx))
    else:
        token = None
    try:
        with span(name, attributes) as rec:
            yield rec
    finally:
        if token is not None:
            _ctx.reset(token)
