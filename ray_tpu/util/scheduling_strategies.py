"""Scheduling strategies accepted by @remote(scheduling_strategy=...).

Reference: python/ray/util/scheduling_strategies.py:15,41.
"""

from __future__ import annotations

from typing import Optional, Union

from ray_tpu.core.common import (NodeAffinityStrategy, PlacementGroupStrategy,
                                 SpreadStrategy)
from ray_tpu.core.ids import NodeID


def PlacementGroupSchedulingStrategy(placement_group,
                                     placement_group_bundle_index: int = -1):
    return PlacementGroupStrategy(pg_id=placement_group.id,
                                  bundle_index=placement_group_bundle_index)


def NodeAffinitySchedulingStrategy(node_id: Union[str, NodeID], soft: bool = False):
    if isinstance(node_id, str):
        node_id = NodeID.from_hex(node_id)
    return NodeAffinityStrategy(node_id=node_id, soft=soft)


SPREAD = SpreadStrategy()
