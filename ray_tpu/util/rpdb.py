"""Remote pdb: breakpoints in cluster tasks, attached to from the CLI.

Reference: python/ray/util/rpdb.py (`ray.util.pdb.set_trace` opens a
socket-backed pdb in the worker and registers itself so `ray debug`
(scripts.py) can list and attach to active breakpoints).

Same shape here: `ray_tpu.util.rpdb.set_trace()` inside a task/actor
method opens a TCP listener, registers {host, port, task, pid} in the
GCS KV under ns="debugger", and blocks until a client attaches. The CLI
(`ray_tpu debug --address ...`) lists sessions and bridges the terminal
to the socket. Plain pdb protocol — `telnet host port` works too.
"""

from __future__ import annotations

import pdb
import socket
import sys
import time
from typing import List, Optional

NS = "debugger"


class _SockIO:
    """File-like adapter over a socket for pdb's stdin/stdout."""

    def __init__(self, conn: socket.socket):
        self.conn = conn
        self._rfile = conn.makefile("r", encoding="utf-8", newline="\n")

    def readline(self, *a):
        return self._rfile.readline(*a)

    def write(self, s: str):
        try:
            self.conn.sendall(s.encode("utf-8"))
        except OSError:
            pass
        return len(s)

    def flush(self):
        pass


class _RemotePdb(pdb.Pdb):
    def __init__(self, conn: socket.socket):
        io = _SockIO(conn)
        super().__init__(stdin=io, stdout=io)
        self.use_rawinput = False
        self.prompt = "(ray_tpu-pdb) "


def _kv_call(method: str, **kw):
    from ray_tpu import _rt

    return _rt.get_runtime().gcs_call(method, **kw)


def _advertised_host() -> str:
    """The worker runtime's routable address (a container hostname often
    doesn't resolve from the CLI machine)."""
    try:
        from ray_tpu import _rt

        return _rt.get_runtime().address.addr[0]
    except Exception:
        return socket.gethostname()


def set_trace(frame=None):
    """Open a breakpoint server and wait for a debugger client
    (ref: rpdb.set_trace). Blocks the task until the client detaches.

    The listener requires a per-breakpoint token as its first line —
    the token lives in the GCS KV, so attach rights == cluster-KV
    access; an unauthenticated socket would be remote code execution
    for anyone who can reach the worker."""
    import json
    import os
    import secrets

    srv = socket.socket()
    srv.bind(("0.0.0.0", 0))
    srv.listen(1)
    port = srv.getsockname()[1]
    host = _advertised_host()
    token = secrets.token_hex(16)
    key = f"bp_{host}_{port}".encode()
    info = {"host": host, "port": port, "pid": os.getpid(),
            "ts": time.time(), "token": token}
    try:
        _kv_call("kv_put", ns=NS, key=key,
                 value=json.dumps(info).encode())
    except Exception:
        pass
    conn = None
    try:
        while conn is None:
            c, _ = srv.accept()
            line = c.makefile("r").readline().strip()
            if line == token:
                conn = c
            else:
                try:
                    c.sendall(b"bad token\n")
                    c.close()
                except OSError:
                    pass
    finally:
        srv.close()
        try:
            _kv_call("kv_del", ns=NS, key=key)
        except Exception:
            pass
    dbg = _RemotePdb(conn)
    dbg.set_trace(frame or sys._getframe().f_back)


def list_breakpoints() -> List[dict]:
    """Active breakpoint sessions from the GCS KV (ref: `ray debug`
    session listing)."""
    import json

    out = []
    try:
        keys = _kv_call("kv_keys", ns=NS)
    except Exception:
        return out
    for k in keys:
        try:
            v = _kv_call("kv_get", ns=NS, key=k)
            if v:
                out.append(json.loads(v))
        except Exception:
            pass
    return out


def attach(host: str, port: int, *, token: str = "", stdin=None,
           stdout=None):
    """Bridge the local terminal to a breakpoint server (ref: `ray
    debug` attach loop). Returns when the remote side closes."""
    import threading

    stdin = stdin or sys.stdin
    stdout = stdout or sys.stdout
    conn = socket.create_connection((host, port))
    conn.sendall((token + "\n").encode())

    def pump_out():
        while True:
            try:
                data = conn.recv(4096)
            except OSError:
                return
            if not data:
                return
            stdout.write(data.decode("utf-8", errors="replace"))
            stdout.flush()

    t = threading.Thread(target=pump_out, daemon=True)
    t.start()
    try:
        for line in stdin:
            try:
                conn.sendall(line.encode("utf-8"))
            except OSError:
                break
            if line.strip() in ("c", "continue", "q", "quit", "exit"):
                break
    finally:
        time.sleep(0.2)
        try:
            conn.close()
        except Exception:
            pass
