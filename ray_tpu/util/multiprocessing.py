"""multiprocessing.Pool API over ray_tpu actors.

Reference: python/ray/util/multiprocessing/pool.py — Pool whose workers are
actors, so `map`/`apply_async` parallelize over the cluster instead of local
forks. Chunking semantics follow the stdlib: iterables are split into
chunks, each chunk is one actor task.
"""

from __future__ import annotations

import itertools
import threading
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu


class AsyncResult:
    """Stdlib-compatible handle over one or more ObjectRefs.

    Collection is lazy: the result is fetched on the first get()/wait()
    on the caller's thread; a collector thread is spawned only when a
    callback requires out-of-band delivery."""

    def __init__(self, refs, single: bool, callback=None, error_callback=None):
        self._refs = list(refs)
        self._single = single
        self._callback = callback
        self._error_callback = error_callback
        self._done = threading.Event()
        self._collect_lock = threading.Lock()
        self._collector_started = False
        self._collecting = False
        self._value = None
        self._error: Optional[BaseException] = None
        if callback is not None or error_callback is not None:
            self._start_collector()

    def _start_collector(self):
        with self._collect_lock:
            if self._collector_started or self._done.is_set():
                return
            self._collector_started = True
        threading.Thread(target=self._collect, daemon=True).start()

    def _collect(self):
        # The lock only claims the fetch; holding it across the get()
        # would stall every wait(timeout) caller (they acquire it in
        # _start_collector) for the full, unbounded collection.
        with self._collect_lock:
            if self._done.is_set() or self._collecting:
                claimed = False
            else:
                self._collecting = True
                claimed = True
        if not claimed:
            self._done.wait()
            return
        try:
            vals = ray_tpu.get(self._refs)
            self._value = vals[0] if self._single else list(
                itertools.chain.from_iterable(vals))
            if self._callback:
                self._callback(self._value)
        except BaseException as e:  # noqa: BLE001 — surfaced via .get()
            self._error = e
            if self._error_callback:
                self._error_callback(e)
        finally:
            self._done.set()

    def wait(self, timeout: Optional[float] = None) -> None:
        if self._done.is_set():
            return
        if timeout is None:
            self._collect()
        else:
            self._start_collector()
            self._done.wait(timeout)

    def ready(self) -> bool:
        return self._done.is_set()

    def successful(self) -> bool:
        if not self.ready():
            raise ValueError("result not ready")
        return self._error is None

    def get(self, timeout: Optional[float] = None):
        self.wait(timeout)
        if not self._done.is_set():
            raise TimeoutError("result not ready in time")
        if self._error is not None:
            raise self._error
        return self._value


@ray_tpu.remote
class _PoolActor:
    def __init__(self, initializer=None, initargs=()):
        if initializer:
            initializer(*initargs)

    def run_chunk(self, fn, chunk, star: bool):
        if star:
            return [fn(*item) for item in chunk]
        return [fn(item) for item in chunk]

    def run_one(self, fn, args, kwargs):
        return fn(*args, **(kwargs or {}))


class Pool:
    """ref: ray.util.multiprocessing.Pool."""

    def __init__(self, processes: Optional[int] = None, initializer=None,
                 initargs=(), maxtasksperchild=None):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        if processes is None:
            total = ray_tpu.cluster_resources().get("CPU", 1)
            processes = max(int(total), 1)
        self._n = processes
        self._actors = [_PoolActor.remote(initializer, tuple(initargs))
                        for _ in range(processes)]
        self._rr = itertools.cycle(range(processes))
        self._closed = False
        self._outstanding: List[AsyncResult] = []

    # -- apply ----------------------------------------------------------------

    def apply(self, func: Callable, args=(), kwds=None):
        return self.apply_async(func, args, kwds).get()

    def apply_async(self, func: Callable, args=(), kwds=None, callback=None,
                    error_callback=None) -> AsyncResult:
        self._check_open()
        a = self._actors[next(self._rr)]
        ref = a.run_one.remote(func, tuple(args), kwds or {})
        return self._track(AsyncResult([ref], single=True, callback=callback,
                                       error_callback=error_callback))

    # -- map ------------------------------------------------------------------

    def map(self, func: Callable, iterable: Iterable, chunksize=None) -> List[Any]:
        return self.map_async(func, iterable, chunksize).get()

    def map_async(self, func, iterable, chunksize=None, callback=None,
                  error_callback=None) -> AsyncResult:
        refs = self._submit_chunks(func, list(iterable), chunksize, star=False)
        return self._track(AsyncResult(refs, single=False, callback=callback,
                                       error_callback=error_callback))

    def starmap(self, func: Callable, iterable: Iterable, chunksize=None):
        return self.starmap_async(func, iterable, chunksize).get()

    def starmap_async(self, func, iterable, chunksize=None, callback=None,
                      error_callback=None) -> AsyncResult:
        refs = self._submit_chunks(func, list(iterable), chunksize, star=True)
        return self._track(AsyncResult(refs, single=False, callback=callback,
                                       error_callback=error_callback))

    def imap(self, func, iterable, chunksize=1):
        items = list(iterable)
        refs = self._submit_chunks(func, items, chunksize, star=False)
        for ref in refs:
            for v in ray_tpu.get(ref):
                yield v

    def imap_unordered(self, func, iterable, chunksize=1):
        items = list(iterable)
        refs = self._submit_chunks(func, items, chunksize, star=False)
        pending = list(refs)
        while pending:
            ready, pending = ray_tpu.wait(pending, num_returns=1)
            for chunk in ray_tpu.get(ready):
                for v in chunk:
                    yield v

    def _submit_chunks(self, func, items, chunksize, star: bool):
        self._check_open()
        if chunksize is None:
            chunksize = max(1, len(items) // (self._n * 4) or 1)
        refs = []
        for i in range(0, len(items), chunksize):
            a = self._actors[next(self._rr)]
            refs.append(a.run_chunk.remote(func, items[i:i + chunksize], star))
        return refs

    # -- lifecycle ------------------------------------------------------------

    def _track(self, r: AsyncResult) -> AsyncResult:
        self._outstanding = [x for x in self._outstanding if not x.ready()]
        self._outstanding.append(r)
        return r

    def _check_open(self):
        if self._closed:
            raise ValueError("Pool not running")

    def close(self):
        self._closed = True

    def terminate(self):
        self._closed = True
        for a in self._actors:
            ray_tpu.kill(a)
        self._actors = []

    def join(self):
        """Blocks until all outstanding async work drains (stdlib
        close()/join() contract)."""
        if not self._closed:
            raise ValueError("Pool is still running")
        for r in self._outstanding:
            try:
                r.wait()
            except BaseException:  # noqa: BLE001 — join only drains
                pass
        self._outstanding = []

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()
        return False
