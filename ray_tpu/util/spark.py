"""Ray-on-Spark shim: launch a ray_tpu cluster inside Spark executors.

Reference: python/ray/util/spark/ (cluster_init.py:794
setup_ray_cluster, :1067 shutdown_ray_cluster — a head starts on the
Spark driver, then a barrier-mode Spark job pins one long-running task
per executor, each execing a worker node that joins the head).

The TPU image ships no pyspark, so the Spark-dependent half is gated
behind an actionable ImportError (same policy as the gated GBDT
trainers, train/sklearn.py). The launch plan construction —
resources-per-node math and the worker bootstrap command — is pure and
tested; a pyspark environment only needs `_run_on_executors` to map the
plan over a barrier RDD.
"""

from __future__ import annotations

import sys
from typing import Dict, List, Optional

# Sentinel: "use every executor the Spark app can give us"
# (ref: cluster_init.py:38)
MAX_NUM_WORKER_NODES = -1

_active_cluster: Optional[dict] = None


def _worker_plan(num_worker_nodes: int, num_cpus_worker_node: int,
                 head_addr: str,
                 resources_worker_node: Optional[Dict[str, float]] = None
                 ) -> List[dict]:
    """One bootstrap spec per Spark executor slot (pure; ref:
    cluster_init.py worker command assembly)."""
    if num_worker_nodes != MAX_NUM_WORKER_NODES and num_worker_nodes <= 0:
        raise ValueError(
            "num_worker_nodes must be a positive integer or "
            "ray_tpu.util.spark.MAX_NUM_WORKER_NODES")
    import json

    n = 0 if num_worker_nodes == MAX_NUM_WORKER_NODES else num_worker_nodes
    plan = []
    for i in range(max(n, 1)):
        # the worker-node join entrypoint (what LocalNodeProvider and the
        # cluster launcher also exec): a nodelet pointed at the head GCS
        cmd = [sys.executable, "-m", "ray_tpu.core.nodelet",
               "--gcs", head_addr,
               "--session-dir", f"/tmp/ray_tpu/spark-worker-{i}",
               "--resources",
               json.dumps({"CPU": float(num_cpus_worker_node),
                           **(resources_worker_node or {})}),
               "--labels", json.dumps({"spark_executor_rank": i})]
        plan.append({"rank": i, "command": cmd})
    return plan if n else plan[:1]  # MAX -> template spec, fanned at run


def setup_ray_cluster(num_worker_nodes: int,
                      num_cpus_worker_node: int = 1,
                      resources_worker_node: Optional[Dict[str, float]]
                      = None, **kwargs) -> str:
    """Start a ray_tpu head on the Spark driver and one worker per Spark
    executor via a barrier-mode job (ref: cluster_init.py:794). Returns
    the head address. Requires pyspark at runtime."""
    global _active_cluster
    try:
        from pyspark.sql import SparkSession  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "ray_tpu.util.spark.setup_ray_cluster needs pyspark, which "
            "is not in the TPU image. Install pyspark in your Spark "
            "driver environment; the shim then starts the head locally "
            "and fans workers out with a barrier-mode Spark job."
        ) from e
    import ray_tpu

    info = ray_tpu.init(num_cpus=num_cpus_worker_node)
    head_addr = info["address"]
    plan = _worker_plan(num_worker_nodes, num_cpus_worker_node,
                        head_addr, resources_worker_node)
    _run_on_executors(plan)
    _active_cluster = {"head_addr": head_addr, "plan": plan}
    return head_addr


def _run_on_executors(plan: List[dict]) -> None:
    """Pin one worker bootstrap per executor with a barrier RDD
    (ref: cluster_init.py _start_ray_worker_nodes)."""
    from pyspark import BarrierTaskContext
    from pyspark.sql import SparkSession

    spark = SparkSession.getActiveSession()
    sc = spark.sparkContext

    def boot(_it):
        import subprocess

        ctx = BarrierTaskContext.get()
        spec = plan[ctx.partitionId() % len(plan)]
        subprocess.Popen(spec["command"])
        ctx.barrier()
        yield 0

    sc.parallelize(range(len(plan)), len(plan)) \
        .barrier().mapPartitions(boot).collect()


def shutdown_ray_cluster() -> None:
    """ref: cluster_init.py:1067."""
    global _active_cluster
    import ray_tpu

    ray_tpu.shutdown()
    _active_cluster = None


__all__ = ["setup_ray_cluster", "shutdown_ray_cluster",
           "MAX_NUM_WORKER_NODES"]
