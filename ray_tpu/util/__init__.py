"""ray_tpu.util — user-facing utilities.

Reference: python/ray/util/ (ActorPool, queue, placement groups, scheduling
strategies, metrics, collective).
"""

from ray_tpu.util.placement_group import (PlacementGroup, placement_group,
                                          remove_placement_group)
from ray_tpu.util.scheduling_strategies import (NodeAffinitySchedulingStrategy,
                                                PlacementGroupSchedulingStrategy)
from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.queue import Queue

__all__ = [
    "PlacementGroup", "placement_group", "remove_placement_group",
    "PlacementGroupSchedulingStrategy", "NodeAffinitySchedulingStrategy",
    "ActorPool", "Queue",
]
