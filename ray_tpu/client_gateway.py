"""Client gateway: remote drivers over a language-neutral JSON protocol.

Reference: Ray Client (python/ray/util/client/ARCHITECTURE.md) — a thin
client forwards API calls to a server-side driver that owns all objects
and actors (util/client/server/{server.py,proxier.py}); and the C++
worker API (cpp/include/ray/api.h) whose runtime speaks to the core from
another language.

Re-design: instead of a gRPC proto + per-language codegen, one gateway
process holds a real driver Runtime and serves newline-free
length-prefixed JSON frames:

    [u32 little-endian length][utf-8 JSON]
    request : {"id": N, "method": str, "params": {...}}
    response: {"id": N, "ok": true, "result": ...} | {"id": N, "ok":
               false, "error": str}

Values cross the wire as JSON, with two extension markers:
    {"__bytes__": base64}   raw bytes (any client)
    {"__pickle__": base64}  cloudpickle payload (python clients only —
                            this is how arbitrary functions/objects ship,
                            like Ray Client's pickled function protocol)
    {"__ref__": hex}        an ObjectRef owned by the gateway driver

The same protocol serves the Python thin client (ray_tpu/client.py) and
the C++ API (cpp/) — one server, any language.
"""

from __future__ import annotations

import argparse
import asyncio
import base64
import importlib
import json
import logging
import struct
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional

logger = logging.getLogger("ray_tpu.client_gateway")

_LEN = struct.Struct("<I")
MAX_FRAME = 1 << 30


def _called_by_name(path: str, *args, **kwargs):
    """Cluster-side trampoline for C++/named-function tasks: resolve
    "module:attr" on the executing worker and call it."""
    mod, _, name = path.partition(":")
    fn = importlib.import_module(mod)
    for part in name.split("."):
        fn = getattr(fn, part)
    return fn(*args, **kwargs)


def _stream_by_name(path: str, *args, **kwargs):
    """Streaming twin of _called_by_name: the named callable's result is
    re-yielded item by item (a generator/iterable becomes a streaming
    generator task; a scalar streams as one item)."""
    out = _called_by_name(path, *args, **kwargs)
    if hasattr(out, "__iter__") and not isinstance(out, (str, bytes, dict)):
        yield from out
    else:
        yield out


class _Codec:
    """JSON <-> python values with the extension markers above."""

    def __init__(self, refs: Dict[str, Any]):
        self.refs = refs  # hex -> ObjectRef (gateway-owned)

    def decode(self, v):
        if isinstance(v, dict):
            if "__bytes__" in v and len(v) == 1:
                return base64.b64decode(v["__bytes__"])
            if "__pickle__" in v and len(v) == 1:
                import cloudpickle

                return cloudpickle.loads(base64.b64decode(v["__pickle__"]))
            if "__ref__" in v and len(v) == 1:
                ref = self.refs.get(v["__ref__"])
                if ref is None:
                    raise KeyError(f"unknown ref {v['__ref__']}")
                return ref
            if "__tuple__" in v and len(v) == 1:
                return tuple(self.decode(x) for x in v["__tuple__"])
            return {k: self.decode(x) for k, x in v.items()}
        if isinstance(v, list):
            return [self.decode(x) for x in v]
        return v

    def encode(self, v, *, pickle_fallback: bool):
        """Containers recurse (so nested ObjectRefs keep their __ref__
        markers in both directions); only non-container leaves fall back
        to pickle. A ref buried inside a custom OBJECT (not a dict/list/
        tuple) is still pickled opaquely — unsupported, as in Ray
        Client's value protocol."""
        import ray_tpu

        if isinstance(v, ray_tpu.ObjectRef):
            h = v.id.hex()
            self.refs[h] = v
            return {"__ref__": h}
        if isinstance(v, bytes):
            return {"__bytes__": base64.b64encode(v).decode()}
        if isinstance(v, dict):
            return {str(k): self.encode(x, pickle_fallback=pickle_fallback)
                    for k, x in v.items()}
        if isinstance(v, tuple) and pickle_fallback:
            return {"__tuple__": [self.encode(x,
                                              pickle_fallback=pickle_fallback)
                                  for x in v]}
        if isinstance(v, (list, tuple)):
            return [self.encode(x, pickle_fallback=pickle_fallback)
                    for x in v]
        if v is None or isinstance(v, (bool, int, float, str)):
            return v
        try:
            import numpy as np

            if isinstance(v, np.generic):
                return v.item()
        except ImportError:
            pass
        if pickle_fallback:
            import cloudpickle

            return {"__pickle__":
                    base64.b64encode(cloudpickle.dumps(v)).decode()}
        # numpy arrays for JSON-only clients
        try:
            import numpy as np

            if isinstance(v, np.ndarray):
                return [self.encode(x, pickle_fallback=pickle_fallback)
                        for x in v.tolist()]
        except ImportError:
            pass
        raise TypeError(f"value of type {type(v).__name__} is not "
                        "JSON-representable; use a python client")


class ClientGateway:
    """One driver Runtime serving many remote clients
    (ref: proxier.py — but sharing one driver, not one per client)."""

    def __init__(self, cluster_address: str, host: str = "0.0.0.0",
                 port: int = 0):
        self.cluster_address = cluster_address
        self.host, self.port = host, port
        self.refs: Dict[str, Any] = {}
        self.actors: Dict[str, Any] = {}
        self.pgs: Dict[str, Any] = {}      # hex -> PlacementGroup
        self.streams: Dict[str, Any] = {}  # id -> ObjectRefGenerator iter
        self._stream_ids = 0
        self.codec = _Codec(self.refs)
        # driver API calls block (ray_tpu.get); keep them off the loop
        self.pool = ThreadPoolExecutor(max_workers=16,
                                       thread_name_prefix="gateway")
        self._server: Optional[asyncio.AbstractServer] = None

    # --------------------------------------------------------------- methods

    def m_ping(self, _session=None, **_):
        return {"ok": True}

    def m_cluster_resources(self, _session=None, **_):
        import ray_tpu

        return ray_tpu.cluster_resources()

    def _track_refs(self, session, refs):
        for r in refs:
            h = r.id.hex()
            self.refs[h] = r
            if session is not None:
                session["refs"].add(h)
        return [r.id.hex() for r in refs]

    def m_put(self, value=None, _session=None):
        import ray_tpu

        ref = ray_tpu.put(self.codec.decode(value))
        return {"ref": self._track_refs(_session, [ref])[0]}

    def m_get(self, refs=None, timeout: float = 60.0, pickle_ok=False,
              _session=None):
        import ray_tpu

        objs = [self.refs[h] for h in refs]
        vals = ray_tpu.get(objs, timeout=timeout)
        return {"values": [self.codec.encode(v, pickle_fallback=pickle_ok)
                           for v in vals]}

    def m_wait(self, refs=None, num_returns: int = 1,
               timeout: Optional[float] = None, _session=None):
        import ray_tpu

        objs = [self.refs[h] for h in refs]
        ready, pending = ray_tpu.wait(objs, num_returns=num_returns,
                                      timeout=timeout)
        return {"ready": [r.id.hex() for r in ready],
                "pending": [p.id.hex() for p in pending]}

    def _options(self, opts):
        out = {}
        for k in ("num_returns", "num_cpus", "resources", "max_retries",
                  "runtime_env", "name", "namespace", "lifetime",
                  "max_restarts", "max_task_retries", "max_concurrency"):
            if opts and k in opts:
                out[k] = opts[k]
        if opts and "placement_group" in opts:
            # PG-aware scheduling over the wire (ref: Ray Client proxies
            # PlacementGroupSchedulingStrategy the same way)
            from ray_tpu.util.scheduling_strategies import (
                PlacementGroupSchedulingStrategy)

            pg = self.pgs[opts["placement_group"]]
            out["scheduling_strategy"] = PlacementGroupSchedulingStrategy(
                pg, opts.get("placement_group_bundle_index", -1))
        return out

    def _track_result(self, refs, _session):
        """Task/actor-call result: plain refs or a streaming generator."""
        import ray_tpu

        if isinstance(refs, ray_tpu.ObjectRefGenerator):
            self._stream_ids += 1
            sid = f"s{self._stream_ids}"
            # generator + explicit cursor (NOT a bare iterator): item
            # fetches go through next_stream_ref with a bounded timeout,
            # and the cursor advances only after a successful delivery —
            # a timed-out pull can be retried without losing the item
            self.streams[sid] = {"gen": refs, "index": 0}
            if _session is not None:
                _session["streams"].add(sid)
            return {"stream": sid}
        refs = refs if isinstance(refs, list) else [refs]
        return {"refs": self._track_refs(_session, refs)}

    def m_task(self, func: str = None, args=None, kwargs=None, opts=None,
               _session=None):
        """Named-function task: any-language clients submit
        "module:function"; execution resolves it on the worker."""
        import ray_tpu

        args = [self.codec.decode(a) for a in (args or [])]
        kwargs = {k: self.codec.decode(v) for k, v in (kwargs or {}).items()}
        streaming = (opts or {}).get("num_returns") == "streaming"
        rf = ray_tpu.remote(_stream_by_name if streaming
                            else _called_by_name)
        o = self._options(opts)
        if o:
            rf = rf.options(**o)
        return self._track_result(rf.remote(func, *args, **kwargs),
                                  _session)

    def m_task_pickled(self, func=None, args=None, kwargs=None, opts=None,
                       _session=None):
        """Python clients ship the function itself (ref: Ray Client's
        pickled-function protocol)."""
        import ray_tpu

        fn = self.codec.decode(func)
        args = [self.codec.decode(a) for a in (args or [])]
        kwargs = {k: self.codec.decode(v) for k, v in (kwargs or {}).items()}
        rf = ray_tpu.remote(fn)
        o = self._options(opts)
        if o:
            rf = rf.options(**o)
        return self._track_result(rf.remote(*args, **kwargs), _session)

    def _register_actor(self, handle, session=None, owned=False):
        h = handle._actor_id.hex()
        self.actors[h] = handle
        if session is not None and owned:
            session["actors"].add(h)
        return {"actor": h}

    def m_actor_create(self, cls: str = None, pickled=None, args=None,
                       kwargs=None, opts=None, _session=None):
        import ray_tpu

        if pickled is not None:
            klass = self.codec.decode(pickled)
        else:
            mod, _, name = cls.partition(":")
            klass = getattr(importlib.import_module(mod), name)
        args = [self.codec.decode(a) for a in (args or [])]
        kwargs = {k: self.codec.decode(v) for k, v in (kwargs or {}).items()}
        ac = ray_tpu.remote(klass)
        o = self._options(opts)
        if o:
            ac = ac.options(**o)
        # unnamed actors die with their session; named ones are
        # detached-like and survive (ref: Ray Client lifetime rules)
        owned = not (opts or {}).get("name")
        return self._register_actor(ac.remote(*args, **kwargs), _session,
                                    owned=owned)

    def m_actor_call(self, actor: str = None, method: str = None, args=None,
                     kwargs=None, num_returns: int = 1, _session=None):
        handle = self.actors[actor]
        args = [self.codec.decode(a) for a in (args or [])]
        kwargs = {k: self.codec.decode(v) for k, v in (kwargs or {}).items()}
        m = getattr(handle, method)
        if num_returns != 1:
            m = m.options(num_returns=num_returns)
        return self._track_result(m.remote(*args, **kwargs), _session)

    def m_get_actor(self, name: str = None, namespace: str = "default",
                    _session=None):
        import ray_tpu

        return self._register_actor(
            ray_tpu.get_actor(name, namespace=namespace))

    def m_kill(self, actor: str = None, _session=None):
        import ray_tpu

        ray_tpu.kill(self.actors.pop(actor))
        if _session is not None:
            _session["actors"].discard(actor)
        return {"ok": True}

    def m_release(self, refs=None, _session=None):
        """Drop gateway-held refs so the cluster can reclaim the objects
        (the thin client's del hook, ref: client reference counting)."""
        for h in refs or []:
            self.refs.pop(h, None)
            if _session is not None:
                _session["refs"].discard(h)
        return {"ok": True}

    def m_stream_next(self, stream: str = None, timeout: float = 60.0,
                      pickle_ok=False, _session=None):
        """Pull the next item of a streaming-generator call (ref: Ray
        Client has no streaming surface — this closes that gap for all
        gateway languages). Returns {"done": true} at exhaustion."""
        import ray_tpu

        st = self.streams.get(stream)
        if st is None:
            raise KeyError(f"unknown stream {stream!r}")
        from ray_tpu.core import runtime as _rt
        from ray_tpu.core.status import GetTimeoutError

        gen, idx = st["gen"], st["index"] + 1
        # bounded wait that does NOT consume on timeout: GetTimeoutError
        # propagates to the client, which may simply call again — unlike
        # next(it), the cursor only moves after a successful delivery,
        # and a slow stream can't park a pool thread forever
        try:
            ref = _rt.get_runtime().next_stream_ref(gen.task_id, idx,
                                                    timeout=timeout)
            ended = ref is None
            value = None if ended else ray_tpu.get(ref)  # ready: no wait
        except GetTimeoutError:
            raise                          # retryable: cursor unmoved
        except Exception:
            self.streams.pop(stream, None)  # stream errored: surface it
            if _session is not None:
                _session["streams"].discard(stream)
            raise
        if ended:
            self.streams.pop(stream, None)
            if _session is not None:
                _session["streams"].discard(stream)
            return {"done": True}
        st["index"] = idx
        return {"done": False,
                "value": self.codec.encode(value, pickle_fallback=pickle_ok)}

    def m_stream_close(self, stream: str = None, _session=None):
        self.streams.pop(stream, None)
        if _session is not None:
            _session["streams"].discard(stream)
        return {"ok": True}

    def m_pg_create(self, bundles=None, strategy: str = "PACK",
                    _session=None):
        """Placement groups over the wire (ref: Ray Client proxies
        util.placement_group the same way)."""
        from ray_tpu.util.placement_group import placement_group

        pg = placement_group(bundles, strategy=strategy)
        h = pg.id.hex()
        self.pgs[h] = pg
        if _session is not None:
            _session["pgs"].add(h)
        return {"pg": h}

    def m_pg_ready(self, pg: str = None, timeout: float = 30.0,
                   _session=None):
        return {"ready": bool(self.pgs[pg].ready(timeout=timeout))}

    def m_pg_table(self, pg: str = None, _session=None):
        def jsonable(v):
            if hasattr(v, "hex") and callable(getattr(v, "hex", None)) \
                    and not isinstance(v, (str, bytes, float)):
                return v.hex()          # BaseID subclasses
            if hasattr(v, "quantities"):
                return dict(v.quantities)   # ResourceSet
            if isinstance(v, dict):
                return {str(k): jsonable(x) for k, x in v.items()}
            if isinstance(v, (list, tuple)):
                return [jsonable(x) for x in v]
            if v is None or isinstance(v, (bool, int, float, str)):
                return v
            return repr(v)
        return {"table": jsonable(self.pgs[pg].table())}

    def m_pg_remove(self, pg: str = None, _session=None):
        from ray_tpu.util.placement_group import remove_placement_group

        g = self.pgs.pop(pg, None)
        if _session is not None:
            _session["pgs"].discard(pg)
        if g is not None:
            remove_placement_group(g)
        return {"ok": True}

    def _close_session(self, session):
        """Connection teardown: release the session's refs and kill its
        unnamed actors (ref: Ray Client per-client driver teardown)."""
        import ray_tpu

        for h in session["refs"]:
            self.refs.pop(h, None)
        for h in session["actors"]:
            handle = self.actors.pop(h, None)
            if handle is not None:
                try:
                    ray_tpu.kill(handle)
                except Exception:
                    pass
        for sid in session["streams"]:
            self.streams.pop(sid, None)
        for h in session["pgs"]:
            g = self.pgs.pop(h, None)
            if g is not None:
                try:
                    from ray_tpu.util.placement_group import (
                        remove_placement_group)

                    remove_placement_group(g)
                except Exception:
                    pass

    # ----------------------------------------------------------------- serve

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter):
        loop = asyncio.get_running_loop()
        session = {"refs": set(), "actors": set(), "streams": set(),
                   "pgs": set()}
        try:
            while True:
                try:
                    hdr = await reader.readexactly(4)
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    return
                (n,) = _LEN.unpack(hdr)
                if n > MAX_FRAME:
                    return
                body = await reader.readexactly(n)
                req = json.loads(body)
                mid = req.get("id")
                try:
                    fn = getattr(self, f"m_{req.get('method')}", None)
                    if fn is None:
                        raise ValueError(f"no method {req.get('method')!r}")
                    res = await loop.run_in_executor(
                        self.pool,
                        lambda: fn(**(req.get("params") or {}),
                                   _session=session))
                    out = {"id": mid, "ok": True, "result": res}
                except Exception as e:
                    logger.debug("gateway method failed", exc_info=True)
                    out = {"id": mid, "ok": False,
                           "error": f"{type(e).__name__}: {e}"}
                try:
                    data = json.dumps(out).encode()
                except TypeError as e:
                    # a method returned something non-JSON: surface the
                    # error to the caller instead of killing the stream
                    out = {"id": mid, "ok": False,
                           "error": f"unserializable result: {e}"}
                    data = json.dumps(out).encode()
                writer.write(_LEN.pack(len(data)) + data)
                await writer.drain()
        finally:
            await loop.run_in_executor(self.pool,
                                       lambda: self._close_session(session))
            try:
                writer.close()
            except Exception:
                pass

    async def start(self):
        import ray_tpu

        if not ray_tpu.is_initialized():
            # init() drives its own asyncio plumbing with asyncio.run —
            # keep it off this (already running) loop
            await asyncio.get_running_loop().run_in_executor(
                self.pool,
                lambda: ray_tpu.init(address=self.cluster_address))
        self._server = await asyncio.start_server(self._handle_conn,
                                                  self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self.host, self.port

    async def stop(self):
        if self._server is not None:
            self._server.close()
            try:
                await self._server.wait_closed()
            except Exception:
                pass
        self.pool.shutdown(wait=False)


async def serve(address: str, host: str = "0.0.0.0", port: int = 10001):
    """Run a gateway forever (shared by __main__ and `cli gateway`)."""
    gw = ClientGateway(address, host, port)
    host, port = await gw.start()
    print(f"gateway listening on {host}:{port}", flush=True)
    while True:
        await asyncio.sleep(3600)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--address", required=True, help="cluster GCS host:port")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=10001)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    asyncio.run(serve(args.address, args.host, args.port))


if __name__ == "__main__":
    main()
