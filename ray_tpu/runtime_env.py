"""Runtime environments: per-task/actor env vars, working_dir, py_modules.

Reference: python/ray/runtime_env/runtime_env.py (spec + validation),
python/ray/_private/runtime_env/packaging.py (directory → content-addressed
zip in GCS KV, `get_uri_for_directory`/`upload_package_if_needed`), and the
per-node agent's URI cache (python/ray/_private/runtime_env/agent/).

TPU-first simplifications kept deliberate:
- Packages ride the GCS KV (ns="packages") like the reference's GCS-backed
  packaging; conda/pip/container plugins are out of scope for a
  single-image TPU fleet (the image is the environment) and are rejected
  with a clear error instead of silently ignored.
- Workers apply env specs at task boundaries (env_vars save/restore around
  execution; working_dir/py_modules installed idempotently into a
  session-scoped cache). The exception is `process_env_vars`: variables
  that must exist BEFORE the worker interpreter imports anything (e.g.
  JAX_PLATFORMS, XLA_FLAGS, LIBTPU_INIT_ARGS). Those key dedicated worker
  pools in the nodelet — the TPU-shaped slice of the reference's
  runtime-env-keyed pools (worker_pool.h:156).
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import sys
import threading
import zipfile
from typing import Any, Dict, List, Optional

_SUPPORTED = {"env_vars", "process_env_vars", "working_dir", "py_modules",
              "config"}
_UNSUPPORTED = {"conda", "pip", "container", "image_uri", "java_jars"}

_EXCLUDE_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}
_MAX_PACKAGE_BYTES = 256 * 1024 * 1024


def validate(env: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """ref: RuntimeEnv.__init__ validation."""
    if not env:
        return {}
    bad = set(env) & _UNSUPPORTED
    if bad:
        raise ValueError(
            f"runtime_env fields {sorted(bad)} are not supported on the "
            "single-image TPU fleet (the machine image is the environment); "
            f"supported: {sorted(_SUPPORTED)}")
    unknown = set(env) - _SUPPORTED
    if unknown:
        raise ValueError(f"unknown runtime_env fields: {sorted(unknown)}")
    for field in ("env_vars", "process_env_vars"):
        ev = env.get(field, {})
        if not all(isinstance(k, str) and isinstance(v, str)
                   for k, v in ev.items()):
            raise TypeError(f"runtime_env.{field} must be Dict[str, str]")
    return dict(env)


def process_env(env: Optional[Dict[str, Any]]) -> Dict[str, str]:
    """Vars that must be set before worker start (keys the worker pool)."""
    return (env or {}).get("process_env_vars", {})


# --- packaging (driver side) -------------------------------------------------


def _zip_directory(path: str) -> bytes:
    buf = io.BytesIO()
    base = os.path.abspath(path)
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        for root, dirs, files in os.walk(base):
            dirs[:] = [d for d in dirs if d not in _EXCLUDE_DIRS]
            for f in sorted(files):
                full = os.path.join(root, f)
                rel = os.path.relpath(full, base)
                z.write(full, rel)
    data = buf.getvalue()
    if len(data) > _MAX_PACKAGE_BYTES:
        raise ValueError(
            f"packaged {path!r} is {len(data)} bytes "
            f"(limit {_MAX_PACKAGE_BYTES}); add excludes or trim the dir")
    return data


def uri_for_directory(path: str) -> str:
    """Content-addressed package URI (ref: get_uri_for_directory —
    hash of file paths + contents, so unchanged dirs re-use the cache)."""
    h = hashlib.sha1()
    base = os.path.abspath(path)
    for root, dirs, files in os.walk(base):
        dirs[:] = [d for d in dirs if d not in _EXCLUDE_DIRS]
        for f in sorted(files):
            full = os.path.join(root, f)
            h.update(os.path.relpath(full, base).encode())
            with open(full, "rb") as fh:
                h.update(fh.read())
    return f"gcs://pkg_{h.hexdigest()}.zip"


def upload_package_if_needed(runtime, path: str) -> str:
    """Zip + store in GCS KV unless already there
    (ref: upload_package_if_needed packaging.py)."""
    uri = uri_for_directory(path)
    key = uri.encode()
    if not runtime.gcs_call("kv_exists", ns="packages", key=key):
        runtime.kv_put("packages", key, _zip_directory(path))
    return uri


def resolve_uris(runtime, env: Dict[str, Any]) -> Dict[str, Any]:
    """Replace local directory paths with uploaded package URIs in
    working_dir / py_modules. Idempotent (URIs pass through)."""
    env = validate(env)
    out = dict(env)
    wd = env.get("working_dir")
    if wd and not wd.startswith("gcs://"):
        if not os.path.isdir(wd):
            raise ValueError(f"working_dir {wd!r} is not a directory")
        out["working_dir"] = upload_package_if_needed(runtime, wd)
    mods: List[str] = []
    for m in env.get("py_modules", []):
        if m.startswith("gcs://"):
            mods.append(m)
        elif os.path.isdir(m):
            mods.append(upload_package_if_needed(runtime, m))
        else:
            raise ValueError(f"py_modules entry {m!r} is not a directory")
    if mods:
        out["py_modules"] = mods
    return out


# --- worker-side setup -------------------------------------------------------

_cache_lock = threading.Lock()
_installed: Dict[str, str] = {}       # uri -> local dir


def _cache_root() -> str:
    session = os.environ.get("RAY_TPU_SESSION_DIR", "/tmp/ray_tpu")
    return os.path.join(session, "runtime_resources")


def ensure_package(runtime, uri: str) -> str:
    """Download + extract a package URI into the session cache, once
    (ref: the runtime-env agent's URI cache with delete-on-unused; we keep
    packages for the session lifetime)."""
    with _cache_lock:
        got = _installed.get(uri)
        if got:
            return got
    name = uri[len("gcs://"):]
    dest = os.path.join(_cache_root(), name[:-len(".zip")])
    if not os.path.isdir(dest):
        data = runtime.kv_get("packages", uri.encode())
        if data is None:
            raise FileNotFoundError(f"package {uri} not in GCS KV")
        tmp = dest + f".tmp{os.getpid()}"
        with zipfile.ZipFile(io.BytesIO(data)) as z:
            z.extractall(tmp)
        try:
            os.replace(tmp, dest)        # atomic; concurrent extractors race
        except OSError:                  # benignly (same content)
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)
    with _cache_lock:
        _installed[uri] = dest
    return dest


class TaskEnvContext:
    """Applies a runtime env around one task execution; restores env_vars
    after. working_dir/py_modules installation is additive + idempotent."""

    def __init__(self, runtime, env: Optional[Dict[str, Any]]):
        self.runtime = runtime
        self.env = env or {}
        self._saved: Dict[str, Optional[str]] = {}

    def __enter__(self):
        env = self.env
        if not env:
            return self
        for k, v in env.get("env_vars", {}).items():
            self._saved[k] = os.environ.get(k)
            os.environ[k] = v
        wd = env.get("working_dir")
        if wd:
            path = ensure_package(self.runtime, wd)
            if path not in sys.path:
                sys.path.insert(0, path)
        for m in env.get("py_modules", []):
            path = ensure_package(self.runtime, m)
            if path not in sys.path:
                sys.path.insert(0, path)
        return self

    def __exit__(self, *exc):
        for k, old in self._saved.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
        self._saved.clear()
        return False


def to_json(env: Optional[Dict[str, Any]]) -> str:
    return json.dumps(env or {}, sort_keys=True)
