"""Binary IDs for tasks/objects/actors/nodes.

Reference: src/ray/common/id.h — Ray embeds ownership info in IDs (ObjectID =
TaskID + index, TaskID embeds ActorID/JobID). We keep the same embedding so an
ObjectID alone identifies the task that produced it (needed for lineage
reconstruction) while fixing all IDs at 20 bytes, the native store's key width.

Layout:
  JobID    = 4 bytes
  ActorID  = 12 bytes = 8 unique + JobID
  TaskID   = 16 bytes = 4 unique + ActorID
  ObjectID = 20 bytes = TaskID + 4-byte big-endian return index
  NodeID / WorkerID / PlacementGroupID = 20 random bytes
"""

from __future__ import annotations

import os
import struct


class BaseID:
    SIZE = 20
    __slots__ = ("_bytes", "_hash")

    def __init__(self, b: bytes):
        if len(b) != self.SIZE:
            raise ValueError(f"{type(self).__name__} needs {self.SIZE} bytes, got {len(b)}")
        self._bytes = b
        self._hash = None

    @classmethod
    def from_random(cls):
        return cls(os.urandom(cls.SIZE))

    @classmethod
    def nil(cls):
        return cls(b"\x00" * cls.SIZE)

    def is_nil(self) -> bool:
        return self._bytes == b"\x00" * self.SIZE

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    @classmethod
    def from_hex(cls, h: str):
        return cls(bytes.fromhex(h))

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __hash__(self):
        # ids key every hot-path dict (directory, refcounts, queues);
        # cache the hash — it's taken dozens of times per task
        h = self._hash
        if h is None:
            h = self._hash = hash((type(self).__name__, self._bytes))
        return h

    # The cache must NOT cross process boundaries: bytes hashing is
    # per-process salted (PYTHONHASHSEED), so a shipped cached hash
    # would disagree with locally-constructed equal ids and silently
    # miss every dict probe (observed: workers "not found" at their own
    # nodelet, actors never alive).
    def __getstate__(self):
        return self._bytes

    def __setstate__(self, state):
        if isinstance(state, tuple):
            # legacy slots format ((None, {"_bytes": ...})) from state
            # files written before __getstate__ existed
            state = state[1]["_bytes"]
        self._bytes = state
        self._hash = None

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()[:16]})"


class NodeID(BaseID):
    SIZE = 20


class WorkerID(BaseID):
    SIZE = 20


class PlacementGroupID(BaseID):
    SIZE = 20


class JobID(BaseID):
    SIZE = 4


class ActorID(BaseID):
    SIZE = 12

    @classmethod
    def of(cls, job_id: JobID) -> "ActorID":
        return cls(os.urandom(8) + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._bytes[8:])


class TaskID(BaseID):
    SIZE = 16

    @classmethod
    def of(cls, actor_id: ActorID) -> "TaskID":
        return cls(os.urandom(4) + actor_id.binary())

    @classmethod
    def for_driver(cls, job_id: JobID) -> "TaskID":
        return cls(os.urandom(4) + b"\x00" * 8 + job_id.binary())

    def actor_id(self) -> ActorID:
        return ActorID(self._bytes[4:])

    def job_id(self) -> JobID:
        return JobID(self._bytes[12:])


class ObjectID(BaseID):
    SIZE = 20

    @classmethod
    def for_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        return cls(task_id.binary() + struct.pack(">I", index))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int) -> "ObjectID":
        # Put objects use the high bit of the index space so they never
        # collide with return indices (ref: id.h ObjectID::FromIndex).
        return cls(task_id.binary() + struct.pack(">I", 0x80000000 | put_index))

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[:16])

    def return_index(self) -> int:
        return struct.unpack(">I", self._bytes[16:])[0]

    def is_put(self) -> bool:
        return bool(self.return_index() & 0x80000000)
