"""Cluster control plane ("GCS").

Reference: src/ray/gcs/gcs_server/gcs_server.cc:192-237 wires the same
subsystems this module holds in one asyncio process:

- node membership + passive health checks (ref: GcsNodeManager,
  GcsHealthCheckManager; thresholds ray_config_def.h:793-799)
- resource view fed by nodelet heartbeats (ref: RaySyncer gossip — here a
  star topology: every nodelet reports (seqno, available) each period)
- actor manager with restart FSM and named-actor registry
  (ref: gcs_actor_manager.cc:246,271,1100)
- placement groups with two-phase PREPARE/COMMIT reservation across nodelets
  (ref: gcs_placement_group_scheduler.h)
- internal KV (ref: gcs_kv_manager.h) — also the function/class code store
  (ref: function_manager.py:61 exports via GCS KV)
- job table, task-event sink (ref: gcs_task_manager.h), pub/sub push
  (ref: src/ray/pubsub/)

Storage is pluggable (ref: GcsTableStorage memory/Redis backends,
gcs_table_storage.h:252): "memory" (default, no durability) or "file" —
debounced pickle snapshots PLUS a per-mutation append-WAL
(core/gcs_storage.py), so every acked write survives a GCS crash, not
just state as of the last snapshot point.
"""

from __future__ import annotations

import asyncio
import logging
import os
import pickle
import time
from collections import defaultdict, deque
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core.common import (Address, NodeInfo, ResourceSet, TaskSpec)
from ray_tpu.core.config import Config
from ray_tpu.core.ids import ActorID, JobID, NodeID, PlacementGroupID
from ray_tpu.core.rpc import ClientPool, ConnectionLost, RemoteError, RpcServer
from ray_tpu.core.scheduling_policy import (HybridPolicy, SchedNode,
                                            SpreadPolicy, pack_bundles)

logger = logging.getLogger("ray_tpu.gcs")

# Actor FSM states (ref: rpc::ActorTableData::ActorState)
DEPENDENCIES_UNREADY = "DEPENDENCIES_UNREADY"
PENDING_CREATION = "PENDING_CREATION"
ALIVE = "ALIVE"
RESTARTING = "RESTARTING"
DEAD = "DEAD"


class ActorRecord:
    def __init__(self, spec: TaskSpec):
        self.spec = spec
        self.actor_id: ActorID = spec.actor_id
        self.state = PENDING_CREATION
        self.address: Optional[Address] = None      # worker RPC address
        self.node_id: Optional[NodeID] = None
        self.worker_id: bytes = b""
        self.num_restarts = 0
        self.max_restarts = spec.max_restarts
        self.name = spec.actor_name
        self.namespace = spec.namespace
        self.death_cause: str = ""

    def view(self) -> dict:
        return {
            "actor_id": self.actor_id,
            "state": self.state,
            "address": self.address,
            "node_id": self.node_id,
            "num_restarts": self.num_restarts,
            "max_restarts": self.max_restarts,
            "name": self.name,
            "namespace": self.namespace,
            "death_cause": self.death_cause,
            "class_name": self.spec.name,
        }


class GcsServer:
    def __init__(self, cfg: Config):
        self.cfg = cfg
        # deadlines/keepalive knobs + optional chaos plan bind from the
        # inherited Config so the whole cluster shares one failure model
        from ray_tpu.core import rpc as _rpc
        from ray_tpu.devtools import chaos as _chaos
        _rpc.configure(cfg)
        _chaos.maybe_install(cfg, role="gcs")
        self.nodes: Dict[NodeID, NodeInfo] = {}
        self.available: Dict[NodeID, ResourceSet] = {}
        self.heartbeat_seq: Dict[NodeID, int] = {}
        self.last_seen: Dict[NodeID, float] = {}
        self.actors: Dict[ActorID, ActorRecord] = {}
        self.named_actors: Dict[Tuple[str, str], ActorID] = {}
        self.jobs: Dict[JobID, dict] = {}
        self.kv: Dict[Tuple[str, bytes], bytes] = {}
        self.pgs: Dict[PlacementGroupID, dict] = {}
        self.subscribers: Dict[str, set] = defaultdict(set)  # channel -> {addr}
        self.pending_leases: Dict[NodeID, int] = {}
        self.unmet_demand: List[dict] = []  # infeasible resource asks
        # reporter-keyed gang shortfalls (elastic training refill/grow;
        # same reporter-keyed + staleness-aged shape as serve
        # report_load) — folded into get_load()'s unmet_demand
        self.gang_demand: Dict[str, dict] = {}
        # reporter -> highest seq applied (monotonic fence against
        # reordered/duplicated stale gang-demand reports)
        self._gang_demand_seq: Dict[str, int] = {}
        self.task_events: deque = deque(maxlen=cfg.task_event_buffer_size)
        # per-edge EWMA latency/bandwidth fed by batched telemetry
        # reports (in-memory: telemetry, re-learned after failover)
        from ray_tpu.observability.edges import EdgeModel
        self.edge_model = EdgeModel()
        # stall watchdog + straggler detection over beacon snapshots
        # riding the same telemetry reports (in-memory, like edge_model)
        from ray_tpu.observability.health import HealthAggregator
        self.health = HealthAggregator(
            straggler_k=cfg.straggler_k,
            straggler_min_peers=cfg.straggler_min_peers)
        # memory attribution fold over per-process tracker snapshots
        # riding the same reports (in-memory, like health/edge_model)
        from ray_tpu.observability.memory import MemoryAggregator
        self.memory = MemoryAggregator(
            leak_suspect_s=cfg.memory_leak_suspect_s,
            cold_after_s=cfg.memory_cold_after_s,
            stale_after_s=max(60.0, 10 * cfg.telemetry_report_interval_s))
        self.pool = ClientPool()
        self.server = RpcServer(self)
        # pluggable node-picking policies (ref: scheduling/policy/)
        self._hybrid_policy = HybridPolicy(
            spread_threshold=cfg.scheduler_spread_threshold,
            top_k_fraction=cfg.scheduler_top_k_fraction)
        self._spread_policy = SpreadPolicy()
        self._stopping = False
        self._dirty = False
        # pluggable persistence: snapshot + append-WAL (ref:
        # gcs_table_storage.h:252 over memory/redis store clients)
        from ray_tpu.core.gcs_storage import FileGcsStorage, MemoryGcsStorage
        if cfg.gcs_storage == "file" and cfg.gcs_file_storage_path:
            self.storage = FileGcsStorage(cfg.gcs_file_storage_path)
        else:
            self.storage = MemoryGcsStorage()
        # node_id -> {actor_id_hex: {"addr", "worker_id"}} from re-registration
        self._hosted: Dict[NodeID, dict] = {}
        # global KV-prefix directory (serve/disagg): page-group chain
        # hash -> exported page-group object in the zero-copy store, so
        # ANY replica can adopt a warm shared prefix instead of
        # re-prefilling it. LRU-bounded; in-memory like edge_model —
        # entries are a cache of what prefill replicas currently retain,
        # re-registered on the next prefill after a GCS failover.
        from collections import OrderedDict
        self.prefix_dir: "OrderedDict[bytes, dict]" = OrderedDict()
        self.prefix_dir_stats: Dict[str, int] = {
            "registered": 0, "hits": 0, "misses": 0, "evicted": 0,
            "dropped": 0}

    # ------------------------------------------------------------------ boot

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> Address:
        self.server.host, self.server.port = host, port
        addr = await self.server.start()
        self._maybe_restore()
        loop = asyncio.get_running_loop()
        loop.create_task(self._health_loop())
        if self._snapshot_path():
            loop.create_task(self._snapshot_loop())
        if self.actors:
            # Restored from a snapshot: reconcile after nodelets rejoin
            # (ref: gcs_actor_manager restart reconstruction on failover).
            loop.create_task(self._failover_reconcile())
        return addr

    async def _failover_reconcile(self):
        """Post-restart actor reconciliation. Surviving nodelets re-register
        within a heartbeat (their register_node carries hosted actors, which
        rpc_register_node adopts). After that grace window:
        - still-PENDING/RESTARTING records re-drive creation (their original
          creation either never ran or was adopted above),
        - ALIVE records whose node never came back, or whose worker is no
          longer hosted there, get the normal restart FSM treatment."""
        await asyncio.sleep(max(1.0, self.cfg.health_check_period_s * 3))
        for rec in list(self.actors.values()):
            if rec.state in (PENDING_CREATION, RESTARTING):
                asyncio.get_running_loop().create_task(self._create_actor(rec))
            elif rec.state == ALIVE:
                info = self.nodes.get(rec.node_id)
                hosted = self._hosted.get(rec.node_id, {})
                if (info is None or not info.alive
                        or rec.actor_id.hex() not in hosted):
                    await self._reconstruct_actor(
                        rec, "worker lost during GCS failover")

    async def _health_loop(self):
        period = self.cfg.health_check_period_s
        timeout = period * self.cfg.health_check_failure_threshold
        while not self._stopping:
            await asyncio.sleep(period)
            now = time.time()
            for nid, info in list(self.nodes.items()):
                if info.alive and now - self.last_seen.get(nid, now) > timeout:
                    await self._on_node_death(nid, "health check timeout")
            # watchdog sweep: beacons whose owner stopped reporting, and
            # straggler candidates that crossed k x p95 since last report
            try:
                self.health.check(now)
                self._drain_health_events()
            except Exception:
                logger.exception("health watchdog sweep failed")

    async def _on_node_death(self, node_id: NodeID, reason: str):
        info = self.nodes.get(node_id)
        if info is None or not info.alive:
            return
        info.alive = False
        self.available.pop(node_id, None)
        # drop the dead node's agent-pushed stats: the dashboard must not
        # export a frozen last sample forever
        self.kv.pop(("node_stats", node_id.binary()), None)
        # ...and its beacons: node death is already attributed; those
        # loops must not also fire as anonymous stalls
        self.health.forget_node(node_id.hex())
        # ...and its memory attribution: the store died with the node
        self.memory.forget_node(node_id.hex())
        # ...and prefix-directory entries whose exported page groups were
        # owned there: their primary copies died with the store, so a
        # lookup must miss (and the requester re-prefill) rather than
        # hand out a dangling ref.
        node_hex = node_id.hex()
        stale = [h for h, e in self.prefix_dir.items()
                 if e.get("owner_node") == node_hex]
        for h in stale:
            del self.prefix_dir[h]
        if stale:
            self.prefix_dir_stats["dropped"] += len(stale)
        logger.warning("node %s dead: %s", node_id.hex()[:8], reason)
        await self._publish("node", {"node_id": node_id, "alive": False})
        # Restart actors that lived there (ref: gcs_actor_manager.cc:1100).
        for rec in list(self.actors.values()):
            if rec.node_id == node_id and rec.state == ALIVE:
                await self._reconstruct_actor(rec, f"node died: {reason}")
        # Release placement-group bundles on the dead node; PGs with STRICT
        # placement become (partially) unplaced — reschedule best-effort.
        for pgid, pg in self.pgs.items():
            changed = False
            for b in pg["bundles"]:
                if b.get("node_id") == node_id:
                    b["node_id"] = None
                    changed = True
            if changed:
                self._wal("pgs", pgid, pg, strict=False)  # node-death path
                self._mark_dirty()
                await self._try_place_pg(pgid)

    # -------------------------------------------------------------- membership

    async def rpc_register_node(self, info: NodeInfo,
                                hosted: Optional[dict] = None) -> dict:
        self.nodes[info.node_id] = info
        self.available[info.node_id] = info.resources_total.copy()
        self.last_seen[info.node_id] = time.time()
        from ray_tpu.devtools.chaos import note_peer
        note_peer(tuple(info.nodelet_addr), "nodelet")
        # A rejoining nodelet reports the actors it hosts; adopt them so a
        # restarted GCS doesn't double-create actors whose creation landed
        # after the last snapshot (ref: failover reconstruction).
        self._hosted[info.node_id] = hosted or {}
        for aid_hex, h in (hosted or {}).items():
            for rec in self.actors.values():
                if rec.actor_id.hex() == aid_hex and rec.state != ALIVE:
                    rec.state = ALIVE
                    rec.address = tuple(h["addr"])
                    rec.worker_id = h["worker_id"]
                    rec.node_id = info.node_id
                    await self._publish_actor(rec)
        await self._publish("node", {"node_id": info.node_id, "alive": True})
        return {"ok": True, "config": self.cfg.to_json()}

    async def rpc_heartbeat(self, node_id: NodeID, seqno: int,
                            available: ResourceSet,
                            pending_leases: int = 0,
                            infeasible: Optional[List[dict]] = None) -> dict:
        # ref: ray_syncer.h versioned snapshots — stale seqnos are dropped.
        if seqno >= self.heartbeat_seq.get(node_id, -1):
            self.heartbeat_seq[node_id] = seqno
            if node_id in self.nodes:
                self.available[node_id] = available
                self.pending_leases[node_id] = pending_leases
        if infeasible is not None:
            # permanently-infeasible lease asks the nodelet queued (no
            # node fits, no spillback target): replace this nodelet's
            # prior rows so the autoscaler sees current state, not a
            # history (ref: infeasible queue -> autoscaler state)
            src = f"nodelet:{node_id.hex()}"
            self.unmet_demand = [d for d in self.unmet_demand
                                 if d.get("source") != src]
            for row in infeasible:
                self.unmet_demand.append({
                    "resources": dict(row.get("resources") or {}),
                    "ts": float(row.get("ts", time.time())),
                    "source": src})
            del self.unmet_demand[:-100]
        self.last_seen[node_id] = time.time()
        if node_id not in self.nodes:
            # Fresh GCS after restart: membership is rebuilt from the
            # still-running nodelets (ref: clients resubscribe/re-register
            # after GCS failover, _raylet.pyx _auto_reconnect).
            return {"ok": False, "reregister": True}
        info = self.nodes.get(node_id)
        if info is not None and not info.alive:
            # Node came back (e.g. transient stall) — reference treats this as
            # a new node; we resurrect membership.
            info.alive = True
            await self._publish("node", {"node_id": node_id, "alive": True})
        return {"ok": True}

    async def rpc_drain_node(self, node_id: NodeID) -> dict:
        await self._on_node_death(node_id, "drained")
        return {"ok": True}

    async def rpc_get_nodes(self) -> List[NodeInfo]:
        return list(self.nodes.values())

    async def rpc_get_available_resources(self) -> Dict[bytes, Dict[str, float]]:
        return {nid.binary(): rs.quantities for nid, rs in self.available.items()}

    async def rpc_get_load(self) -> dict:
        """Cluster load for the autoscaler (ref: LoadMetrics
        load_metrics.py:63 fed from GCS resource state)."""
        now = time.time()
        demand = [d for d in self.unmet_demand if now - d["ts"] < 30.0]
        # gang shortfalls (elastic training): one row per missing worker,
        # tagged with the gang so the autoscaler can attribute the launch
        for reporter, g in list(self.gang_demand.items()):
            if now - g["ts"] >= 30.0:
                del self.gang_demand[reporter]
                continue
            demand.extend({"resources": dict(g["resources"]), "ts": g["ts"],
                           "gang": g["name"]}
                          for _ in range(min(int(g["count"]), 16)))
        return {
            "pending_leases": {nid.hex(): n
                               for nid, n in self.pending_leases.items()},
            "unmet_demand": demand,
            "idle_nodes": [nid.hex() for nid, info in self.nodes.items()
                           if info.alive and self.available.get(nid) is not None
                           and self.available[nid].quantities ==
                           info.resources_total.quantities],
        }

    async def rpc_report_gang_demand(self, name: str, reporter: str,
                                     resources: Dict[str, float],
                                     count: int,
                                     seq: Optional[int] = None) -> dict:
        """An elastic gang (ray_tpu.train.elastic) is `count` workers
        short of its target. Reporter-keyed with a timestamp — the same
        idempotent, staleness-aged shape the serve controller's
        report_load uses — so re-reports replace rather than accumulate,
        count=0 clears, and a dead coordinator's row ages out.

        ``seq`` is the reporter's monotonic sequence number: a delayed
        or duplicated stale report (reordered under partition, or
        chaos-injected) must not overwrite — or resurrect after a
        count=0 clear — a newer row. seq=None keeps the old
        last-writer-wins semantics for legacy reporters."""
        if seq is not None:
            last = self._gang_demand_seq.get(reporter, -1)
            if seq <= last:
                return {"ok": True, "stale": True}
            self._gang_demand_seq[reporter] = seq
        if count <= 0:
            self.gang_demand.pop(reporter, None)
        else:
            self.gang_demand[reporter] = {
                "name": name, "resources": dict(resources),
                "count": int(count), "ts": time.time()}
        return {"ok": True}

    async def rpc_report_remediation(self, event: dict) -> dict:
        """An elastic coordinator reports a remediation action (shrink,
        refill, grow, degraded start). Folded into the health event
        stream: timeline instant + log line via _drain_health_events,
        visible in health_report()/`cli doctor`."""
        self.health.observe_remediation(dict(event))
        self._drain_health_events()
        return {"ok": True}

    # ------------------------------------------------------------- scheduling

    async def rpc_pick_node(self, resources: ResourceSet, strategy_kind: str = "DEFAULT",
                            exclude: Optional[list] = None) -> Optional[dict]:
        """Spillback target selection (ref: ClusterResourceScheduler::
        GetBestSchedulableNode, cluster_resource_scheduler.cc:129).

        Delegates to the standalone policy suite (scheduling_policy.py):
        DEFAULT -> HybridPolicy (truncated critical-utilization score,
        top-k pick), SPREAD -> round-robin over available nodes."""
        exclude_set = set(exclude) if exclude else set()
        snapshot = [
            SchedNode(node_id=nid, total=info.resources_total,
                      available=self.available.get(nid, ResourceSet()),
                      alive=info.alive)
            for nid, info in self.nodes.items() if nid not in exclude_set]
        if strategy_kind == "SPREAD":
            nid = self._spread_policy.schedule(resources, snapshot)
        else:
            nid = self._hybrid_policy.schedule(resources, snapshot)
        if nid is None:
            # record unmet demand for the autoscaler
            # (ref: infeasible queue -> gcs_autoscaler_state_manager.h)
            self.unmet_demand.append({"resources": resources.quantities,
                                      "ts": time.time()})
            del self.unmet_demand[:-100]
            return None
        return {"node_id": nid, "addr": self.nodes[nid].nodelet_addr}

    # ------------------------------------------------------------------ actors

    async def rpc_register_actor(self, spec: TaskSpec) -> dict:
        """ref: gcs_actor_manager.cc:246 RegisterActor. Idempotent: clients
        retry across GCS restarts (gcs_call auto-reconnect), so a replayed
        registration of an already-known actor_id must succeed without
        double-creating."""
        if spec.actor_id in self.actors:
            return {"ok": True}
        if spec.actor_name:
            key = (spec.namespace, spec.actor_name)
            if key in self.named_actors and self.named_actors[key] != spec.actor_id:
                existing = self.actors[self.named_actors[key]]
                if existing.state != DEAD:
                    return {"ok": False, "error": f"actor name {key} taken"}
            self.named_actors[key] = spec.actor_id
            self._wal("named_actors", key, spec.actor_id)
        rec = ActorRecord(spec)
        self.actors[spec.actor_id] = rec
        # Write-through: registration must survive an immediate GCS crash
        # (ref: Redis-backed GcsTableStorage persists before the reply) —
        # one WAL record, not a whole-state snapshot per registration.
        self._wal("actors", spec.actor_id, rec)
        asyncio.get_running_loop().create_task(self._create_actor(rec))
        return {"ok": True}

    async def _create_actor(self, rec: ActorRecord):
        """Lease a worker somewhere and push the creation task
        (ref: gcs_actor_scheduler.h lease-based actor scheduling)."""
        spec = rec.spec
        deadline = time.time() + self.cfg.worker_lease_timeout_s * 10
        # Stable per-incarnation idempotency token: every retry of THIS
        # creation attempt (e.g. after a dropped response) carries the
        # same token, so the nodelet replays the recorded placement
        # instead of leasing a second worker and running __init__ twice.
        # A restart bumps num_restarts and legitimately creates anew.
        idem = f"{rec.actor_id.hex()}:{rec.num_restarts}"
        while not self._stopping:
            target = await self._pick_for_spec(spec)
            if target is None:
                if time.time() > deadline:
                    rec.state = DEAD
                    rec.death_cause = "no feasible node for actor resources"
                    await self._publish_actor(rec)
                    return
                await asyncio.sleep(0.2)
                continue
            nid = target["node_id"]
            client = self.pool.get(tuple(target["addr"]))
            try:
                # Creation waits on a worker lease + __init__, so it gets
                # its own bound rather than the default rpc deadline.
                r = await client.call(
                    "create_actor", spec=spec, idem=idem,
                    timeout=self.cfg.worker_start_timeout_s
                    + self.cfg.worker_lease_timeout_s + 10.0)
            except (ConnectionLost, RemoteError, OSError) as e:
                logger.warning("actor create on %s failed: %s", nid.hex()[:8], e)
                await asyncio.sleep(0.2)
                continue
            if not r.get("ok"):
                if r.get("retryable", True):
                    await asyncio.sleep(0.2)
                    continue
                rec.state = DEAD
                rec.death_cause = r.get("error", "creation failed")
                await self._publish_actor(rec)
                return
            rec.state = ALIVE
            rec.address = tuple(r["worker_addr"])
            rec.worker_id = r["worker_id"]
            rec.node_id = nid
            await self._publish_actor(rec)
            return

    async def _pick_for_spec(self, spec: TaskSpec) -> Optional[dict]:
        if spec.scheduling.kind == "PLACEMENT_GROUP":
            pg = self.pgs.get(spec.scheduling.pg_id)
            if pg is None:
                return None
            idx = spec.scheduling.bundle_index
            bundles = pg["bundles"]
            cands = [bundles[idx]] if idx >= 0 else bundles
            for b in cands:
                if b.get("node_id") is not None:
                    info = self.nodes.get(b["node_id"])
                    if info and info.alive:
                        return {"node_id": b["node_id"], "addr": info.nodelet_addr}
            return None
        if spec.scheduling.kind == "NODE_AFFINITY":
            info = self.nodes.get(spec.scheduling.node_id)
            if info and info.alive:
                return {"node_id": info.node_id, "addr": info.nodelet_addr}
            if not spec.scheduling.soft:
                return None
        return await self.rpc_pick_node(resources=spec.resources,
                                        strategy_kind=spec.scheduling.kind)

    async def _reconstruct_actor(self, rec: ActorRecord, cause: str):
        """ref: gcs_actor_manager.cc:1100 ReconstructActor."""
        unlimited = rec.max_restarts < 0
        if not unlimited and rec.num_restarts >= rec.max_restarts:
            rec.state = DEAD
            rec.death_cause = cause
            await self._publish_actor(rec)
            return
        rec.num_restarts += 1
        rec.state = RESTARTING
        rec.address = None
        await self._publish_actor(rec)
        await self._create_actor(rec)

    async def rpc_report_worker_death(self, worker_id: bytes, node_id: NodeID,
                                      intentional: bool = False,
                                      reason: str = "worker died",
                                      actor_id=None) -> dict:
        # actor_id scopes the report to one lane of a lane-host worker
        # (the process survives, only that actor died)
        for rec in list(self.actors.values()):
            if rec.worker_id == worker_id and rec.state == ALIVE and (
                    actor_id is None or rec.actor_id == actor_id):
                if intentional:
                    rec.state = DEAD
                    rec.death_cause = reason
                    await self._publish_actor(rec)
                else:
                    await self._reconstruct_actor(rec, reason)
        return {"ok": True}

    async def rpc_kill_actor(self, actor_id: ActorID, no_restart: bool = True) -> dict:
        rec = self.actors.get(actor_id)
        if rec is None:
            return {"ok": False, "error": "no such actor"}
        if no_restart:
            rec.max_restarts = rec.num_restarts  # exhaust budget
        if rec.address is not None and rec.node_id in self.nodes:
            client = self.pool.get(self.nodes[rec.node_id].nodelet_addr)
            try:
                # actor_id lets a lane-host nodelet kill ONLY this lane
                await client.call("kill_worker", worker_id=rec.worker_id,
                                  actor_id=actor_id, reason="ray_tpu.kill",
                                  timeout=10.0)
            except (ConnectionLost, RemoteError, OSError):
                pass
        if no_restart:
            rec.state = DEAD
            rec.death_cause = "killed via ray_tpu.kill"
            await self._publish_actor(rec)
        return {"ok": True}

    async def rpc_get_actor(self, actor_id: ActorID) -> Optional[dict]:
        rec = self.actors.get(actor_id)
        return rec.view() if rec else None

    async def rpc_get_named_actor(self, name: str, namespace: str = "default") -> Optional[dict]:
        aid = self.named_actors.get((namespace, name))
        if aid is None:
            return None
        rec = self.actors.get(aid)
        if rec is None or rec.state == DEAD:
            return None
        return {"spec": rec.spec, "view": rec.view()}

    async def rpc_list_actors(self) -> List[dict]:
        return [r.view() for r in self.actors.values()]

    async def rpc_wait_actor_alive(self, actor_id: ActorID, wait_timeout: float = 30.0) -> dict:
        deadline = time.time() + wait_timeout
        while time.time() < deadline:
            rec = self.actors.get(actor_id)
            if rec is not None and rec.state == ALIVE:
                return {"ok": True, "view": rec.view()}
            if rec is not None and rec.state == DEAD:
                return {"ok": False, "view": rec.view()}
            await asyncio.sleep(0.05)
        # timed out: return the current view so callers can tell a
        # still-starting actor (keep waiting) from an unknown id (fail)
        rec = self.actors.get(actor_id)
        return {"ok": False, "view": rec.view() if rec is not None else None}

    async def _publish_actor(self, rec: ActorRecord):
        await self._publish(f"actor:{rec.actor_id.hex()}", rec.view())
        # every FSM transition; no RPC caller to fail -> non-strict
        self._wal("actors", rec.actor_id, rec, strict=False)
        self._mark_dirty()

    # -------------------------------------------------------- placement groups

    async def rpc_create_placement_group(self, pg_id: PlacementGroupID,
                                         bundles: List[ResourceSet],
                                         strategy: str = "PACK",
                                         name: str = "") -> dict:
        """2-phase reservation across nodelets
        (ref: gcs_placement_group_scheduler.h PREPARE/COMMIT)."""
        self.pgs[pg_id] = {
            "pg_id": pg_id,
            "bundles": [{"resources": b, "node_id": None, "index": i}
                        for i, b in enumerate(bundles)],
            "strategy": strategy,
            "name": name,
            "state": "PENDING",
        }
        ok = await self._try_place_pg(pg_id)
        self._wal("pgs", pg_id, self.pgs.get(pg_id))
        self._mark_dirty()
        return {"ok": ok, "state": self.pgs[pg_id]["state"]}

    def _record_pg_demand(self, pg_id: PlacementGroupID,
                          unplaced: List[dict]) -> None:
        """A PENDING placement group is unmet demand too (ref: the
        autoscaler counts pending PG bundles, resource_demand_scheduler):
        one row per unplaced bundle, replacing this pg's prior rows so
        retries don't accumulate."""
        tag = pg_id.hex()
        now = time.time()
        self.unmet_demand = [d for d in self.unmet_demand
                             if d.get("pg") != tag]
        for b in unplaced:
            res = b["resources"]
            self.unmet_demand.append({
                "resources": dict(getattr(res, "quantities", res)),
                "ts": now, "pg": tag})
        del self.unmet_demand[:-100]

    def _clear_pg_demand(self, pg_id: PlacementGroupID) -> None:
        tag = pg_id.hex()
        self.unmet_demand = [d for d in self.unmet_demand
                             if d.get("pg") != tag]

    async def _try_place_pg(self, pg_id: PlacementGroupID) -> bool:
        pg = self.pgs[pg_id]
        strategy = pg["strategy"]
        unplaced = [b for b in pg["bundles"] if b["node_id"] is None]
        if not unplaced:
            pg["state"] = "CREATED"
            self._clear_pg_demand(pg_id)
            self._wal("pgs", pg_id, pg)
            self._mark_dirty()
            return True
        # Phase 0: plan via the standalone bundle-packing policy
        # (ref: bundle_scheduling_policy.cc), honoring bundles already
        # placed by a previous partial attempt / node-failure replacement.
        placed_on_by_strict = set(
            b["node_id"] for b in pg["bundles"] if b["node_id"] is not None)
        snapshot = [
            SchedNode(node_id=nid, total=info.resources_total,
                      available=self.available.get(nid, ResourceSet()),
                      alive=info.alive)
            for nid, info in self.nodes.items()]
        if strategy == "STRICT_PACK" and placed_on_by_strict:
            # the gang already lives on one node; the rest must join it
            snapshot = [n for n in snapshot
                        if n.node_id in placed_on_by_strict]
        exclude = placed_on_by_strict if strategy == "STRICT_SPREAD" \
            else None
        assignment = pack_bundles([b["resources"] for b in unplaced],
                                  snapshot, strategy,
                                  exclude_nodes=exclude)
        if assignment is None:
            pg["state"] = "PENDING"
            self._record_pg_demand(pg_id, unplaced)
            return False
        plan: List[Tuple[dict, NodeID]] = list(zip(unplaced, assignment))
        # Phase 1: PREPARE on each nodelet.
        prepared: List[Tuple[dict, NodeID]] = []
        for b, nid in plan:
            client = self.pool.get(self.nodes[nid].nodelet_addr)
            try:
                # tight bound: a gray nodelet must not stall the 2PC
                # prepare loop for the default deadline per bundle
                r = await client.call("pg_prepare", pg_id=pg_id, bundle_index=b["index"],
                                      resources=b["resources"], timeout=10.0)
            except (ConnectionLost, RemoteError, OSError):
                r = {"ok": False}
            if not r.get("ok"):
                for pb, pnid in prepared:  # rollback
                    try:
                        await self.pool.get(self.nodes[pnid].nodelet_addr).call(
                            "pg_return", pg_id=pg_id, bundle_index=pb["index"],
                            timeout=10.0)
                    except Exception:
                        pass
                pg["state"] = "PENDING"
                self._record_pg_demand(pg_id, unplaced)
                return False
            prepared.append((b, nid))
        # Phase 2: COMMIT.
        for b, nid in prepared:
            try:
                await self.pool.get(self.nodes[nid].nodelet_addr).call(
                    "pg_commit", pg_id=pg_id, bundle_index=b["index"],
                    timeout=10.0)
            except (ConnectionLost, RemoteError, OSError):
                pass
            b["node_id"] = nid
        pg["state"] = "CREATED"
        self._clear_pg_demand(pg_id)
        # placement succeeded through PREPARE/COMMIT: the bundle->node
        # assignments are now reservations held by nodelets and MUST
        # survive a GCS crash, or restore would double-reserve elsewhere
        self._wal("pgs", pg_id, pg, strict=False)
        self._mark_dirty()
        await self._publish(f"pg:{pg_id.hex()}", {"state": "CREATED"})
        return True

    async def rpc_remove_placement_group(self, pg_id: PlacementGroupID) -> dict:
        pg = self.pgs.pop(pg_id, None)
        self._clear_pg_demand(pg_id)
        if pg is None:
            return {"ok": False}
        self._wal("pgs", pg_id, None)
        self._mark_dirty()
        for b in pg["bundles"]:
            nid = b.get("node_id")
            if nid is not None and nid in self.nodes:
                try:
                    await self.pool.get(self.nodes[nid].nodelet_addr).call(
                        "pg_return", pg_id=pg_id, bundle_index=b["index"],
                        timeout=10.0)
                except Exception:
                    pass
        return {"ok": True}

    async def rpc_get_placement_group(self, pg_id: PlacementGroupID) -> Optional[dict]:
        pg = self.pgs.get(pg_id)
        if pg is None:
            return None
        return {"pg_id": pg_id, "state": pg["state"], "strategy": pg["strategy"],
                "name": pg["name"],
                "bundles": [{"index": b["index"], "node_id": b["node_id"],
                             "resources": b["resources"].quantities}
                            for b in pg["bundles"]]}

    async def rpc_list_placement_groups(self) -> List[dict]:
        """All placement groups in rpc_get_placement_group's view shape
        (ref: GcsPlacementGroupManager::HandleGetAllPlacementGroup)."""
        return [{"pg_id": pg_id, "state": pg["state"],
                 "strategy": pg["strategy"], "name": pg["name"],
                 "bundles": [{"index": b["index"], "node_id": b["node_id"],
                              "resources": b["resources"].quantities}
                             for b in pg["bundles"]]}
                for pg_id, pg in self.pgs.items()]

    async def rpc_wait_placement_group(self, pg_id: PlacementGroupID,
                                       wait_timeout: float = 30.0) -> dict:
        deadline = time.time() + wait_timeout
        while time.time() < deadline:
            pg = self.pgs.get(pg_id)
            if pg is None:
                return {"ok": False, "error": "removed"}
            if pg["state"] == "CREATED":
                return {"ok": True}
            await self._try_place_pg(pg_id)
            if self.pgs[pg_id]["state"] == "CREATED":
                return {"ok": True}
            await asyncio.sleep(0.2)
        return {"ok": False, "error": "timeout"}

    # ---------------------------------------------------------------- jobs/kv

    async def rpc_add_job(self, job_id: JobID, driver_addr: Address, meta: dict) -> dict:
        self.jobs[job_id] = {"job_id": job_id, "driver": driver_addr,
                             "meta": meta, "start": time.time(), "end": None}
        self._wal("jobs", job_id, self.jobs[job_id])
        self._mark_dirty()
        return {"ok": True}

    async def rpc_finish_job(self, job_id: JobID) -> dict:
        if job_id in self.jobs:
            self.jobs[job_id]["end"] = time.time()
            self._wal("jobs", job_id, self.jobs[job_id])
            self._mark_dirty()
        return {"ok": True}

    async def rpc_list_jobs(self) -> List[dict]:
        return list(self.jobs.values())

    async def rpc_kv_put(self, ns: str, key: bytes, value: bytes,
                         overwrite: bool = True) -> bool:
        k = (ns, key)
        if not overwrite and k in self.kv:
            # Idempotent for client retries across GCS restarts: replaying
            # the same first-write succeeds; a genuine conflict still fails.
            return self.kv[k] == value
        self.kv[k] = value
        self._wal("kv", k, value)
        self._mark_dirty()
        return True

    async def rpc_kv_get(self, ns: str, key: bytes) -> Optional[bytes]:
        return self.kv.get((ns, key))

    async def rpc_kv_del(self, ns: str, key: bytes) -> bool:
        existed = self.kv.pop((ns, key), None) is not None
        if existed:
            self._wal("kv", (ns, key), None)
            self._mark_dirty()
        return existed

    async def rpc_kv_exists(self, ns: str, key: bytes) -> bool:
        return (ns, key) in self.kv

    async def rpc_kv_keys(self, ns: str, prefix: bytes = b"") -> List[bytes]:
        return [k for (n, k) in self.kv if n == ns and k.startswith(prefix)]

    # ------------------------------------------------------------- task events

    async def rpc_add_task_events(self, events: List[dict]) -> dict:
        # ref: gcs_task_manager.h bounded task-event store for observability.
        self.task_events.extend(events)
        for ev in events:
            self.health.observe_task_event(ev)
        return {"ok": True}

    async def rpc_telemetry_report(self, report: dict) -> dict:
        """One batched report from a process's TelemetryAgent (ref:
        metrics_agent.py push): task events + spans extend the bounded
        event store, metric deltas merge into KV ns="metrics" (WAL'd like
        kv_put so scrapers survive failover), edge observations feed the
        EWMA edge model, and beacon snapshots feed the stall watchdog.
        The reply names the reporter's own stalled components so the
        stalled process can dump its flight recorder within one report
        interval of detection."""
        import json

        from ray_tpu.util.metrics import merge_payload

        events = report.get("events") or []
        if events:
            self.task_events.extend(events)
            for ev in events:
                self.health.observe_task_event(ev)
        stalled: List[str] = []
        beacons = report.get("beacons")
        if beacons:
            stalled = self.health.update(str(report.get("worker", "?")),
                                         report.get("node"), beacons)
            self._drain_health_events()
        mem = report.get("memory")
        if mem:
            self.memory.update(str(report.get("worker", "?")),
                               report.get("node"), mem)
        susp = report.get("rpc_suspicions")
        if susp:
            # rpc-deadline misses reported by callers: folded into
            # peer-suspicion health events (gray-failure evidence)
            self.health.observe_rpc_suspicions(
                str(report.get("worker", "?")), report.get("node"), susp)
            self._drain_health_events()
        for ob in report.get("edges") or []:
            self.edge_model.observe(ob.get("src"), ob.get("dst"),
                                    ob.get("nbytes", 0.0),
                                    ob.get("seconds", 0.0),
                                    ob.get("kind", "transfer"))
        dirty = False
        for delta in report.get("metrics") or []:
            name = delta.get("name")
            if not name:
                continue
            k = ("metrics", name.encode())
            try:
                base = json.loads(self.kv[k]) if k in self.kv else None
            except Exception:
                base = None
            value = json.dumps(merge_payload(base, delta)).encode()
            self.kv[k] = value
            self._wal("kv", k, value)
            dirty = True
        if dirty:
            self._mark_dirty()
        return {"ok": True, "stalled": stalled}

    def _drain_health_events(self) -> None:
        """New StallEvents become log lines + timeline instants, exactly
        once each (instants render in chrome_trace as 'i' markers on a
        per-worker health track)."""
        for ev in self.health.drain_fresh():
            logger.warning("health: %s %s worker=%s age=%.1fs context=%s",
                           ev.get("kind"), ev.get("component"),
                           ev.get("worker"), ev.get("age_s", 0.0),
                           ev.get("context"))
            self.task_events.append({
                "kind": "instant",
                "name": f"{ev.get('kind')}::{ev.get('component')}",
                "ts": ev.get("ts"), "worker": ev.get("worker"),
                "component": ev.get("component"),
                "age_s": ev.get("age_s"), "context": ev.get("context"),
            })

    async def rpc_health_report(self) -> dict:
        """The state-API / `cli doctor` view: every known beacon with
        its freshness, recent stall/straggler events, and the telemetry
        drop counters."""
        import json as _json

        rep = self.health.report()
        drops = {}
        for name in ("ray_tpu_task_events_dropped",
                     "ray_tpu_telemetry_reports_dropped"):
            raw = self.kv.get(("metrics", name.encode()))
            total = 0.0
            if raw:
                try:
                    payload = _json.loads(raw)
                    total = sum(s.get("value", 0.0)
                                for s in payload.get("series", []))
                except Exception:
                    total = 0.0
            drops[name] = total
        rep["drop_counters"] = drops
        rep["nodes_alive"] = sum(1 for n in self.nodes.values() if n.alive)
        rep["nodes_dead"] = sum(1 for n in self.nodes.values() if not n.alive)
        return rep

    async def rpc_memory_report(self, top_n: int = 20) -> dict:
        """Cluster memory attribution view (observability/memory.py):
        worker tracker snapshots folded by the aggregator, joined with
        the per-node store occupancy the nodelet agents push to KV
        ns="node_stats" — which also carries each nodelet's own tracker
        payload (primary-pin records), folded here on read."""
        import json as _json

        node_stats: Dict[str, dict] = {}
        for (ns, key) in list(self.kv):
            if ns != "node_stats":
                continue
            try:
                st = _json.loads(self.kv[(ns, key)])
            except Exception:
                continue
            node_hex = key.hex()
            node_stats[node_hex] = st
            mem = st.get("memory")
            if mem:
                self.memory.update(f"nodelet:{node_hex[:12]}", node_hex, mem)
        return self.memory.report(node_stats, top_n=top_n)

    # ------------------------------------------- global KV-prefix directory

    async def rpc_prefix_register(self, entries: List[dict]) -> dict:
        """serve/disagg: a prefill replica registers exported page-group
        objects, keyed by the group-boundary page-chain hash. Entry:
        {"hash", "ref", "owner", "owner_node", "nbytes", "group_tokens"}.
        First-writer-wins across owners (same rule as PagePool.register)
        so concurrent prefills of a shared prefix converge on one copy;
        a re-register by the incumbent owner refreshes its entry."""
        now = time.time()
        for e in entries:
            h = e["hash"]
            cur = self.prefix_dir.pop(h, None)
            if cur is not None and cur.get("owner") != e.get("owner"):
                e = cur   # keep the incumbent's ref, just refresh LRU
            e["last_touch"] = now
            self.prefix_dir[h] = e
            self.prefix_dir_stats["registered"] += 1
        cap = max(int(getattr(self.cfg, "gcs_prefix_dir_capacity", 4096)), 1)
        while len(self.prefix_dir) > cap:
            self.prefix_dir.popitem(last=False)
            self.prefix_dir_stats["evicted"] += 1
        return {"size": len(self.prefix_dir)}

    async def rpc_prefix_lookup(self, hashes: List[bytes]) -> List[Optional[dict]]:
        """Resolve the longest warm leading run of page groups: one entry
        (or None) per group-boundary hash, in order, stopping at the
        first miss — a group is only adoptable if every group before it
        is too (chain hashes encode position, not just content)."""
        now = time.time()
        out: List[Optional[dict]] = []
        miss = False
        for h in hashes:
            e = None if miss else self.prefix_dir.get(h)
            if e is None:
                miss = True
                self.prefix_dir_stats["misses"] += 1
                out.append(None)
            else:
                e["last_touch"] = now
                self.prefix_dir.move_to_end(h)
                self.prefix_dir_stats["hits"] += 1
                out.append(dict(e))
        return out

    async def rpc_prefix_drop(self, hashes: List[bytes],
                              owner: str = "") -> int:
        """A prefill replica evicted retained groups locally (or is
        draining): its directory entries must go too, or lookups hand
        out refs whose primaries are about to be unpinned. With owner
        set, only that owner's entries drop (a different owner may have
        re-registered the hash since)."""
        n = 0
        for h in hashes:
            e = self.prefix_dir.get(h)
            if e is None:
                continue
            if owner and e.get("owner") != owner:
                continue
            del self.prefix_dir[h]
            n += 1
        if n:
            self.prefix_dir_stats["dropped"] += n
        return n

    async def rpc_prefix_stats(self) -> dict:
        st = dict(self.prefix_dir_stats)
        st["size"] = len(self.prefix_dir)
        st["capacity"] = int(getattr(self.cfg, "gcs_prefix_dir_capacity",
                                     4096))
        return st

    async def rpc_edge_stats(self) -> Dict[str, dict]:
        return self.edge_model.stats()

    async def rpc_list_task_events(self, limit: int = 1000,
                                   job_id: Optional[JobID] = None) -> List[dict]:
        out = []
        for ev in reversed(self.task_events):
            if job_id is not None and ev.get("job_id") != job_id:
                continue
            out.append(ev)
            if len(out) >= limit:
                break
        return out

    # ----------------------------------------------------------------- pubsub

    async def rpc_subscribe(self, channel: str, addr: Address) -> dict:
        self.subscribers[channel].add(tuple(addr))
        self._wal("subscribers", channel, self.subscribers[channel])
        self._mark_dirty()
        return {"ok": True}

    async def rpc_unsubscribe(self, channel: str, addr: Address) -> dict:
        self.subscribers[channel].discard(tuple(addr))
        self._wal("subscribers", channel, self.subscribers[channel])
        self._mark_dirty()
        return {"ok": True}

    async def rpc_publish(self, channel: str, message: Any) -> dict:
        await self._publish(channel, message)
        return {"ok": True}

    async def _publish(self, channel: str, message: Any):
        dead = []
        # snapshot: subscribe/unsubscribe coroutines can mutate the set
        # while the oneway push awaits ("Set changed size during
        # iteration" otherwise)
        for addr in tuple(self.subscribers.get(channel, ())):  # push model
            try:
                await self.pool.get(addr).oneway("pubsub_message",
                                                channel=channel, message=message)
            except (ConnectionLost, OSError):
                dead.append(addr)
        for addr in dead:
            self.subscribers[channel].discard(addr)
            self.pool.drop(addr)

    # ------------------------------------------------------------ persistence

    def _snapshot_path(self) -> Optional[str]:
        if self.cfg.gcs_storage == "file" and self.cfg.gcs_file_storage_path:
            return os.path.join(self.cfg.gcs_file_storage_path, "gcs_snapshot.pkl")
        return None

    def _mark_dirty(self):
        self._dirty = True

    def _wal(self, table: str, key, value, strict: bool = True):
        """Durably log one mutation BEFORE the RPC reply (value=None is a
        delete). Restore = snapshot + replay; see gcs_storage.py.

        strict=True (mutation RPC handlers): an append failure raises, so
        the RPC FAILS instead of acking a write that won't survive a crash
        (ref: the Redis-backed table storage fails the request when the
        store write fails). strict=False (background FSM transitions with
        no caller to fail): log and continue — in-memory state stays
        authoritative until the disk recovers."""
        try:
            self.storage.append(pickle.dumps((table, key, value),
                                             protocol=4))
        except Exception:
            logger.exception("gcs wal append failed (table=%s)", table)
            if strict:
                raise RuntimeError(
                    "GCS storage append failed; write not durable") from None

    async def _snapshot_loop(self):
        """Debounced persistence: at most one snapshot per period
        (ref: Redis-backed GcsTableStorage writes per-mutation; a periodic
        whole-state snapshot gives the same restart guarantee here)."""
        while not self._stopping:
            await asyncio.sleep(0.5)
            if self._dirty:
                self._dirty = False
                await self._snapshot_async()

    def _snapshot_bytes(self) -> bytes:
        return pickle.dumps({"kv": self.kv, "named_actors": self.named_actors,
                             "jobs": self.jobs, "actors": self.actors,
                             "pgs": self.pgs,
                             "subscribers": dict(self.subscribers)})

    async def _snapshot_async(self):
        """Pickle on the loop (consistent state view; the WAL rotates at
        the same instant, so snapshot+newer-segments is always complete),
        write off-loop so heartbeats/leases aren't blocked on disk."""
        path = self._snapshot_path()
        if not path:
            return
        try:
            data = self._snapshot_bytes()
            watermark = self.storage.rotate()
            await asyncio.to_thread(self.storage.commit_snapshot, data,
                                    watermark)
        except Exception:
            logger.exception("gcs snapshot failed")

    def _maybe_restore(self):
        try:
            snap, records = self.storage.restore()
        except Exception:
            logger.exception("gcs restore failed")
            return
        if snap is not None:
            try:
                data = pickle.loads(snap)
                self.kv = data.get("kv", {})
                self.named_actors = data.get("named_actors", {})
                self.jobs = data.get("jobs", {})
                self.actors = data.get("actors", {})
                self.pgs = data.get("pgs", {})
                for ch, addrs in data.get("subscribers", {}).items():
                    self.subscribers[ch] |= set(addrs)
            except Exception:
                logger.exception("gcs snapshot restore failed")
        replayed = 0
        for raw in records:
            try:
                table, key, value = pickle.loads(raw)
            except Exception:
                continue
            if table == "subscribers":
                if value is None:
                    self.subscribers.pop(key, None)
                else:
                    self.subscribers[key] = set(value)
                replayed += 1
                continue
            tab = getattr(self, table, None)
            if not isinstance(tab, dict):
                continue
            if value is None:
                tab.pop(key, None)
            else:
                tab[key] = value
            replayed += 1
        if snap is not None or replayed:
            logger.info(
                "gcs restored %d kv entries, %d actors, %d pgs "
                "(+%d WAL records)", len(self.kv), len(self.actors),
                len(self.pgs), replayed)

    async def rpc_ping(self) -> dict:
        return {"ok": True, "time": time.time()}

    async def rpc_shutdown(self) -> dict:
        self._stopping = True
        try:
            self.storage.close()   # final fsync of the live WAL segment
        except Exception:
            pass
        asyncio.get_running_loop().call_later(0.05, _exit_soon)
        return {"ok": True}


def _exit_soon():
    os._exit(0)


def main():
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--config", default="{}")
    parser.add_argument("--ready-fd", type=int, default=-1)
    args = parser.parse_args()

    logging.basicConfig(level=logging.INFO,
                        format="[gcs] %(asctime)s %(levelname)s %(message)s")
    cfg = Config.from_json(args.config)

    async def run():
        gcs = GcsServer(cfg)
        host, port = await gcs.start(args.host, args.port)
        if args.ready_fd >= 0:
            os.write(args.ready_fd, f"{host}:{port}\n".encode())
            os.close(args.ready_fd)
        logger.info("gcs listening on %s:%d", host, port)
        while True:
            await asyncio.sleep(3600)

    asyncio.run(run())


if __name__ == "__main__":
    main()
