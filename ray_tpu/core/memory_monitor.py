"""Node OOM defense: memory monitor + worker-killing policies.

Reference: src/ray/common/memory_monitor.h:52 (MemoryMonitor polls system /
cgroup usage on a timer and fires a callback above a usage threshold) and
src/ray/raylet/worker_killing_policy_group_by_owner.h /
worker_killing_policy_retriable_fifo.h (pick which worker dies: group tasks
by owner so every owner keeps making progress, kill the newest member of
the largest group; or kill retriable tasks newest-first). The raylet kills
the chosen worker, the owner's task FSM sees the death and retries
(ray_config_def.h:74 default threshold 0.95, :100 OOM-specific retries).

TPU re-design notes: host RAM pressure matters mostly for the data/ingest
plane (Arrow blocks, spill staging); HBM pressure is handled separately by
the device-tier object accounting. The monitor therefore watches host
memory (cgroup v2 when present, else /proc/meminfo) and only ever kills
*worker* processes — never the nodelet or the store segment.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import List, Optional, Tuple

_CGROUP_V2_USAGE = "/sys/fs/cgroup/memory.current"
_CGROUP_V2_LIMIT = "/sys/fs/cgroup/memory.max"
_CGROUP_V1_USAGE = "/sys/fs/cgroup/memory/memory.usage_in_bytes"
_CGROUP_V1_LIMIT = "/sys/fs/cgroup/memory/memory.limit_in_bytes"


def _read_int(path: str) -> Optional[int]:
    try:
        with open(path) as f:
            s = f.read().strip()
        if s == "max":
            return None
        return int(s)
    except (OSError, ValueError):
        return None


def _meminfo() -> Tuple[Optional[int], Optional[int]]:
    """(used, total) from /proc/meminfo, used = total - MemAvailable."""
    total = avail = None
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = int(line.split()[1]) * 1024
                elif line.startswith("MemAvailable:"):
                    avail = int(line.split()[1]) * 1024
                if total is not None and avail is not None:
                    break
    except OSError:
        return None, None
    if total is None or avail is None:
        return None, total
    return total - avail, total


def get_memory_usage() -> Tuple[int, int]:
    """Current (used_bytes, total_bytes) for this node.

    Prefers the cgroup limit when one is set and tighter than physical RAM
    (containerized nodes), mirroring MemoryMonitor::GetMemoryBytes.
    """
    used, total = _meminfo()
    for upath, lpath in ((_CGROUP_V2_USAGE, _CGROUP_V2_LIMIT),
                         (_CGROUP_V1_USAGE, _CGROUP_V1_LIMIT)):
        climit = _read_int(lpath)
        cused = _read_int(upath)
        if climit is not None and cused is not None and (
                total is None or climit < total):
            return cused, climit
    if used is None or total is None:
        return 0, 1
    return used, total


@dataclass
class KillCandidate:
    """What the policy knows about a running worker."""
    worker_id: bytes
    job_id: Optional[bytes]         # owner grouping key
    is_actor: bool                  # actors are never retriable w/o restarts
    retriable: bool                 # stateless tasks retry by default
    start_time: float               # lease/creation time (newest dies first)


def pick_worker_to_kill(candidates: List[KillCandidate],
                        policy: str = "group_by_owner"
                        ) -> Optional[KillCandidate]:
    """Choose the worker to kill under memory pressure.

    group_by_owner (ref: worker_killing_policy_group_by_owner.h): group by
    (job, retriable); prefer retriable groups, then larger groups — so the
    last task of an owner is only killed when every group is a singleton —
    and kill the newest member (LIFO), which has done the least work.

    retriable_fifo (ref: worker_killing_policy.h RetriableFIFO): kill the
    newest retriable worker; fall back to the newest non-retriable.
    """
    if not candidates:
        return None
    if policy == "retriable_fifo":
        pool = [c for c in candidates if c.retriable] or list(candidates)
        return max(pool, key=lambda c: c.start_time)
    groups: dict = {}
    for c in candidates:
        groups.setdefault((not c.retriable, c.job_id), []).append(c)
    # Sort groups: retriable first (False<True), bigger first; tie → group
    # holding the globally newest member.
    def group_key(item):
        (nonretriable, _job), members = item
        return (nonretriable, -len(members),
                -max(m.start_time for m in members))
    _, members = sorted(groups.items(), key=group_key)[0]
    return max(members, key=lambda c: c.start_time)


class MemoryMonitor:
    """Threshold watcher; the nodelet drives it from an async loop.

    usage_fraction() reads the live system numbers unless a test override
    file is configured (tests write a bare float to it, mirroring how the
    reference fakes usage in memory_monitor_test.cc).
    """

    def __init__(self, threshold: float,
                 test_usage_file: str = ""):
        self.threshold = threshold
        self.test_usage_file = test_usage_file
        self.kills = 0

    def usage_fraction(self) -> float:
        if self.test_usage_file:
            try:
                with open(self.test_usage_file) as f:
                    return float(f.read().strip())
            except (OSError, ValueError):
                return 0.0
        used, total = get_memory_usage()
        return used / max(total, 1)

    def above_threshold(self) -> bool:
        return self.usage_fraction() > self.threshold
