"""Shared runtime structures: addresses, task specs, object refs, resources.

Reference: src/ray/common/task/task_spec.h (TaskSpecification),
src/ray/common/scheduling/ (ResourceSet), python/ray/_raylet.pyx ObjectRef.

The resource model departs from the reference's flat {CPU, GPU, custom} map:
TPU hosts are described by labeled quantities {CPU, TPU (chips), memory} plus
topology labels (slice name, ICI coordinates) carried on the node record, so
gang placement can reserve whole ICI-connected shapes (SURVEY.md §7).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core.ids import ActorID, JobID, NodeID, ObjectID, PlacementGroupID, TaskID, WorkerID

Address = Tuple[str, int]  # (host, port)


@dataclass(frozen=True)
class RuntimeAddress:
    """Where an owner/worker runtime can be reached (ref: rpc::Address)."""
    host: str
    port: int
    worker_id: bytes = b""

    @property
    def addr(self) -> Address:
        return (self.host, self.port)


class ObjectRef:
    """A first-class future for a task return or put object.

    Carries the owner's runtime address — ownership is embedded in the ref so
    any holder can reach the owner for liveness/location/refcount traffic
    (ref: reference_count.h:59 borrower protocol; ObjectRef in _raylet.pyx).

    Refcounting: ObjectRef registers itself with the in-process runtime on
    construction and deregisters on __del__; remote holders count via the
    borrow protocol in ray_tpu.core.refcount.
    """

    __slots__ = ("id", "owner", "_runtime", "__weakref__")

    def __init__(self, oid: ObjectID, owner: RuntimeAddress, _register: bool = True):
        self.id = oid
        self.owner = owner
        self._runtime = None
        if _register:
            from ray_tpu.core import runtime as rt

            r = rt.current_runtime_or_none()
            if r is not None:
                self._runtime = r
                r.refs.on_ref_created(self.id, self.owner)

    def binary(self) -> bytes:
        return self.id.binary()

    def hex(self) -> str:
        return self.id.hex()

    def future(self):
        """concurrent.futures.Future resolving to the value (ref: .future())."""
        from ray_tpu.core import runtime as rt

        return rt.get_runtime().as_future(self)

    def __reduce__(self):
        # Serialization counts as a borrow: the deserializing process
        # registers with the owner via its runtime (refcount.py).
        return (_deserialize_ref, (self.id, self.owner))

    def __del__(self):
        r = self._runtime
        if r is not None:
            try:
                r.refs.on_ref_deleted(self.id, self.owner)
            except Exception:
                pass

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __hash__(self):
        return hash(self.id)

    def __repr__(self):
        return f"ObjectRef({self.id.hex()})"


# num_returns sentinel for generator tasks whose return refs are created
# incrementally as the executor yields (ref: task_manager.h:143-171
# streaming-generator refs / num_returns="dynamic").
STREAMING = -1


class ObjectRefGenerator:
    """Iterator over a streaming task's item refs, in yield order.

    next() blocks until the executor has reported the next item to the
    owner (or the stream ended: StopIteration, or errored: the task's
    exception — after all successfully-yielded items were consumed, like
    the reference's generator semantics). Only meaningful in the owning
    process; pass individual item refs, not the generator, to other tasks.
    """

    def __init__(self, task_id, owner: RuntimeAddress):
        self.task_id = task_id
        self.owner = owner
        self._index = 0

    def __iter__(self) -> "ObjectRefGenerator":
        return self

    def __next__(self) -> ObjectRef:
        from ray_tpu.core import runtime as rt

        # next_stream_ref returns None on clean end-of-stream (StopIteration
        # cannot ride through asyncio futures, so the sentinel keeps the
        # sync and async paths on one runtime call)
        ref = rt.get_runtime().next_stream_ref(self.task_id,
                                               self._index + 1)
        if ref is None:
            raise StopIteration
        self._index += 1
        return ref

    def __aiter__(self) -> "ObjectRefGenerator":
        return self

    async def __anext__(self) -> ObjectRef:
        import asyncio

        from ray_tpu.core import runtime as rt

        rt_ = rt.get_runtime()
        ref = await asyncio.get_running_loop().run_in_executor(
            None, rt_.next_stream_ref, self.task_id, self._index + 1)
        if ref is None:
            raise StopAsyncIteration
        self._index += 1
        return ref

    def completed(self) -> int:
        """Items reported so far (non-blocking)."""
        from ray_tpu.core import runtime as rt

        return rt.get_runtime().stream_progress(self.task_id)[0]

    def __reduce__(self):
        raise TypeError(
            "ObjectRefGenerator is only meaningful in the owning process; "
            "pass the individual item refs instead")

    def __del__(self):
        # Discarding the generator releases a backpressure-blocked
        # executor (its next report returns ok=False and it stops).
        # MUST be the deferred variant: a finalizer can run mid-allocation
        # inside the runtime's own stream-lock critical section, and
        # taking the lock here would self-deadlock.
        from ray_tpu.core import runtime as rt

        r = rt.current_runtime_or_none()
        if r is not None:
            try:
                r.drop_stream_soon(self.task_id)
            except Exception:
                pass

    def __repr__(self):
        return f"ObjectRefGenerator({self.task_id.hex()}, next={self._index + 1})"


def _deserialize_ref(oid: ObjectID, owner: RuntimeAddress) -> ObjectRef:
    return ObjectRef(oid, owner)


# --- resources --------------------------------------------------------------


@dataclass
class ResourceSet:
    """Labeled resource quantities. TPU chips are a first-class resource."""
    quantities: Dict[str, float] = field(default_factory=dict)

    def fits_in(self, avail: "ResourceSet") -> bool:
        return all(avail.quantities.get(k, 0.0) + 1e-9 >= v
                   for k, v in self.quantities.items())

    def subtract(self, other: "ResourceSet") -> None:
        for k, v in other.quantities.items():
            self.quantities[k] = self.quantities.get(k, 0.0) - v

    def add(self, other: "ResourceSet") -> None:
        for k, v in other.quantities.items():
            self.quantities[k] = self.quantities.get(k, 0.0) + v

    def copy(self) -> "ResourceSet":
        return ResourceSet(dict(self.quantities))

    @classmethod
    def from_options(cls, num_cpus: Optional[float], num_tpus: Optional[float],
                     memory: Optional[float], resources: Optional[Dict[str, float]],
                     default_cpus: float = 1.0) -> "ResourceSet":
        q: Dict[str, float] = {}
        q["CPU"] = default_cpus if num_cpus is None else float(num_cpus)
        if num_tpus:
            # num_tpus is sugar for the logical chip resource; fleets
            # that rename it (cfg.chip_resource, RAY_TPU_CHIP_RESOURCE)
            # need task requests and node capacities to agree
            from ray_tpu.core import runtime as _rt
            from ray_tpu.core.config import GLOBAL_CONFIG

            r = _rt.current_runtime_or_none()
            cfg = r.cfg if r is not None else GLOBAL_CONFIG
            q[cfg.chip_resource] = float(num_tpus)
        if memory:
            q["memory"] = float(memory)
        for k, v in (resources or {}).items():
            q[k] = float(v)
        q = {k: v for k, v in q.items() if v != 0.0}
        return cls(q)


@dataclass
class NodeInfo:
    """Cluster-membership record (ref: GcsNodeInfo proto)."""
    node_id: NodeID
    nodelet_addr: Address
    resources_total: ResourceSet
    # TPU topology labels: e.g. {"slice": "v5e-8/0", "ici_coord": (0,0),
    # "hostname": ...}. Used by slice-aware placement (placement_group.py).
    labels: Dict[str, Any] = field(default_factory=dict)
    alive: bool = True
    store_name: str = ""
    start_time: float = field(default_factory=time.time)


# --- scheduling strategies --------------------------------------------------


@dataclass(frozen=True)
class SchedulingStrategy:
    """DEFAULT hybrid policy (ref: hybrid_scheduling_policy.cc:186)."""
    kind: str = "DEFAULT"


@dataclass(frozen=True)
class SpreadStrategy(SchedulingStrategy):
    kind: str = "SPREAD"


@dataclass(frozen=True)
class NodeAffinityStrategy(SchedulingStrategy):
    """ref: util/scheduling_strategies.py:41 NodeAffinitySchedulingStrategy."""
    kind: str = "NODE_AFFINITY"
    node_id: Optional[NodeID] = None
    soft: bool = False


@dataclass(frozen=True)
class PlacementGroupStrategy(SchedulingStrategy):
    """ref: util/scheduling_strategies.py:15 PlacementGroupSchedulingStrategy."""
    kind: str = "PLACEMENT_GROUP"
    pg_id: Optional[PlacementGroupID] = None
    bundle_index: int = -1


# --- task spec --------------------------------------------------------------


@dataclass
class TaskSpec:
    """Everything needed to run a task anywhere (ref: TaskSpecification).

    `args` is a list of either ("v", pickled_bytes) for inline values or
    ("ref", ObjectRef) for object dependencies; the executing worker resolves
    refs through its own runtime (big objects come from the node store).
    """
    task_id: TaskID
    name: str
    func_id: bytes                      # GCS-KV key of the pickled function
    args: List[Tuple[str, Any]]
    num_returns: int
    resources: ResourceSet
    owner: RuntimeAddress
    job_id: JobID
    max_retries: int = 0
    retry_exceptions: bool = False
    scheduling: SchedulingStrategy = field(default_factory=SchedulingStrategy)
    runtime_env: Optional[dict] = None
    # actor creation
    is_actor_creation: bool = False
    actor_id: Optional[ActorID] = None
    max_restarts: int = 0
    max_concurrency: int = 1
    actor_name: Optional[str] = None
    namespace: str = "default"
    # actor method call
    is_actor_call: bool = False
    method_name: Optional[str] = None
    seq_no: int = -1                    # per-caller ordering (ref: actor submit queue)
    # tracing context {trace_id, span_id} (ref: tracing_helper.py
    # _function_hydrate_span_args — span context rides the task spec)
    trace_ctx: Optional[dict] = None
    # streaming tasks: executor stays at most this many unconsumed items
    # ahead of the consumer (ref: _generator_backpressure_num_objects);
    # None = unbounded
    generator_backpressure: Optional[int] = None
    # byte-budget variant: ack withheld while unconsumed item BYTES exceed
    # this (the data layer sizes it from the object-store budget, ref:
    # streaming_executor_state.py admission by store memory)
    generator_backpressure_bytes: Optional[int] = None

    def return_ids(self) -> List[ObjectID]:
        if self.num_returns == STREAMING:
            return []   # item ids are created incrementally as they stream
        return [ObjectID.for_return(self.task_id, i + 1) for i in range(self.num_returns)]

    @property
    def is_streaming(self) -> bool:
        return self.num_returns == STREAMING

    def scheduling_class(self) -> Tuple:
        """Tasks with equal class can reuse a lease (ref: SchedulingClass).
        Includes the process-env key: leases pin workers whose process env
        was fixed at spawn, so tasks with different process_env_vars must
        never share one."""
        from ray_tpu.runtime_env import process_env

        pe = tuple(sorted(process_env(self.runtime_env).items()))
        # Placement-TARGETED strategies must key the class by their
        # target: lease reuse would otherwise hand a task affined to
        # node B the parked worker leased on node A (observed: every
        # NodeAffinity broadcast task ran on the driver's node), and a
        # PG task the wrong bundle's worker.
        target = ()
        if self.scheduling.kind == "NODE_AFFINITY":
            nid = self.scheduling.node_id
            target = (nid.hex() if nid is not None else None,
                      self.scheduling.soft)
        elif self.scheduling.kind == "PLACEMENT_GROUP":
            target = (self.scheduling.pg_id.hex(),
                      self.scheduling.bundle_index)
        return (self.func_id, tuple(sorted(self.resources.quantities.items())),
                self.scheduling.kind, target, pe)


@dataclass
class TaskResult:
    """Reply of a task push (ref: PushTaskReply proto)."""
    task_id: TaskID
    # per-return: ("inline", pickled) | ("store", {"addr","size"}) |
    #             ("err", SerializedException)
    returns: List[Tuple[str, Any]]
    worker_id: bytes = b""
