"""Asyncio message layer used by all ray_tpu daemons and workers.

Reference: src/ray/rpc/ (GrpcServer / ClientCallManager). The reference wraps
gRPC; here the control plane is a compact asyncio TCP protocol with
length-prefixed pickled frames. The wire layer is isolated behind
`RpcServer`/`RpcClient` so it can be swapped for gRPC (grpcio is available)
without touching callers; for the target deployment shape — one daemon pair
per TPU VM host, tens of hosts — connection counts are small and the pickle
frame path is faster than protobuf ser/des for numpy-bearing payloads.

Frames:  [u32 len][pickle((kind, msg_id, method, payload))]
  kind: 0 = request, 1 = response-ok, 2 = response-error, 3 = one-way
"""

from __future__ import annotations

import asyncio
import concurrent.futures as _futures
import itertools
import pickle
import struct
import threading
import time
import traceback
from typing import Any, Callable, Dict, Optional, Tuple

_LEN = struct.Struct("<I")
REQUEST, RESPONSE_OK, RESPONSE_ERR, ONEWAY = 0, 1, 2, 3
MAX_FRAME = 1 << 31


class RpcError(Exception):
    pass


class RemoteError(RpcError):
    """Handler raised on the other side; message carries remote traceback."""


class ConnectionLost(RpcError):
    pass


async def _read_frame(reader: asyncio.StreamReader):
    hdr = await reader.readexactly(_LEN.size)
    (n,) = _LEN.unpack(hdr)
    data = await reader.readexactly(n)
    return pickle.loads(data)


def _frame(msg) -> bytes:
    data = pickle.dumps(msg, protocol=5)
    return _LEN.pack(len(data)) + data


class RpcServer:
    """Serves methods of a handler object. Any coroutine or plain method named
    ``rpc_<method>`` is callable remotely with a single dict payload."""

    def __init__(self, handler: Any, host: str = "127.0.0.1", port: int = 0):
        self.handler = handler
        self.host = host
        self.port = port
        self._server: Optional[asyncio.AbstractServer] = None
        # Per-method handler stats (ref: src/ray/common/event_stats.h —
        # every asio handler is timed; surfaced via `internal_stats`).
        self._stats: Dict[str, Dict[str, float]] = {}
        self._started_at = time.time()
        self._loop_lag_s = 0.0
        self._loop_lag_max_s = 0.0
        self._lag_task: Optional[asyncio.Task] = None
        self._conns: set = set()          # live connection writers
        self._dispatches: set = set()     # in-flight handler tasks

    async def start(self) -> Tuple[str, int]:
        self._server = await asyncio.start_server(self._on_conn, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        self._lag_task = asyncio.get_running_loop().create_task(
            self._measure_loop_lag())
        return self.host, self.port

    async def _measure_loop_lag(self):
        """Event-loop responsiveness probe: how late a 100ms sleep wakes
        up (ref: event-loop lag surfaced by RAY_CONFIG(event_stats ...)).
        Tracks the max as well — a one-cycle spike would otherwise be
        overwritten before anyone reads it."""
        while True:
            t0 = time.monotonic()
            try:
                await asyncio.sleep(0.1)
            except asyncio.CancelledError:
                return
            lag = max(time.monotonic() - t0 - 0.1, 0.0)
            self._loop_lag_s = lag
            if lag > self._loop_lag_max_s:
                self._loop_lag_max_s = lag

    def _stat(self, method: str) -> Dict[str, float]:
        return self._stats.setdefault(
            method, {"count": 0, "errors": 0, "total_s": 0.0, "max_s": 0.0})

    def internal_stats(self) -> dict:
        """Per-method handler counts/latency + loop lag, for every daemon
        (ref: per-daemon OpenCensus stats, src/ray/stats/metric_defs.h)."""
        return {
            "uptime_s": time.time() - self._started_at,
            "event_loop_lag_s": self._loop_lag_s,
            "event_loop_lag_max_s": self._loop_lag_max_s,
            "handlers": {m: dict(s) for m, s in self._stats.items()},
        }

    @property
    def address(self) -> Tuple[str, int]:
        return (self.host, self.port)

    async def stop(self):
        if self._lag_task is not None:
            self._lag_task.cancel()
            self._lag_task = None
        if self._server:
            self._server.close()
        # Grace first, with writers still open, so in-flight handlers can
        # deliver their responses; then close connections to unblock
        # handlers parked in _read_frame; then cancel stragglers — looping,
        # because buffered frames can spawn new dispatches after any
        # one-shot snapshot. Un-awaited tasks at loop teardown are
        # destroyed pending, which is the noise this exists to prevent.
        loop = asyncio.get_running_loop()
        deadline = loop.time() + 1.0
        while self._dispatches and loop.time() < deadline:
            await asyncio.wait(set(self._dispatches),
                               timeout=deadline - loop.time())
        for w in list(self._conns):
            try:
                w.close()
            except Exception:
                pass
        cancel_deadline = loop.time() + 1.0
        while self._dispatches and loop.time() < cancel_deadline:
            stragglers = set(self._dispatches)
            for t in stragglers:
                t.cancel()
            await asyncio.wait(stragglers,
                               timeout=cancel_deadline - loop.time())
        if self._server:
            try:
                await self._server.wait_closed()
            except Exception:
                pass

    async def _on_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self._conns.add(writer)
        try:
            while True:
                try:
                    kind, msg_id, method, payload = await _read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    return
                if kind == ONEWAY:
                    # inline fast path for handlers that opt in (standing
                    # channel frames): a synchronous, non-blocking handler
                    # runs right here, skipping a dispatch-task round on
                    # the loop — the per-hop hot path of compiled DAGs
                    fn = getattr(self.handler, f"rpc_{method}", None)
                    if fn is not None and getattr(fn, "_rpc_inline", False):
                        try:
                            fn(**payload)
                        except Exception:
                            self._stat(method)["errors"] += 1
                        continue
                t = asyncio.get_running_loop().create_task(
                    self._dispatch(writer, kind, msg_id, method, payload))
                self._dispatches.add(t)
                t.add_done_callback(self._dispatches.discard)
        finally:
            self._conns.discard(writer)
            try:
                writer.close()
            except Exception:
                pass

    async def _dispatch(self, writer, kind, msg_id, method, payload):
        t0 = time.monotonic()
        known = True
        try:
            if method == "internal_stats":
                res = self.internal_stats()
            else:
                fn = getattr(self.handler, f"rpc_{method}", None)
                if fn is None:
                    # don't let client-supplied garbage names grow _stats
                    known = False
                    raise RpcError(f"no such method: {method}")
                res = fn(**payload)
                if asyncio.iscoroutine(res):
                    res = await res
            el = time.monotonic() - t0
            s = self._stat(method)
            s["count"] += 1
            s["total_s"] += el
            if el > s["max_s"]:
                s["max_s"] = el
            if kind == REQUEST:
                writer.write(_frame((RESPONSE_OK, msg_id, method, res)))
                await writer.drain()
        except BaseException:
            # BaseException: a handler awaiting a cancelled executor
            # future raises CancelledError — the caller must still get a
            # RESPONSE_ERR, or its pending future hangs forever. (During
            # server stop the writer is already closed, so the write
            # below fails silently and cancellation proceeds.)
            if known:
                self._stat(method)["errors"] += 1
            if kind == REQUEST:
                try:
                    writer.write(_frame(
                        (RESPONSE_ERR, msg_id, method, traceback.format_exc())))
                    await writer.drain()
                except Exception:
                    pass


class RpcClient:
    """One connection to one server; safe for concurrent calls from one loop."""

    def __init__(self, host: str, port: int):
        self.host, self.port = host, port
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._pending: Dict[int, asyncio.Future] = {}
        self._ids = itertools.count()
        self._conn_lock: Optional[asyncio.Lock] = None
        self._read_task: Optional[asyncio.Task] = None
        # bumps on every (re)connect — lets callers notice a silent
        # server restart (e.g. to re-register pubsub subscriptions)
        self.generation = 0

    async def _ensure(self):
        if self._conn_lock is None:
            self._conn_lock = asyncio.Lock()
        async with self._conn_lock:
            if self._writer is not None and not self._writer.is_closing():
                return
            self._reader, self._writer = await asyncio.open_connection(self.host, self.port)
            self.generation += 1
            self._read_task = asyncio.get_running_loop().create_task(self._read_loop())

    async def _read_loop(self):
        try:
            while True:
                kind, msg_id, method, payload = await _read_frame(self._reader)
                fut = self._pending.pop(msg_id, None)
                if fut is None or fut.done():
                    continue
                if kind == RESPONSE_OK:
                    fut.set_result(payload)
                else:
                    fut.set_exception(RemoteError(f"{method} failed remotely:\n{payload}"))
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError):
            pass
        finally:
            err = ConnectionLost(f"connection to {self.host}:{self.port} lost")
            for fut in self._pending.values():
                try:
                    if not fut.done():
                        fut.set_exception(err)
                except RuntimeError:
                    pass  # loop already closed during shutdown
            self._pending.clear()
            if self._writer is not None:
                try:
                    self._writer.close()
                except Exception:
                    pass
            self._writer = None

    async def connect(self) -> None:
        """Ensure the connection is open without sending anything — lets
        callers that need send-vs-connect failure attribution (actor task
        dispatch) establish the link as a separate, provably-unsent step."""
        await self._ensure()

    async def call(self, method: str, timeout: Optional[float] = None, **payload) -> Any:
        fut = await self.start_call(method, **payload)
        if timeout is None:
            return await fut
        return await asyncio.wait_for(fut, timeout)

    async def start_call(self, method: str, **payload) -> asyncio.Future:
        """Write the request frame now; return the pending future.

        The frame is on the wire (FIFO per connection) when this returns, so
        callers that need ordered delivery (actor submit queues) serialize by
        awaiting start_call before issuing the next one."""
        await self._ensure()
        msg_id = next(self._ids)
        fut = asyncio.get_running_loop().create_future()
        self._pending[msg_id] = fut
        self._writer.write(_frame((REQUEST, msg_id, method, payload)))
        await self._writer.drain()
        return fut

    async def oneway(self, method: str, **payload) -> None:
        await self._ensure()
        self._writer.write(_frame((ONEWAY, next(self._ids), method, payload)))
        await self._writer.drain()

    async def close(self):
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass
            self._writer = None
        if self._read_task:
            self._read_task.cancel()
            await asyncio.wait([self._read_task], timeout=0.5)
            self._read_task = None


class ClientPool:
    """Caches RpcClients by address (ref: rpc::ClientCallManager pooling)."""

    def __init__(self):
        self._clients: Dict[Tuple[str, int], RpcClient] = {}

    def get(self, addr: Tuple[str, int]) -> RpcClient:
        addr = tuple(addr)
        c = self._clients.get(addr)
        if c is None:
            c = self._clients[addr] = RpcClient(*addr)
        return c

    def drop(self, addr: Tuple[str, int]) -> None:
        self._clients.pop(tuple(addr), None)

    async def close_all(self):
        for c in self._clients.values():
            await c.close()
        self._clients.clear()


class EventLoopThread:
    """A dedicated asyncio loop on a daemon thread.

    Drivers and workers embed their networked runtime this way (the reference
    embeds an io_service thread inside CoreWorker). Synchronous public API
    calls bridge in via `run()`.
    """

    def __init__(self, name: str = "ray_tpu-io"):
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._main, name=name, daemon=True)
        self._started = threading.Event()
        self._thread.start()
        self._started.wait()

    def _main(self):
        asyncio.set_event_loop(self.loop)
        self.loop.call_soon(self._started.set)
        self.loop.run_forever()

    def run(self, coro, timeout: Optional[float] = None):
        """Run coroutine on the loop from another thread; blocks for result.

        Never blocks past loop death: if the loop stops (shutdown) while a
        caller waits, raise ConnectionLost instead of hanging — otherwise a
        non-daemon executor thread parked in fut.result(None) deadlocks
        interpreter exit (concurrent.futures joins its threads at exit)."""
        import time as _time

        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        deadline = None if timeout is None else _time.monotonic() + timeout
        while True:
            step = 0.5
            if deadline is not None:
                step = min(step, max(deadline - _time.monotonic(), 0.0))
            try:
                return fut.result(step)
            except asyncio.CancelledError:
                # stop()'s drain cancelled the task under us; keep the
                # documented contract (CancelledError is a BaseException —
                # callers' `except Exception` handlers never see it)
                raise ConnectionLost("runtime event loop stopped") from None
            except (TimeoutError, _futures.TimeoutError):
                # both spellings: before 3.11 concurrent.futures'
                # TimeoutError is NOT the builtin, and fut.result raises
                # the futures one — catching only the builtin turns every
                # >0.5s coroutine into a spurious timeout
                if fut.done():
                    # Completed during the poll window: surface the real
                    # outcome (result, or the coroutine's own exception).
                    return fut.result()
                if not self.loop.is_running() or not self._thread.is_alive():
                    fut.cancel()
                    raise ConnectionLost("runtime event loop stopped") from None
                if deadline is not None and _time.monotonic() >= deadline:
                    fut.cancel()
                    # normalize to the builtin so callers need one spelling
                    raise TimeoutError(
                        f"coroutine did not finish within {timeout}s"
                    ) from None

    def spawn(self, coro):
        """Fire-and-forget from any thread."""
        def _create():
            self.loop.create_task(coro)
        self.loop.call_soon_threadsafe(_create)

    def stop(self):
        # Drain before stopping: a task still pending when the loop dies is
        # destroyed un-awaited and asyncio logs "Task was destroyed but it
        # is pending!" — in a long-lived daemon that noise is where real
        # leaks hide, so cancel and await everything first.
        async def _drain():
            # Iterate: cancelling one task can spawn another (a cancelled
            # caller's teardown may reconnect, creating a fresh _read_loop),
            # so a one-shot snapshot can leave brand-new tasks pending.
            cur = asyncio.current_task()
            deadline = asyncio.get_running_loop().time() + 2.0
            while True:
                tasks = [t for t in asyncio.all_tasks() if t is not cur]
                if not tasks:
                    break
                for t in tasks:
                    t.cancel()
                left = deadline - asyncio.get_running_loop().time()
                if left <= 0:
                    break
                await asyncio.wait(tasks, timeout=min(left, 1.0))

        if self._thread.is_alive() and self.loop.is_running():
            try:
                asyncio.run_coroutine_threadsafe(
                    _drain(), self.loop).result(3.0)
            except Exception:
                pass
        try:
            self.loop.call_soon_threadsafe(self.loop.stop)
        except RuntimeError:
            pass  # loop already closed
        self._thread.join(timeout=2)
