"""Asyncio message layer used by all ray_tpu daemons and workers.

Reference: src/ray/rpc/ (GrpcServer / ClientCallManager). The reference wraps
gRPC; here the control plane is a compact asyncio TCP protocol with
length-prefixed pickled frames. The wire layer is isolated behind
`RpcServer`/`RpcClient` so it can be swapped for gRPC (grpcio is available)
without touching callers; for the target deployment shape — one daemon pair
per TPU VM host, tens of hosts — connection counts are small and the pickle
frame path is faster than protobuf ser/des for numpy-bearing payloads.

Frames:  [u32 len][pickle((kind, msg_id, method, payload))]
  kind: 0 = request, 1 = response-ok, 2 = response-error, 3 = one-way,
        4 = keepalive ping, 5 = keepalive pong

Partition tolerance: TCP alone cannot distinguish a black-holed link from
a slow peer — writes buffer locally for minutes before erroring (the gray
failure mode of Huang et al., HotOS'17). Two defenses live here:

- every ``RpcClient.call`` carries a transport deadline by default
  (``configure()`` binds it to Config.rpc_call_timeout_s); expiry raises
  the typed ``RpcTimeout`` and feeds a per-peer suspicion counter the
  telemetry agent drains into the health plane.
- each client connection runs an application-level keepalive: PING every
  ``rpc_keepalive_interval_s``; a connection that stays rx-silent past
  ``rpc_keepalive_timeout_s`` is aborted, converting the black hole into
  ``ConnectionLost`` for every pending caller.

The devtools.chaos interposer (``set_chaos``) sits on the four frame
edges — client egress/ingress, server ingress/egress — so a seeded
FaultPlan can drop/delay/duplicate/reorder/black-hole/reset any link.
"""

from __future__ import annotations

import asyncio
import concurrent.futures as _futures
import itertools
import pickle
import struct
import threading
import time
import traceback
from typing import Any, Callable, Dict, List, Optional, Tuple

_LEN = struct.Struct("<I")
REQUEST, RESPONSE_OK, RESPONSE_ERR, ONEWAY, PING, PONG = 0, 1, 2, 3, 4, 5
MAX_FRAME = 1 << 31

# Module defaults; configure(cfg) rebinds them from Config in every
# process entrypoint (runtime/gcs/nodelet/worker). A sentinel — not None —
# marks "caller passed nothing", because explicit timeout=None must keep
# meaning "unbounded" for the reviewed allowlist (push_task).
_UNSET_TIMEOUT: Any = object()
_call_timeout_s: float = 60.0
_keepalive_interval_s: float = 5.0
_keepalive_timeout_s: float = 20.0

# devtools.chaos.Interposer | None — consulted (never imported) here, so
# core stays import-free of devtools.
_chaos: Optional[Any] = None


def configure(cfg) -> None:
    """Bind module-level transport defaults from a core.config.Config."""
    global _call_timeout_s, _keepalive_interval_s, _keepalive_timeout_s
    _call_timeout_s = cfg.rpc_call_timeout_s
    _keepalive_interval_s = cfg.rpc_keepalive_interval_s
    _keepalive_timeout_s = cfg.rpc_keepalive_timeout_s


def set_chaos(interposer: Optional[Any]) -> None:
    global _chaos
    _chaos = interposer


def get_chaos() -> Optional[Any]:
    return _chaos


class RpcError(Exception):
    pass


class RemoteError(RpcError):
    """Handler raised on the other side; message carries remote traceback."""


class ConnectionLost(RpcError):
    pass


class RpcTimeout(RpcError, asyncio.TimeoutError, TimeoutError):
    """Transport deadline expired with no response.

    Subclasses BOTH timeout spellings (pre-3.11 asyncio.TimeoutError is
    not the builtin) so every existing wait_for/OSError-family handler
    keeps working — retry loops that treat OSError as "peer unreachable,
    retry" absorb timeouts the same way. Distinct from ConnectionLost
    because the link may be fine and the *peer* gray-failed — the health
    plane treats repeated RpcTimeouts as a peer-suspicion signal."""


# Per-peer timeout suspicions: {(host, port, method): count}, drained by
# the telemetry agent into the GCS health aggregator (a black-holed or
# wedged peer shows up here long before any crash-stop signal).
_suspicion_lock = threading.Lock()
_suspicions: Dict[Tuple[str, int, str], int] = {}


def _note_timeout(host: str, port: int, method: str) -> None:
    with _suspicion_lock:
        key = (host, port, method)
        _suspicions[key] = _suspicions.get(key, 0) + 1
        while len(_suspicions) > 256:
            _suspicions.pop(next(iter(_suspicions)))


def drain_timeout_suspicions() -> List[dict]:
    """Pop-and-return accumulated RpcTimeout counts (telemetry agent)."""
    with _suspicion_lock:
        if not _suspicions:
            return []
        out = [{"peer": f"{h}:{p}", "method": m, "count": c}
               for (h, p, m), c in _suspicions.items()]
        _suspicions.clear()
        return out


async def _read_frame(reader: asyncio.StreamReader):
    hdr = await reader.readexactly(_LEN.size)
    (n,) = _LEN.unpack(hdr)
    data = await reader.readexactly(n)
    return pickle.loads(data)


def _frame(msg) -> bytes:
    data = pickle.dumps(msg, protocol=5)
    return _LEN.pack(len(data)) + data


class RpcServer:
    """Serves methods of a handler object. Any coroutine or plain method named
    ``rpc_<method>`` is callable remotely with a single dict payload."""

    def __init__(self, handler: Any, host: str = "127.0.0.1", port: int = 0):
        self.handler = handler
        self.host = host
        self.port = port
        self._server: Optional[asyncio.AbstractServer] = None
        # Per-method handler stats (ref: src/ray/common/event_stats.h —
        # every asio handler is timed; surfaced via `internal_stats`).
        self._stats: Dict[str, Dict[str, float]] = {}
        self._started_at = time.time()
        self._loop_lag_s = 0.0
        self._loop_lag_max_s = 0.0
        self._lag_task: Optional[asyncio.Task] = None
        self._conns: set = set()          # live connection writers
        self._dispatches: set = set()     # in-flight handler tasks

    async def start(self) -> Tuple[str, int]:
        self._server = await asyncio.start_server(self._on_conn, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        self._lag_task = asyncio.get_running_loop().create_task(
            self._measure_loop_lag())
        return self.host, self.port

    async def _measure_loop_lag(self):
        """Event-loop responsiveness probe: how late a 100ms sleep wakes
        up (ref: event-loop lag surfaced by RAY_CONFIG(event_stats ...)).
        Tracks the max as well — a one-cycle spike would otherwise be
        overwritten before anyone reads it."""
        while True:
            t0 = time.monotonic()
            try:
                await asyncio.sleep(0.1)
            except asyncio.CancelledError:
                return
            lag = max(time.monotonic() - t0 - 0.1, 0.0)
            self._loop_lag_s = lag
            if lag > self._loop_lag_max_s:
                self._loop_lag_max_s = lag

    def _stat(self, method: str) -> Dict[str, float]:
        return self._stats.setdefault(
            method, {"count": 0, "errors": 0, "total_s": 0.0, "max_s": 0.0})

    def internal_stats(self) -> dict:
        """Per-method handler counts/latency + loop lag, for every daemon
        (ref: per-daemon OpenCensus stats, src/ray/stats/metric_defs.h)."""
        return {
            "uptime_s": time.time() - self._started_at,
            "event_loop_lag_s": self._loop_lag_s,
            "event_loop_lag_max_s": self._loop_lag_max_s,
            "handlers": {m: dict(s) for m, s in self._stats.items()},
        }

    @property
    def address(self) -> Tuple[str, int]:
        return (self.host, self.port)

    async def stop(self):
        if self._lag_task is not None:
            self._lag_task.cancel()
            self._lag_task = None
        if self._server:
            self._server.close()
        # Grace first, with writers still open, so in-flight handlers can
        # deliver their responses; then close connections to unblock
        # handlers parked in _read_frame; then cancel stragglers — looping,
        # because buffered frames can spawn new dispatches after any
        # one-shot snapshot. Un-awaited tasks at loop teardown are
        # destroyed pending, which is the noise this exists to prevent.
        loop = asyncio.get_running_loop()
        deadline = loop.time() + 1.0
        while self._dispatches and loop.time() < deadline:
            await asyncio.wait(set(self._dispatches),
                               timeout=deadline - loop.time())
        for w in list(self._conns):
            try:
                w.close()
            except Exception:
                pass
        cancel_deadline = loop.time() + 1.0
        while self._dispatches and loop.time() < cancel_deadline:
            stragglers = set(self._dispatches)
            for t in stragglers:
                t.cancel()
            await asyncio.wait(stragglers,
                               timeout=cancel_deadline - loop.time())
        if self._server:
            try:
                await self._server.wait_closed()
            except Exception:
                pass

    async def _on_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self._conns.add(writer)
        # Sender role for chaos rule matching: the client announces it in
        # a __hello__ oneway right after connect (only when a plan is
        # installed); "*" until/unless one arrives.
        conn_role = "*"
        try:
            while True:
                try:
                    kind, msg_id, method, payload = await _read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    return
                if kind == ONEWAY and method == "__hello__":
                    conn_role = payload.get("role", "*")
                    continue
                if kind == PING:
                    # keepalive probe: answer inline unless an installed
                    # fault plan black-holes this link (a dropped PONG is
                    # exactly how a black hole converts to ConnectionLost
                    # on the other side)
                    if _chaos is None or _chaos.on_frame(
                            "recv", "__ping__", PING,
                            peer_role=conn_role).action == "pass":
                        writer.write(_frame((PONG, msg_id, "", None)))
                        await writer.drain()
                    continue
                delay_s = 0.0
                copies = 1
                if _chaos is not None:
                    v = _chaos.on_frame("recv", method, kind,
                                        peer_role=conn_role)
                    if v.action == "drop":
                        continue
                    if v.action == "reset":
                        try:
                            writer.transport.abort()
                        except Exception:
                            pass
                        return
                    if v.action == "delay":
                        delay_s = v.delay_s
                    elif v.action == "duplicate":
                        copies = 2
                if kind == ONEWAY and not delay_s and copies == 1:
                    # inline fast path for handlers that opt in (standing
                    # channel frames): a synchronous, non-blocking handler
                    # runs right here, skipping a dispatch-task round on
                    # the loop — the per-hop hot path of compiled DAGs
                    fn = getattr(self.handler, f"rpc_{method}", None)
                    if fn is not None and getattr(fn, "_rpc_inline", False):
                        try:
                            fn(**payload)
                        except Exception:
                            self._stat(method)["errors"] += 1
                        continue
                for _ in range(copies):
                    t = asyncio.get_running_loop().create_task(
                        self._dispatch(writer, kind, msg_id, method, payload,
                                       conn_role=conn_role, delay_s=delay_s))
                    self._dispatches.add(t)
                    t.add_done_callback(self._dispatches.discard)
        finally:
            self._conns.discard(writer)
            try:
                writer.close()
            except Exception:
                pass

    async def _dispatch(self, writer, kind, msg_id, method, payload,
                        conn_role: str = "*", delay_s: float = 0.0):
        if delay_s:
            # injected ingress delay: later frames overtake this dispatch
            # (reordering), which is the point
            await asyncio.sleep(delay_s)
        t0 = time.monotonic()
        known = True
        try:
            if method == "internal_stats":
                res = self.internal_stats()
            else:
                fn = getattr(self.handler, f"rpc_{method}", None)
                if fn is None:
                    # don't let client-supplied garbage names grow _stats
                    known = False
                    raise RpcError(f"no such method: {method}")
                res = fn(**payload)
                if asyncio.iscoroutine(res):
                    res = await res
            el = time.monotonic() - t0
            s = self._stat(method)
            s["count"] += 1
            s["total_s"] += el
            if el > s["max_s"]:
                s["max_s"] = el
            if kind == REQUEST:
                await self._send_response(
                    writer, (RESPONSE_OK, msg_id, method, res), conn_role)
        except BaseException:
            # BaseException: a handler awaiting a cancelled executor
            # future raises CancelledError — the caller must still get a
            # RESPONSE_ERR, or its pending future hangs forever. (During
            # server stop the writer is already closed, so the write
            # below fails silently and cancellation proceeds.)
            if known:
                self._stat(method)["errors"] += 1
            if kind == REQUEST:
                try:
                    await self._send_response(
                        writer,
                        (RESPONSE_ERR, msg_id, method, traceback.format_exc()),
                        conn_role)
                except Exception:
                    pass

    async def _send_response(self, writer, msg, conn_role: str):
        """Response egress — the server-side chaos edge for reply frames."""
        if _chaos is not None:
            v = _chaos.on_frame("send", msg[2], msg[0], peer_role=conn_role)
            if v.action == "drop":
                return
            if v.action == "reset":
                try:
                    writer.transport.abort()
                except Exception:
                    pass
                return
            if v.action == "delay":
                await asyncio.sleep(v.delay_s)
            elif v.action == "duplicate":
                writer.write(_frame(msg))
        writer.write(_frame(msg))
        await writer.drain()


class RpcClient:
    """One connection to one server; safe for concurrent calls from one loop."""

    def __init__(self, host: str, port: int):
        self.host, self.port = host, port
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._pending: Dict[int, asyncio.Future] = {}
        self._ids = itertools.count()
        self._conn_lock: Optional[asyncio.Lock] = None
        self._read_task: Optional[asyncio.Task] = None
        self._keepalive_task: Optional[asyncio.Task] = None
        self._last_rx = 0.0
        self._chaos_tasks: set = set()   # injected delayed-send tasks
        # bumps on every (re)connect — lets callers notice a silent
        # server restart (e.g. to re-register pubsub subscriptions)
        self.generation = 0

    async def _ensure(self):
        if self._conn_lock is None:
            self._conn_lock = asyncio.Lock()
        async with self._conn_lock:
            if self._writer is not None and not self._writer.is_closing():
                return
            self._reader, self._writer = await asyncio.open_connection(self.host, self.port)
            self.generation += 1
            self._last_rx = time.monotonic()
            loop = asyncio.get_running_loop()
            self._read_task = loop.create_task(self._read_loop())
            if _chaos is not None:
                # announce our role so the server side can match
                # src-role rules on this connection
                self._writer.write(_frame(
                    (ONEWAY, 0, "__hello__", {"role": _chaos.role})))
            if _keepalive_interval_s > 0:
                if self._keepalive_task is not None:
                    self._keepalive_task.cancel()
                self._keepalive_task = loop.create_task(
                    self._keepalive(self._writer))

    async def _keepalive(self, writer):
        """PING the server every interval; abort the connection when no
        frame (response OR pong) has arrived within the keepalive
        timeout — a black-holed link becomes ConnectionLost for every
        pending caller instead of an indefinite hang."""
        interval = _keepalive_interval_s
        try:
            while True:
                await asyncio.sleep(interval)
                if self._writer is not writer or writer.is_closing():
                    return
                if time.monotonic() - self._last_rx > _keepalive_timeout_s:
                    try:
                        writer.transport.abort()
                    except Exception:
                        pass
                    return
                try:
                    if _chaos is None or _chaos.on_frame(
                            "send", "__ping__", PING,
                            peer=(self.host, self.port)).action == "pass":
                        writer.write(_frame((PING, 0, "", None)))
                        await writer.drain()
                except Exception:
                    return
        except asyncio.CancelledError:
            return

    async def _read_loop(self):
        try:
            while True:
                kind, msg_id, method, payload = await _read_frame(self._reader)
                self._last_rx = time.monotonic()
                if kind == PONG:
                    continue
                if _chaos is not None:
                    v = _chaos.on_frame("recv", method, kind,
                                        peer=(self.host, self.port))
                    if v.action == "drop":
                        continue
                    if v.action == "reset":
                        try:
                            self._writer.transport.abort()
                        except Exception:
                            pass
                        break
                    if v.action == "delay":
                        fut = self._pending.pop(msg_id, None)
                        if fut is not None:
                            self._spawn_chaos(self._deliver_late(
                                fut, kind, method, payload, v.delay_s))
                        continue
                fut = self._pending.pop(msg_id, None)
                if fut is None or fut.done():
                    continue
                if kind == RESPONSE_OK:
                    fut.set_result(payload)
                else:
                    fut.set_exception(RemoteError(f"{method} failed remotely:\n{payload}"))
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError):
            pass
        finally:
            err = ConnectionLost(f"connection to {self.host}:{self.port} lost")
            for fut in self._pending.values():
                try:
                    if not fut.done():
                        fut.set_exception(err)
                except RuntimeError:
                    pass  # loop already closed during shutdown
            self._pending.clear()
            if self._keepalive_task is not None:
                self._keepalive_task.cancel()
                self._keepalive_task = None
            if self._writer is not None:
                try:
                    self._writer.close()
                except Exception:
                    pass
            self._writer = None

    def _spawn_chaos(self, coro):
        t = asyncio.get_running_loop().create_task(coro)
        self._chaos_tasks.add(t)
        t.add_done_callback(self._chaos_tasks.discard)

    @staticmethod
    async def _deliver_late(fut, kind, method, payload, delay_s: float):
        await asyncio.sleep(delay_s)
        if fut.done():
            return
        if kind == RESPONSE_OK:
            fut.set_result(payload)
        else:
            fut.set_exception(RemoteError(f"{method} failed remotely:\n{payload}"))

    async def connect(self) -> None:
        """Ensure the connection is open without sending anything — lets
        callers that need send-vs-connect failure attribution (actor task
        dispatch) establish the link as a separate, provably-unsent step."""
        await self._ensure()

    async def call(self, method: str, timeout: Optional[float] = _UNSET_TIMEOUT,
                   **payload) -> Any:
        """One request/response round-trip.

        ``timeout`` omitted ⇒ the module default deadline
        (Config.rpc_call_timeout_s) applies and expiry raises RpcTimeout.
        An *explicit* ``timeout=None`` means unbounded — reserved for the
        reviewed allowlist (raylint: unbounded-rpc-call)."""
        if timeout is _UNSET_TIMEOUT:
            timeout = _call_timeout_s
        fut = await self.start_call(method, **payload)
        if timeout is None:
            return await fut
        try:
            return await asyncio.wait_for(fut, timeout)
        except (asyncio.TimeoutError, TimeoutError):
            if fut.done() and not fut.cancelled():
                # completed inside wait_for's cancellation window
                return fut.result()
            for mid, f in list(self._pending.items()):
                if f is fut:
                    self._pending.pop(mid, None)
                    break
            _note_timeout(self.host, self.port, method)
            raise RpcTimeout(
                f"rpc {method} to {self.host}:{self.port} exceeded its "
                f"{timeout}s deadline") from None

    async def _send(self, msg, method: str, kind: int) -> None:
        """Request/oneway egress — the client-side chaos edge."""
        if _chaos is not None:
            v = _chaos.on_frame("send", method, kind,
                                peer=(self.host, self.port))
            if v.action == "drop":
                # pretend written: the caller's deadline (or keepalive)
                # surfaces the loss as RpcTimeout/ConnectionLost
                return
            if v.action == "reset":
                try:
                    self._writer.transport.abort()
                except Exception:
                    pass
                raise ConnectionLost(
                    f"connection to {self.host}:{self.port} reset (injected)")
            if v.action == "delay":
                writer, frame = self._writer, _frame(msg)

                async def _later():
                    await asyncio.sleep(v.delay_s)
                    if self._writer is writer and not writer.is_closing():
                        writer.write(frame)

                self._spawn_chaos(_later())
                return
            if v.action == "duplicate":
                self._writer.write(_frame(msg))
        self._writer.write(_frame(msg))
        await self._writer.drain()

    async def start_call(self, method: str, **payload) -> asyncio.Future:
        """Write the request frame now; return the pending future.

        The frame is on the wire (FIFO per connection) when this returns, so
        callers that need ordered delivery (actor submit queues) serialize by
        awaiting start_call before issuing the next one."""
        await self._ensure()
        msg_id = next(self._ids)
        fut = asyncio.get_running_loop().create_future()
        self._pending[msg_id] = fut
        await self._send((REQUEST, msg_id, method, payload), method, REQUEST)
        return fut

    async def oneway(self, method: str, **payload) -> None:
        await self._ensure()
        await self._send((ONEWAY, next(self._ids), method, payload),
                         method, ONEWAY)

    async def close(self):
        if self._keepalive_task is not None:
            self._keepalive_task.cancel()
            self._keepalive_task = None
        for t in list(self._chaos_tasks):
            t.cancel()
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass
            self._writer = None
        if self._read_task:
            self._read_task.cancel()
            await asyncio.wait([self._read_task], timeout=0.5)
            self._read_task = None


class ClientPool:
    """Caches RpcClients by address (ref: rpc::ClientCallManager pooling)."""

    def __init__(self):
        self._clients: Dict[Tuple[str, int], RpcClient] = {}

    def get(self, addr: Tuple[str, int]) -> RpcClient:
        addr = tuple(addr)
        c = self._clients.get(addr)
        if c is None:
            c = self._clients[addr] = RpcClient(*addr)
        return c

    def drop(self, addr: Tuple[str, int]) -> None:
        self._clients.pop(tuple(addr), None)

    async def close_all(self):
        for c in self._clients.values():
            await c.close()
        self._clients.clear()


class EventLoopThread:
    """A dedicated asyncio loop on a daemon thread.

    Drivers and workers embed their networked runtime this way (the reference
    embeds an io_service thread inside CoreWorker). Synchronous public API
    calls bridge in via `run()`.
    """

    def __init__(self, name: str = "ray_tpu-io"):
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._main, name=name, daemon=True)
        self._started = threading.Event()
        self._thread.start()
        self._started.wait()

    def _main(self):
        asyncio.set_event_loop(self.loop)
        self.loop.call_soon(self._started.set)
        self.loop.run_forever()

    def run(self, coro, timeout: Optional[float] = None):
        """Run coroutine on the loop from another thread; blocks for result.

        Never blocks past loop death: if the loop stops (shutdown) while a
        caller waits, raise ConnectionLost instead of hanging — otherwise a
        non-daemon executor thread parked in fut.result(None) deadlocks
        interpreter exit (concurrent.futures joins its threads at exit)."""
        import time as _time

        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        deadline = None if timeout is None else _time.monotonic() + timeout
        while True:
            step = 0.5
            if deadline is not None:
                step = min(step, max(deadline - _time.monotonic(), 0.0))
            try:
                return fut.result(step)
            except asyncio.CancelledError:
                # stop()'s drain cancelled the task under us; keep the
                # documented contract (CancelledError is a BaseException —
                # callers' `except Exception` handlers never see it)
                raise ConnectionLost("runtime event loop stopped") from None
            except (TimeoutError, _futures.TimeoutError):
                # both spellings: before 3.11 concurrent.futures'
                # TimeoutError is NOT the builtin, and fut.result raises
                # the futures one — catching only the builtin turns every
                # >0.5s coroutine into a spurious timeout
                if fut.done():
                    # Completed during the poll window: surface the real
                    # outcome (result, or the coroutine's own exception).
                    return fut.result()
                if not self.loop.is_running() or not self._thread.is_alive():
                    fut.cancel()
                    raise ConnectionLost("runtime event loop stopped") from None
                if deadline is not None and _time.monotonic() >= deadline:
                    fut.cancel()
                    # normalize to the builtin so callers need one spelling
                    raise TimeoutError(
                        f"coroutine did not finish within {timeout}s"
                    ) from None

    def spawn(self, coro):
        """Fire-and-forget from any thread."""
        def _create():
            self.loop.create_task(coro)
        self.loop.call_soon_threadsafe(_create)

    def stop(self):
        # Drain before stopping: a task still pending when the loop dies is
        # destroyed un-awaited and asyncio logs "Task was destroyed but it
        # is pending!" — in a long-lived daemon that noise is where real
        # leaks hide, so cancel and await everything first.
        async def _drain():
            # Iterate: cancelling one task can spawn another (a cancelled
            # caller's teardown may reconnect, creating a fresh _read_loop),
            # so a one-shot snapshot can leave brand-new tasks pending.
            cur = asyncio.current_task()
            deadline = asyncio.get_running_loop().time() + 2.0
            while True:
                tasks = [t for t in asyncio.all_tasks() if t is not cur]
                if not tasks:
                    break
                for t in tasks:
                    t.cancel()
                left = deadline - asyncio.get_running_loop().time()
                if left <= 0:
                    break
                await asyncio.wait(tasks, timeout=min(left, 1.0))

        if self._thread.is_alive() and self.loop.is_running():
            try:
                asyncio.run_coroutine_threadsafe(
                    _drain(), self.loop).result(3.0)
            except Exception:
                pass
        try:
            self.loop.call_soon_threadsafe(self.loop.stop)
        except RuntimeError:
            pass  # loop already closed
        self._thread.join(timeout=2)
