"""External storage for spilled objects (host-tier → disk).

Reference: python/ray/_private/external_storage.py:72 (FileSystemStorage —
spill serialized objects to files under a spill dir, return restore URLs) and
src/ray/raylet/local_object_manager.h:41 (spill under memory pressure,
restore on demand, delete on ref release).

TPU-first redesign notes: the shm segment is the host staging tier for both
control-plane objects and HBM-offloaded arrays, so spilling backs *both*
tiers; files carry the already-serialized wire bytes (zero re-serialization
on either side of the spill boundary).
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Tuple

from ray_tpu.core.ids import ObjectID


class FilesystemStorage:
    """Spill store writing one file per object under `root`.

    URLs are `file://<path>`; paths embed the object id so restore needs no
    extra index (the nodelet keeps one anyway for fast `contains`).
    """

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._lock = threading.Lock()
        self._spilled: Dict[ObjectID, str] = {}
        self._sizes: Dict[ObjectID, int] = {}
        self._bytes = 0

    # -- spill ----------------------------------------------------------------

    def spill(self, oid: ObjectID, data: memoryview | bytes) -> str:
        nbytes = data.nbytes if isinstance(data, memoryview) else len(data)
        path = os.path.join(self.root, oid.hex())
        # unique tmp per attempt: concurrent spills of the SAME object
        # (periodic spill loop vs put-pressure free_space) must not share
        # a tmp path, or one racer renames it away under the other
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)  # atomic: readers never see partial files
        url = f"file://{path}"
        with self._lock:
            prev = self._sizes.get(oid)
            if prev is not None:
                self._bytes -= prev
            self._bytes += nbytes
            self._sizes[oid] = nbytes
            self._spilled[oid] = url
        return url

    def contains(self, oid: ObjectID) -> bool:
        with self._lock:
            return oid in self._spilled

    def url_of(self, oid: ObjectID) -> Optional[str]:
        with self._lock:
            return self._spilled.get(oid)

    # -- restore --------------------------------------------------------------

    def restore(self, oid: ObjectID) -> Optional[bytes]:
        url = self.url_of(oid)
        if url is None:
            return None
        path = url[len("file://"):]
        try:
            with open(path, "rb") as f:
                return f.read()
        except FileNotFoundError:
            self._forget(oid)
            return None

    def read_range(self, oid: ObjectID, offset: int,
                   size: int) -> Optional[Tuple[int, bytes]]:
        """(total_size, chunk) for chunked remote pulls straight off disk."""
        url = self.url_of(oid)
        if url is None:
            return None
        path = url[len("file://"):]
        try:
            total = os.path.getsize(path)
            with open(path, "rb") as f:
                f.seek(offset)
                return total, f.read(size)
        except FileNotFoundError:
            return None

    # -- delete ---------------------------------------------------------------

    def _forget(self, oid: ObjectID) -> None:
        with self._lock:
            self._spilled.pop(oid, None)
            sz = self._sizes.pop(oid, None)
            if sz is not None:
                self._bytes -= sz

    def delete(self, oid: ObjectID) -> None:
        url = self.url_of(oid)
        self._forget(oid)
        if url is None:
            return
        try:
            os.remove(url[len("file://"):])
        except FileNotFoundError:
            pass

    def delete_all(self) -> None:
        with self._lock:
            oids = list(self._spilled)
        for oid in oids:
            self.delete(oid)

    # -- stats ----------------------------------------------------------------

    def num_spilled(self) -> int:
        with self._lock:
            return len(self._spilled)

    def bytes_spilled(self) -> int:
        with self._lock:
            return self._bytes

    def spilled_ids(self) -> List[ObjectID]:
        with self._lock:
            return list(self._spilled)
