"""Pluggable GCS persistence: snapshot + append-WAL.

Reference: src/ray/gcs/gcs_server/gcs_table_storage.h:252 (pluggable table
storage) over store_client/{in_memory,redis}_store_client.h. The round-1
design persisted debounced whole-state snapshots only, which loses writes
acknowledged between snapshot points; this adds a write-ahead log so every
acked mutation survives a GCS crash:

- `append(record)` durably logs one mutation (buffered write + flush per
  record; fsync at most once a second — the same window as Redis
  appendfsync-everysec, documented rather than pretended away).
- `rotate()` starts a new WAL segment and returns the old segment's seq;
  called atomically with the state pickle on the GCS loop, so a snapshot
  plus all segments newer than its watermark is always a complete state.
- `commit_snapshot(data, watermark)` persists the snapshot, then deletes
  segments <= watermark. If the commit crashes mid-way, restore still
  works from the previous snapshot + the surviving segments.
- `restore()` -> (snapshot_bytes | None, [records...]) replaying every
  surviving segment in order; a torn tail record (crash mid-append) ends
  replay for that segment.

Record framing: [u32 len][u32 crc32][payload].
"""

from __future__ import annotations

import os
import re
import struct
import time
import zlib
from typing import List, Optional, Tuple

_SEG_RE = re.compile(r"^wal\.(\d{8})$")


class GcsStorage:
    """Interface (ref: GcsTableStorage). Implementations must make
    append() durable enough that restore() returns it after a crash."""

    def append(self, record: bytes) -> None:
        raise NotImplementedError

    def rotate(self) -> int:
        raise NotImplementedError

    def commit_snapshot(self, data: bytes, watermark: int) -> None:
        raise NotImplementedError

    def restore(self) -> Tuple[Optional[bytes], List[bytes]]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class MemoryGcsStorage(GcsStorage):
    """No durability (ref: in_memory_store_client.h) — default for tests
    and throwaway clusters."""

    def append(self, record: bytes) -> None:
        pass

    def rotate(self) -> int:
        return 0

    def commit_snapshot(self, data: bytes, watermark: int) -> None:
        pass

    def restore(self) -> Tuple[Optional[bytes], List[bytes]]:
        return None, []


class FileGcsStorage(GcsStorage):
    def __init__(self, dirpath: str, fsync_interval_s: float = 1.0):
        self.dir = dirpath
        os.makedirs(dirpath, exist_ok=True)
        self._fsync_interval = fsync_interval_s
        self._last_fsync = 0.0
        seqs = self._segments()
        self._seq = (seqs[-1] + 1) if seqs else 1
        self._f = None
        self._open_segment()

    # -- internals -----------------------------------------------------------

    def _segments(self) -> List[int]:
        out = []
        try:
            for name in os.listdir(self.dir):
                m = _SEG_RE.match(name)
                if m:
                    out.append(int(m.group(1)))
        except OSError:
            pass
        return sorted(out)

    def _seg_path(self, seq: int) -> str:
        return os.path.join(self.dir, f"wal.{seq:08d}")

    def _open_segment(self):
        if self._f is not None:
            self._f.close()
        self._f = open(self._seg_path(self._seq), "ab")

    # -- GcsStorage ----------------------------------------------------------

    def append(self, record: bytes) -> None:
        self._f.write(struct.pack("<II", len(record),
                                  zlib.crc32(record) & 0xFFFFFFFF))
        self._f.write(record)
        self._f.flush()
        now = time.monotonic()
        if now - self._last_fsync >= self._fsync_interval:
            self._last_fsync = now
            os.fsync(self._f.fileno())

    def rotate(self) -> int:
        # no fsync here: rotate runs on the GCS event loop and must stay
        # cheap (segment swap only). The everysec append fsync already
        # bounds machine-crash loss; process crashes lose nothing that
        # was flushed to the page cache.
        old = self._seq
        self._seq += 1
        self._open_segment()
        return old

    def commit_snapshot(self, data: bytes, watermark: int) -> None:
        path = os.path.join(self.dir, "gcs_snapshot.pkl")
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        for seq in self._segments():
            if seq <= watermark:
                try:
                    os.unlink(self._seg_path(seq))
                except OSError:
                    pass

    def restore(self) -> Tuple[Optional[bytes], List[bytes]]:
        snap = None
        path = os.path.join(self.dir, "gcs_snapshot.pkl")
        try:
            with open(path, "rb") as f:
                snap = f.read()
        except OSError:
            pass
        records: List[bytes] = []
        for seq in self._segments():
            if seq == self._seq:
                continue   # our own (empty) live segment
            try:
                with open(self._seg_path(seq), "rb") as f:
                    while True:
                        hdr = f.read(8)
                        if len(hdr) < 8:
                            break
                        n, crc = struct.unpack("<II", hdr)
                        payload = f.read(n)
                        if len(payload) < n or \
                                (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
                            break   # torn tail: crash mid-append
                        records.append(payload)
            except OSError:
                continue
        return snap, records

    def close(self) -> None:
        if self._f is not None:
            try:
                os.fsync(self._f.fileno())
            except OSError:
                pass
            self._f.close()
            self._f = None
