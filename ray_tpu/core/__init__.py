"""ray_tpu.core: the distributed runtime (tasks, actors, objects, scheduling).

Layering (bottom-up), mirroring the reference's architecture
(SURVEY.md section 1) but re-designed for TPU hosts:

- ids/config/status/serialization  — common substrate (ref: src/ray/common/)
- object_store                     — node object plane: native shm store +
                                     in-process memory store (ref: plasma +
                                     core_worker/store_provider/)
- rpc                              — asyncio message layer (ref: src/ray/rpc/)
- gcs                              — cluster control plane (ref: src/ray/gcs/)
- nodelet                          — per-node daemon: worker pool, leases,
                                     object manager (ref: src/ray/raylet/)
- worker                           — worker process runtime (ref: core_worker
                                     execution side)
- runtime                          — in-process driver/worker runtime:
                                     ownership, task manager, submission
                                     (ref: core_worker submission side)
"""
