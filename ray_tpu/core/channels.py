"""Standing channels: the compiled-DAG data plane.

Reference: the compiled-graph (aDAG) execution layer the reference ships
under python/ray/dag — once a static DAG is compiled, per-call dispatch
(task-spec build, submit queue, scheduler round) is replaced by raw
enqueues onto channels negotiated once at compile time. Our transport is
the existing worker RPC plane rather than shared-memory mutable objects,
but the shape is the same: one standing channel per compiled node, opened
on the worker hosting that node's actor, with pre-resolved routes to its
consumers.

Protocol (all frames carry the driver-assigned execution sequence number):

  channel_open(spec)                    negotiate: bind the channel to its
                                        actor lane, unpack const args once
  channel_push(channel_id, seq, slot,   one value frame for one input slot
               kind, payload)           of one execution
  channel_close(channel_id)             release the channel
  channel_result(sink_id, seq, slot,    worker -> driver delivery onto the
                 kind, payload)         CompiledDAG's output sink

A channel gathers the frames of execution `seq` until all of its input
slots arrived, then dispatches — strictly in seq order, so pipelined
in-flight executions cannot interleave on the actor even when their
frames arrive out of order. Results forward directly worker->worker along
the compiled edges (driver round-trips only at the sink), with a local
fast path when producer and consumer lanes share a worker process.

Error propagation is typed and per-sequence: an input error frame is
forwarded downstream without executing (poisoning exactly that seq), an
actor death surfaces as ActorDiedError carrying the actor id, and a
method raise travels as the raised exception itself.
"""

from __future__ import annotations

import asyncio
import inspect
import logging
import time
from dataclasses import dataclass
from typing import Any, Dict, Tuple

from ray_tpu.core import serialization
from ray_tpu.core.status import ActorDiedError, RayTpuError

_memory_mod = None


def _memattr():
    """Lazy memory-attribution tracker (observability imports core at
    module top, so core modules must import it on first use)."""
    global _memory_mod
    if _memory_mod is None:
        from ray_tpu.observability import memory
        _memory_mod = memory.tracker()
    return _memory_mod

logger = logging.getLogger("ray_tpu.channels")

# Standing-channel instruments, created on first channel_open (lazy so
# importing this module never pulls util.metrics -> runtime). Held in a
# module global because the metrics registry is weak.
_instruments = None


def _channel_instruments():
    global _instruments
    if _instruments is None:
        from ray_tpu.util import metrics
        _instruments = (
            metrics.Gauge(
                "ray_tpu_channel_queue_depth",
                "executions buffered in a standing channel's seq gather "
                "map (arrived but not yet dispatched)", tag_keys=("channel",)),
            metrics.Gauge(
                "ray_tpu_channel_inflight_seq",
                "next execution sequence a standing channel will dispatch "
                "(monotonic progress indicator)", tag_keys=("channel",)),
            metrics.Histogram(
                "ray_tpu_channel_hop_seconds",
                "per-hop forward latency along compiled-DAG channel edges",
                boundaries=[1e-5, 5e-5, 1e-4, 5e-4, 1e-3, 5e-3,
                            0.01, 0.05, 0.1, 0.5, 1.0],
                tag_keys=("channel",)),
        )
    return _instruments

# frame kinds
F_DATA = "data"    # one packed value
F_ERR = "err"      # packed exception; poisons this seq downstream
F_ITEM = "item"    # one packed stream item (sink-bound only)
F_END = "end"      # stream end; payload = packed item count


@dataclass(frozen=True)
class ChannelEdge:
    """One pre-resolved route out of a channel."""

    kind: str                 # "push" (to another channel) | "result" (sink)
    addr: Tuple[str, int]     # worker (push) or driver (result) RPC address
    target: str               # downstream channel_id or sink_id
    slot: int                 # input slot at the target
    label: str = ""           # consumer label, for edge telemetry


@dataclass
class ChannelSpec:
    """Everything a worker needs to host one compiled node; shipped once
    at channel_open, never per call."""

    channel_id: str
    actor_id: Any                       # ids.ActorID
    method: str
    args_template: Tuple[Tuple, ...]    # ("const",packed)|("slot",i)|("slot_attr",i,key)
    kwargs_template: Tuple[Tuple[str, Tuple], ...]
    n_slots: int                        # frames required per seq (>= 1)
    downstream: Tuple[ChannelEdge, ...] = ()
    streaming_ok: bool = False          # generator results stream item frames
    label: str = ""


def _extract(base: Any, key: Any) -> Any:
    """InputAttributeNode semantics, applied worker-side."""
    if isinstance(base, dict):
        return base[key]
    if isinstance(key, int):
        return base[key]
    return getattr(base, key)


def pack_value(value: Any) -> bytes:
    return serialization.pack(value)


def pack_error(err: BaseException) -> bytes:
    """Exceptions travel as themselves; unpicklable ones degrade to a
    typed wrapper carrying the repr."""
    try:
        return serialization.pack(err)
    except Exception:
        return serialization.pack(
            RayTpuError(f"{type(err).__name__}: {err!r}"))


class _Channel:
    """Worker-side state of one standing channel."""

    __slots__ = ("spec", "args_template", "kwargs_template", "frames",
                 "next_seq", "dispatched", "buffered_bytes", "mem_tracked")

    def __init__(self, spec: ChannelSpec):
        self.spec = spec
        # consts unpack ONCE here; per-execution cost is slot lookups only
        self.args_template = [self._prep(e) for e in spec.args_template]
        self.kwargs_template = [(k, self._prep(e))
                                for k, e in spec.kwargs_template]
        self.frames: Dict[int, Dict[int, Tuple[str, bytes]]] = {}
        self.next_seq = 0
        self.dispatched = 0
        self.buffered_bytes = 0     # payload bytes parked in `frames`
        self.mem_tracked = False    # synthetic record currently registered

    @staticmethod
    def _prep(entry: Tuple) -> Tuple:
        if entry[0] == "const":
            return ("const", serialization.unpack(entry[1]))
        return entry

    def build_args(self, values: Dict[int, Any]) -> Tuple[list, dict]:
        def one(entry):
            tag = entry[0]
            if tag == "const":
                return entry[1]
            if tag == "slot":
                return values[entry[1]]
            return _extract(values[entry[1]], entry[2])   # slot_attr

        return ([one(e) for e in self.args_template],
                {k: one(e) for k, e in self.kwargs_template})


class ChannelHost:
    """Hosts the standing channels of one worker process: gathers frames,
    dispatches executions onto actor lanes in seq order, forwards results
    along pre-resolved edges."""

    def __init__(self, worker):
        self.worker = worker
        self.runtime = worker.runtime
        self._channels: Dict[str, _Channel] = {}
        # one progress beacon for this host's channel reader: armed while
        # any channel holds partially-gathered / out-of-order seqs (the
        # compiled-graph wedge signature: an upstream stopped pushing
        # mid-execution), ticked on every frame
        from ray_tpu.observability import health
        self._beacon = health.beacon("channels", deadline_s=30.0)
        self._gauges = _channel_instruments()

    # ------------------------------------------------------------ rpc surface

    async def rpc_channel_open(self, spec: ChannelSpec) -> dict:
        lane = self.worker.lanes.get(spec.actor_id)
        if lane is None or lane.instance is None:
            return {"ok": False, "error": "no actor hosted here"}
        self._channels[spec.channel_id] = _Channel(spec)
        return {"ok": True}

    def push(self, channel_id: str, seq: int, slot: int, kind: str,
             payload: bytes) -> dict:
        """Synchronous, non-blocking up to the lane enqueue — eligible for
        the RPC server's inline ONEWAY fast path."""
        ch = self._channels.get(channel_id)
        if ch is None:
            return {"ok": False, "error": "no such channel"}
        self._deliver(ch, seq, slot, kind, payload)
        return {"ok": True}

    async def rpc_channel_close(self, channel_id: str) -> dict:
        ch = self._channels.pop(channel_id, None)
        if ch is not None and ch.mem_tracked:
            _memattr().release("channel:" +
                               (ch.spec.label or ch.spec.channel_id[:8]))
        return {"ok": True}

    # --------------------------------------------------------------- delivery

    def _deliver(self, ch: _Channel, seq: int, slot: int, kind: str,
                 payload: bytes) -> None:
        """Runs on the event loop (RPC handler or local fast path)."""
        if seq < ch.next_seq:
            return   # stale duplicate of an already-dispatched seq
        frames = ch.frames.setdefault(seq, {})
        prev = frames.get(slot)
        frames[slot] = (kind, payload)
        ch.buffered_bytes += len(payload) - (len(prev[1]) if prev else 0)
        fl = getattr(self.runtime, "flight", None)
        if fl is not None:
            fl.record({"kind": "channel_frame", "ts": time.time(),
                       "channel": ch.spec.label or ch.spec.channel_id[:8],
                       "seq": seq, "slot": slot, "frame_kind": kind,
                       "nbytes": len(payload)})
        # dispatch strictly in seq order: pipelined executions whose frames
        # raced ahead wait in the gather map until their turn
        while ch.frames.get(ch.next_seq) is not None \
                and len(ch.frames[ch.next_seq]) >= ch.spec.n_slots:
            slots = ch.frames.pop(ch.next_seq)
            ch.buffered_bytes -= sum(len(p) for _, p in slots.values())
            seq_now = ch.next_seq
            ch.next_seq += 1
            ch.dispatched += 1
            self._dispatch(ch, seq_now, slots)
        self._beacon.tick()
        label = ch.spec.label or ch.spec.channel_id[:8]
        self._track_buffer(ch, label)
        depth_g, seq_g, _hop = self._gauges
        depth_g.set(float(len(ch.frames)), {"channel": label})
        seq_g.set(float(ch.next_seq), {"channel": label})
        if ch.frames:
            self._beacon.arm(channel=label, waiting_seq=ch.next_seq,
                             buffered=len(ch.frames))
        elif self._beacon.busy \
                and not any(c.frames for c in self._channels.values()):
            self._beacon.disarm()

    def _track_buffer(self, ch: _Channel, label: str) -> None:
        """Mirror this channel's parked reorder bytes into the memory
        plane as a synthetic (non-store) record, pinned with the seq the
        gather is stuck behind while frames are parked — `top mem` then
        shows a wedged compiled graph as channel bytes waiting on a seq."""
        mem = _memattr()
        key = "channel:" + label
        if ch.buffered_bytes > 0:
            mem.attribute(key, "channel", ch.buffered_bytes, store=False,
                          waiting_seq=ch.next_seq, buffered=len(ch.frames))
            if not ch.mem_tracked:
                mem.pin(key, "reorder")
                ch.mem_tracked = True
        elif ch.mem_tracked:
            mem.release(key)
            ch.mem_tracked = False

    def _dispatch(self, ch: _Channel, seq: int,
                  slots: Dict[int, Tuple[str, bytes]]) -> None:
        # an errored input poisons this seq: forward, don't execute
        for kind, payload in slots.values():
            if kind == F_ERR:
                self._spawn_forward(ch, seq, F_ERR, payload)
                return
        lane = self.worker.lanes.get(ch.spec.actor_id)
        if lane is None or lane.instance is None:
            self._spawn_forward(ch, seq, F_ERR, pack_error(ActorDiedError(
                f"compiled-dag actor {ch.spec.actor_id.hex()[:12]} is not "
                f"hosted here (killed or restarted)",
                actor_id=ch.spec.actor_id.hex())))
            return
        method = getattr(lane.instance, ch.spec.method, None)
        if method is None:
            self._spawn_forward(ch, seq, F_ERR, pack_error(AttributeError(
                f"actor has no method {ch.spec.method!r}")))
            return
        if inspect.iscoroutinefunction(method) \
                or inspect.isasyncgenfunction(method):
            # async methods run on the loop; create order == dispatch order
            asyncio.get_running_loop().create_task(
                self._run_async(ch, seq, slots, lane, method))
            return
        # sync methods keep actor FIFO semantics: the whole
        # resolve+execute+pack rides the actor's serial lane executor
        fut = lane.executor.submit(self._run_sync, ch, seq, slots,
                                   lane, method)
        fut.add_done_callback(lambda f: f.exception())  # never unraised

    # -------------------------------------------------------------- execution

    def _run_sync(self, ch: _Channel, seq: int, slots, lane, method) -> None:
        """Lane-executor thread: unpack inputs, run, forward."""
        t0 = time.perf_counter()
        try:
            values = {i: serialization.unpack(p)
                      for i, (_, p) in slots.items()}
            args, kwargs = ch.build_args(values)
            value = method(*args, **kwargs)
        except BaseException as e:   # noqa: BLE001 — typed err frame
            if isinstance(e, KeyboardInterrupt) \
                    and self.worker.lanes.get(ch.spec.actor_id) is not lane:
                e = ActorDiedError(
                    f"compiled-dag actor {ch.spec.actor_id.hex()[:12]} "
                    "killed mid-execute", actor_id=ch.spec.actor_id.hex())
            self._spawn_forward(ch, seq, F_ERR, pack_error(e))
            return
        if ch.spec.streaming_ok and inspect.isgenerator(value):
            idx = 0
            try:
                for item in value:
                    idx += 1
                    self._spawn_forward(ch, seq, F_ITEM, pack_value(item))
                self._spawn_forward(ch, seq, F_END, pack_value(idx))
            except BaseException as e:   # noqa: BLE001 — typed err frame
                self._spawn_forward(ch, seq, F_ERR, pack_error(e))
            self._emit_span(ch, seq, t0)
            return
        self._spawn_forward(ch, seq, F_DATA, pack_value(value))
        self._emit_span(ch, seq, t0)

    async def _run_async(self, ch: _Channel, seq: int, slots, lane,
                         method) -> None:
        """Event loop: async (generator) methods; arg unpack still hops to
        the lane executor because user payloads can be arbitrarily big."""
        loop = asyncio.get_running_loop()
        t0 = time.perf_counter()
        try:
            args, kwargs = await loop.run_in_executor(
                lane.executor, self._build_async_args, ch, slots)
        except BaseException as e:   # noqa: BLE001 — typed err frame
            await self._forward(ch, seq, F_ERR, pack_error(e))
            return
        try:
            if inspect.isasyncgenfunction(method):
                if not ch.spec.streaming_ok:
                    raise TypeError(
                        f"{ch.spec.label or ch.spec.method}: generator "
                        "methods are only supported at a compiled DAG's "
                        "output node")
                agen = method(*args, **kwargs)
                idx = 0
                async for item in agen:
                    idx += 1
                    payload = await loop.run_in_executor(None, pack_value,
                                                         item)
                    await self._forward(ch, seq, F_ITEM, payload)
                await self._forward(ch, seq, F_END, pack_value(idx))
            else:
                async with lane.async_sem:
                    if self.worker.lanes.get(ch.spec.actor_id) is not lane \
                            or lane.instance is None:
                        raise ActorDiedError(
                            f"compiled-dag actor "
                            f"{ch.spec.actor_id.hex()[:12]} killed",
                            actor_id=ch.spec.actor_id.hex())
                    value = await method(*args, **kwargs)
                payload = await loop.run_in_executor(None, pack_value, value)
                await self._forward(ch, seq, F_DATA, payload)
            self._emit_span(ch, seq, t0)
        except BaseException as e:   # noqa: BLE001 — typed err frame
            await self._forward(ch, seq, F_ERR, pack_error(e))

    @staticmethod
    def _build_async_args(ch: _Channel, slots) -> Tuple[list, dict]:
        values = {i: serialization.unpack(p) for i, (_, p) in slots.items()}
        return ch.build_args(values)

    # ------------------------------------------------------------- forwarding

    def _spawn_forward(self, ch: _Channel, seq: int, kind: str,
                       payload: bytes) -> None:
        """Fire the forward from any thread without blocking the lane —
        the downstream's seq gate re-establishes ordering."""
        self.runtime._spawn(self._forward(ch, seq, kind, payload))

    async def _forward(self, ch: _Channel, seq: int, kind: str,
                       payload: bytes) -> None:
        for edge in ch.spec.downstream:
            # stream frames are sink-bound only: an intermediate consumer
            # of a streaming node is rejected at compile time
            if kind in (F_ITEM, F_END) and edge.kind != "result":
                continue
            try:
                t0 = time.perf_counter()
                await self._send_one(edge, seq, kind, payload)
                self._record_edge(ch, edge, len(payload),
                                  time.perf_counter() - t0)
            except Exception as e:
                # the consumer is unreachable: the driver's in-flight
                # poisoning (actor-state watch at the ref) surfaces it
                logger.warning("channel %s -> %s forward failed: %s",
                               ch.spec.label or ch.spec.channel_id,
                               edge.target[:12], e)

    async def _send_one(self, edge: ChannelEdge, seq: int, kind: str,
                        payload: bytes) -> None:
        addr = tuple(edge.addr)
        me = self.runtime.address
        if me is not None and addr == me.addr:
            # local fast path: producer and consumer lanes share this
            # worker (lane packing) or the driver compiled its own node
            if edge.kind == "push":
                chd = self._channels.get(edge.target)
                if chd is not None:
                    self._deliver(chd, seq, edge.slot, kind, payload)
                return
            if self.runtime.deliver_channel_result(edge.target, seq,
                                                   edge.slot, kind, payload):
                return
        # one-way frames: no reply round-trip on the hot path — the wire is
        # FIFO per connection and the consumer's seq gate tolerates loss
        # only via the driver's actor-death poisoning, which is the same
        # failure domain that would have eaten the reply anyway
        client = self.runtime.pool.get(addr)
        if edge.kind == "push":
            await client.oneway("channel_push", channel_id=edge.target,
                                seq=seq, slot=edge.slot, kind=kind,
                                payload=payload)
        else:
            await client.oneway("channel_result", sink_id=edge.target,
                                seq=seq, slot=edge.slot, kind=kind,
                                payload=payload)

    # ------------------------------------------------------------ telemetry

    def _record_edge(self, ch: _Channel, edge: ChannelEdge, nbytes: int,
                     seconds: float) -> None:
        """Per-edge EWMA observations under dag:-prefixed endpoints, so
        the observability edge model prices compiled hops the same way it
        prices object pulls and collective rounds."""
        try:
            self.runtime.telemetry.record_edge(
                f"dag:{ch.spec.label or ch.spec.channel_id[:8]}",
                f"dag:{edge.label or edge.target[:8]}",
                nbytes, seconds, kind="dag_channel")
            self._gauges[2].observe(
                seconds, {"channel": ch.spec.label or ch.spec.channel_id[:8]})
        except Exception:
            pass

    def _emit_span(self, ch: _Channel, seq: int, t0: float) -> None:
        from ray_tpu.util import tracing

        if tracing.is_enabled():
            tracing.emit_span(
                f"dag::{ch.spec.label or ch.spec.method}",
                time.time() - (time.perf_counter() - t0),
                time.perf_counter() - t0,
                {"seq": seq, "channel": ch.spec.channel_id[:8],
                 "actor_id": ch.spec.actor_id.hex()[:12]})
