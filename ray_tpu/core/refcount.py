"""Distributed reference counting (ownership protocol).

Reference: src/ray/core_worker/reference_count.h:59 — every object has one
owner (the process whose task created it / that called put). The owner tracks:
  - local refcount: live ObjectRef pythons in the owner process
  - submitted-task count: pending tasks that take the ref as an argument
  - borrower set: other processes holding deserialized copies of the ref

A borrower registers itself with the owner when it deserializes a ref and
deregisters when its last local ref dies (the reference's WaitForRefRemoved
push protocol is simplified to borrower-initiated add/remove messages — same
liveness outcome, fewer round trips, acceptable because borrowers that die
are detected via connection loss and their borrows dropped).

When all counts reach zero the owner frees the object: deletes copies from
every node store that holds one and drops lineage if no descendant needs it.
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Callable, Dict, Optional, Set, Tuple

from ray_tpu.core.common import RuntimeAddress
from ray_tpu.core.ids import ObjectID


class ReferenceCounter:
    def __init__(self, self_addr_fn: Callable[[], Optional[RuntimeAddress]],
                 on_zero: Callable[[ObjectID], None],
                 notify_owner: Callable[[RuntimeAddress, str, ObjectID], None],
                 on_borrow_zero: Optional[Callable[[ObjectID], None]] = None):
        """notify_owner(owner, op, oid) sends borrow add/remove to a remote
        owner asynchronously; on_zero(oid) frees an owned object;
        on_borrow_zero(oid) drops local caches of a borrowed object whose
        last local ref died (the owner keeps the authoritative copy)."""
        self._lock = threading.Lock()
        self._self_addr_fn = self_addr_fn
        self._on_zero = on_zero
        self._notify_owner = notify_owner
        self._on_borrow_zero = on_borrow_zero or (lambda oid: None)
        # owned objects: oid -> counts
        self._local: Dict[ObjectID, int] = defaultdict(int)
        self._submitted: Dict[ObjectID, int] = defaultdict(int)
        self._borrowers: Dict[ObjectID, Set[bytes]] = defaultdict(set)
        self._owned: Set[ObjectID] = set()
        # borrowed objects: oid -> (owner, local refcount)
        self._borrowed: Dict[ObjectID, Tuple[RuntimeAddress, int]] = {}

    # --- owner side ---------------------------------------------------------

    def register_owned(self, oid: ObjectID) -> None:
        with self._lock:
            self._owned.add(oid)

    def is_owned(self, oid: ObjectID) -> bool:
        with self._lock:
            return oid in self._owned

    def on_ref_created(self, oid: ObjectID, owner: RuntimeAddress) -> None:
        me = self._self_addr_fn()
        mine = me is not None and owner.worker_id == me.worker_id
        with self._lock:
            if mine or oid in self._owned:
                self._local[oid] += 1
                return
            entry = self._borrowed.get(oid)
            if entry is None:
                self._borrowed[oid] = (owner, 1)
                notify = True
            else:
                self._borrowed[oid] = (entry[0], entry[1] + 1)
                notify = False
        if notify and me is not None:
            self._notify_owner(owner, "add_borrow", oid)

    def on_ref_deleted(self, oid: ObjectID, owner: RuntimeAddress) -> None:
        me = self._self_addr_fn()
        mine = me is not None and owner.worker_id == me.worker_id
        freed = False
        notify = False
        with self._lock:
            if mine or oid in self._owned:
                self._local[oid] -= 1
                freed = self._zero_locked(oid)
            else:
                entry = self._borrowed.get(oid)
                if entry is not None:
                    owner_addr, n = entry
                    if n <= 1:
                        del self._borrowed[oid]
                        notify = True
                    else:
                        self._borrowed[oid] = (owner_addr, n - 1)
        if notify:
            # last local borrow died: drop local caches (memory-store
            # entries warmed by prefetch, read pins) — no other decrement
            # event exists for borrowed ids, so skipping this leaks them
            self._on_borrow_zero(oid)
            if me is not None:
                self._notify_owner(owner, "remove_borrow", oid)
        if freed:
            self._on_zero(oid)

    def release_owned_if_unreferenced(self, oid: ObjectID) -> bool:
        """Free an owned object NOW if nothing references it. Needed for
        objects registered owned without any local ObjectRef (stream items
        the consumer never claimed): no decrement event will ever fire for
        them, so an explicit sweep is the only path to _on_zero."""
        freed = False
        with self._lock:
            if oid in self._owned:
                freed = self._zero_locked(oid)
        if freed:
            self._on_zero(oid)
        return freed

    def on_task_submitted(self, arg_ids) -> None:
        with self._lock:
            for oid in arg_ids:
                self._submitted[oid] += 1

    def on_task_done(self, arg_ids) -> None:
        freed = []
        with self._lock:
            for oid in arg_ids:
                self._submitted[oid] -= 1
                if oid in self._owned and self._zero_locked(oid):
                    freed.append(oid)
        for oid in freed:
            self._on_zero(oid)

    def add_borrower(self, oid: ObjectID, borrower_id: bytes) -> None:
        with self._lock:
            self._borrowers[oid].add(borrower_id)

    def remove_borrower(self, oid: ObjectID, borrower_id: bytes) -> None:
        freed = False
        with self._lock:
            self._borrowers[oid].discard(borrower_id)
            if oid in self._owned:
                freed = self._zero_locked(oid)
        if freed:
            self._on_zero(oid)

    def remove_borrower_everywhere(self, borrower_id: bytes) -> None:
        """Borrower process died: drop all its borrows (liveness)."""
        freed = []
        with self._lock:
            for oid, bs in self._borrowers.items():
                if borrower_id in bs:
                    bs.discard(borrower_id)
                    if oid in self._owned and self._zero_locked(oid):
                        freed.append(oid)
        for oid in freed:
            self._on_zero(oid)

    def _zero_locked(self, oid: ObjectID) -> bool:
        if oid not in self._owned:
            return False
        if (self._local.get(oid, 0) <= 0 and self._submitted.get(oid, 0) <= 0
                and not self._borrowers.get(oid)):
            self._owned.discard(oid)
            self._local.pop(oid, None)
            self._submitted.pop(oid, None)
            self._borrowers.pop(oid, None)
            return True
        return False

    def stats(self) -> dict:
        with self._lock:
            return {
                "owned": len(self._owned),
                "borrowed": len(self._borrowed),
                "with_borrowers": sum(1 for b in self._borrowers.values() if b),
            }
