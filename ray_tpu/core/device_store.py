"""HBM-resident object tier: device arrays stay on-device at put time.

Reference: the plasma store (src/ray/object_manager/plasma/store.h:55) is
the reference's primary tier — every object is host bytes in shm. On TPU
the expensive copy is device<->host over PCIe, so this tier inverts the
design (SURVEY §7 step 2): `put(jax.Array)` registers the live device
buffer in a per-process table and defers the D2H transfer until a REMOTE
consumer actually needs the bytes (host-staging through the shm store,
from where the existing native transfer plane ships them) or until HBM
pressure spills it. A same-process `get` returns the identical jax.Array
object — zero copies, zero D2H.

Spill chain: HBM (this table) -> host shm (store) -> disk (the nodelet's
existing spill loop). Cross-process device sharing does not exist on TPU
(each process owns its chip's context), so this tier is deliberately
per-process; the shm tier remains the cross-process meeting point.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, List, Optional, Tuple


def _is_device_array(x: Any) -> bool:
    t = type(x)
    if not (t.__module__.startswith("jax")
            and t.__name__ in ("ArrayImpl", "Array")):
        return False
    try:
        return bool(x.is_fully_addressable) and not x.is_deleted()
    except Exception:
        return False


def is_device_value(x: Any) -> bool:
    """True for a value the HBM tier accepts: a concrete,
    fully-addressable jax.Array, or a pytree whose EVERY leaf is one
    (the train/serve hot-path shape — a params pytree put for weight
    sync). Mixed trees take the host path: partial residency would
    split one object across tiers."""
    return try_device_snapshot(x, -1) is not None


def try_device_snapshot(x: Any, min_bytes: int):
    """ONE traversal deciding device-tier admission: returns
    (snapshot, nbytes) or None. The snapshot shares every leaf buffer
    (zero-copy) but owns fresh containers, so the caller mutating its
    own dict/list after put() cannot desync the stored object or its
    byte accounting. nbytes dedupes aliased leaves (tied weights appear
    once per buffer, not once per tree path)."""
    if _is_device_array(x):
        n = int(x.nbytes)
        return (x, n) if n > min_bytes else None
    if not isinstance(x, (dict, list, tuple)) or not x:
        return None
    try:
        import jax

        leaves, treedef = jax.tree.flatten(x)
    except Exception:
        return None
    if not leaves or not all(_is_device_array(a) for a in leaves):
        return None
    seen, total = set(), 0
    for a in leaves:
        if id(a) not in seen:
            seen.add(id(a))
            total += int(a.nbytes)
    if total <= min_bytes:
        return None
    return jax.tree.unflatten(treedef, leaves), total


def any_leaf_deleted(x: Any) -> bool:
    """True if any array in the value was donated/deleted under us."""
    import jax

    leaves = [x] if _is_device_array(x) else jax.tree.leaves(x)
    for a in leaves:
        if getattr(a, "is_deleted", lambda: False)():
            return True
    return False


class DeviceStore:
    """oid -> live jax.Array, LRU-ordered, byte-accounted.

    Eviction is NOT decided here: the runtime asks for `victims(need)`
    and host-stages them through the shm store before dropping, so a
    device object is never lost — only demoted down the spill chain.
    """

    def __init__(self, capacity_bytes: int):
        self.capacity = int(capacity_bytes)
        self._lock = threading.Lock()
        self._objs: "OrderedDict[Any, Tuple[Any, int]]" = OrderedDict()
        self.total = 0

    def put(self, oid, arr, nbytes: Optional[int] = None) -> int:
        if nbytes is None:
            snap = try_device_snapshot(arr, -1)
            nbytes = snap[1] if snap else 0
        with self._lock:
            old = self._objs.pop(oid, None)
            if old is not None:
                self.total -= old[1]
            self._objs[oid] = (arr, nbytes)
            self.total += nbytes
        return nbytes

    def get(self, oid) -> Optional[Any]:
        with self._lock:
            ent = self._objs.get(oid)
            if ent is None:
                return None
            self._objs.move_to_end(oid)     # LRU touch
            return ent[0]

    def contains(self, oid) -> bool:
        with self._lock:
            return oid in self._objs

    def delete(self, oid) -> bool:
        with self._lock:
            ent = self._objs.pop(oid, None)
            if ent is None:
                return False
            self.total -= ent[1]
            return True

    def over_capacity(self) -> int:
        """Bytes above the watermark (0 if within budget)."""
        with self._lock:
            return max(self.total - self.capacity, 0)

    def victims(self, need_bytes: int) -> List[Any]:
        """Oldest-first oids whose combined size covers `need_bytes`.
        Does not remove them — the runtime stages each to shm first."""
        out, covered = [], 0
        with self._lock:
            for oid, (_, nbytes) in self._objs.items():
                if covered >= need_bytes:
                    break
                out.append(oid)
                covered += nbytes
        return out

    def stats(self) -> dict:
        with self._lock:
            return {"objects": len(self._objs), "bytes": self.total,
                    "capacity": self.capacity}
