"""Per-node daemon ("nodelet").

Reference: src/ray/raylet/ — NodeManager (node_manager.h:119) owns the worker
pool, grants worker leases, manages local resources and placement-group
bundles, and embeds the object plane. Re-designs for TPU hosts:

- Resources are {CPU, TPU(chips), memory, custom...}; the TPU quantity is the
  host's local chip count, and slice/ICI topology labels ride on the
  NodeInfo record so the control plane can gang-schedule whole slices.
- The node object store is the native shm segment (ray_tpu/native); the
  nodelet creates it and hands its name to every worker it spawns.
- Object transfer between nodes is chunked pull over the RPC layer
  (ref: ObjectManager::Push/HandlePush object_manager.cc:338,561 and
  PullManager pull_manager.h:52): the requesting nodelet streams chunks from
  the holder into a create/seal buffer.

Lease protocol (ref: node_manager.cc:1881 HandleRequestWorkerLease →
cluster_task_manager.h:42 queue/dispatch/spillback):
  owner → rpc_request_lease(resources, ...) →
    granted {worker_addr, lease_id} | spillback {addr} | queued until free.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import signal
import subprocess
import sys
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core.common import Address, NodeInfo, ResourceSet, TaskSpec
from ray_tpu.core.config import Config
from ray_tpu.core.external_storage import FilesystemStorage
from ray_tpu.core.ids import NodeID, ObjectID, PlacementGroupID
from ray_tpu.core.memory_monitor import (KillCandidate, MemoryMonitor,
                                         pick_worker_to_kill)
from ray_tpu.core.object_store import SharedMemoryStore
from ray_tpu.core.rpc import ClientPool, ConnectionLost, RemoteError, RpcServer
from ray_tpu.util.backoff import Backoff
from ray_tpu.util.idempotency import IdemCache

logger = logging.getLogger("ray_tpu.nodelet")

_memory_mod = None


def _memattr():
    """Lazy memory-attribution tracker (observability imports core at
    module top, so core modules must import it on first use)."""
    global _memory_mod
    if _memory_mod is None:
        from ray_tpu.observability import memory
        _memory_mod = memory.tracker()
    return _memory_mod


class WorkerRecord:
    def __init__(self, worker_id: bytes, proc: subprocess.Popen,
                 env_key: str = ""):
        self.worker_id = worker_id
        self.proc = proc
        self.env_key = env_key         # runtime-env pool key ("" = plain)
        self.addr: Optional[Address] = None
        self.state = "starting"        # starting | idle | leased | actor | dead
        self.lease_id: Optional[bytes] = None
        self.job_id: Optional[bytes] = None
        self.last_idle = time.time()
        self.lease_time = 0.0          # when the current lease was granted
        self.retriable = True          # current task retries on worker death
        self.resources_released = False  # blocked in get(); CPU given back
        self.actor_id = None           # set when this worker hosts an actor
        self.lane_host = False         # hosts multiple fractional actors
        self.lanes: Dict = {}          # actor_id -> ResourceSet (lane hosts)
        self.ready = asyncio.Event()


class _PendingLease:
    def __init__(self, resources: ResourceSet, pg, fut, job_id=None,
                 retriable=True, env_vars=None):
        self.resources = resources
        self.pg = pg                   # (pg_id, bundle_index) or None
        self.fut: asyncio.Future = fut
        self.job_id = job_id
        self.retriable = retriable
        self.env_vars = env_vars       # process_env_vars for the worker


def _env_key(env_vars) -> str:
    """Pool key for a process-env dict ("" = plain pool)."""
    if not env_vars:
        return ""
    return json.dumps(sorted(env_vars.items()))


class Nodelet:
    def __init__(self, cfg: Config, gcs_addr: Address, session_dir: str,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, Any]] = None,
                 store_name: Optional[str] = None):
        self.cfg = cfg
        self.gcs_addr = gcs_addr
        self.session_dir = session_dir
        # deadlines/keepalive knobs + optional chaos plan bind from the
        # inherited Config so the whole cluster shares one failure model
        from ray_tpu.core import rpc as _rpc
        from ray_tpu.devtools import chaos as _chaos
        _rpc.configure(cfg)
        _chaos.maybe_install(cfg, role="nodelet")
        _chaos.note_peer(tuple(gcs_addr), "gcs")
        self.node_id = NodeID.from_random()
        self.store_name = store_name or f"/raytpu_{self.node_id.hex()[:12]}"
        res = dict(resources) if resources else {}
        res.setdefault("CPU", float(os.cpu_count() or 1))
        self.total = ResourceSet(res)
        self.available = self.total.copy()
        self.labels = labels or {}
        self.workers: Dict[bytes, WorkerRecord] = {}
        # pulsed whenever any worker turns idle, so lease waiters wake
        # immediately instead of on a poll tick (a 20 ms poll quantized
        # every lease grant under fan-out: ~46 obj-arg tasks/s vs ~390
        # event-driven; ref: worker_pool.h callbacks fire on idle)
        self._worker_idle = asyncio.Event()
        self.leases: Dict[bytes, WorkerRecord] = {}
        self.lease_resources: Dict[bytes, Tuple[ResourceSet, Optional[Tuple]]] = {}
        self.pending: deque[_PendingLease] = deque()
        # permanently-infeasible lease asks (no node fits, no spillback
        # target): queued here and shipped to the GCS on the next
        # heartbeat as autoscaler-visible unmet demand (ref: the
        # raylet's infeasible queue feeding autoscaler state)
        self._infeasible: List[dict] = []
        # pg_id -> {bundle_index -> {"resources", "available", "committed"}}
        self.pg_bundles: Dict[PlacementGroupID, Dict[int, dict]] = {}
        self.pool = ClientPool()
        self.server = RpcServer(self)
        self.store: Optional[SharedMemoryStore] = None
        self.spill: Optional[FilesystemStorage] = None
        # Primary copies pinned on behalf of owners (ref: raylet pins
        # primaries, local_object_manager spills them under pressure). The
        # nodelet may spill-then-unpin these autonomously: the disk copy
        # keeps the availability guarantee.
        self.primary_pins: set = set()
        self._spilled_then_dropped = 0
        self._restored = 0
        # cumulative spill-tier traffic (bytes written to / read back
        # from disk) — the observability plane's evidence of what the
        # spill loop actually does, vs. the point-in-time on-disk gauge
        self._spill_bytes_total = 0
        self._restore_bytes_total = 0
        self._native_pulls = 0
        self.xfer_port = -1
        # source addr -> (xfer port or -1, cache expiry time)
        self._xfer_ports: Dict[Tuple, Tuple[int, float]] = {}
        self._hb_seq = 0
        self._stopping = False
        self._lane_locks: Dict[str, asyncio.Lock] = {}
        # Idempotency-token dedupe for the two side-effecting handlers a
        # duplicated frame (retry after dropped response, chaos-injected
        # duplication) would double-spend: lease grants and actor
        # creation. Only granted/ok outcomes are replayed — see
        # util/idempotency.py for why failures must not be.
        self._idem_lease = IdemCache()
        self._idem_create = IdemCache()
        self.memory_monitor = MemoryMonitor(
            cfg.memory_usage_threshold, cfg.memory_monitor_test_usage_file)

    # ------------------------------------------------------------------- boot

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> Address:
        self.store = SharedMemoryStore(
            self.store_name, capacity=self.cfg.object_store_memory,
            max_objects=self.cfg.object_store_max_objects, create=True)
        # Native transfer plane (xfer.cc): shm->socket zero-staging path
        # for inter-node pulls; -1 (disabled or failed to start) falls
        # back to the chunk RPC path transparently.
        self.xfer_port = self.store.xfer_serve_start(host) \
            if self.cfg.native_transfer_enabled else -1
        if self.xfer_port > 0:
            self.store.xfer_set_serve_cap(self.cfg.object_serve_concurrency)
        self.server.host, self.server.port = host, port
        addr = await self.server.start()
        info = NodeInfo(node_id=self.node_id, nodelet_addr=addr,
                        resources_total=self.total, labels=self.labels,
                        store_name=self.store_name)
        self._node_info = info
        gcs = self.pool.get(self.gcs_addr)
        r = await gcs.call("register_node", info=info,
                           timeout=self.cfg.rpc_connect_timeout_s)
        assert r["ok"]
        if self.cfg.object_spill_enabled:
            spill_dir = self.cfg.object_spill_dir or os.path.join(
                self.session_dir, "spill", self.node_id.hex()[:12])
            self.spill = FilesystemStorage(spill_dir)
        loop = asyncio.get_running_loop()
        loop.create_task(self._heartbeat_loop())
        loop.create_task(self._reap_loop())
        loop.create_task(self._log_loop())
        if self.cfg.metrics_report_interval_s > 0:
            loop.create_task(self._agent_loop())
        if self.spill is not None:
            loop.create_task(self._spill_loop())
        if self.cfg.memory_monitor_refresh_ms > 0:
            loop.create_task(self._memory_monitor_loop())
        n_prestart = self.cfg.worker_pool_prestart
        if n_prestart < 0:   # auto: a pair of warm workers per node —
            # enough that back-to-back leases never wait on the previous
            # lease-return race; more would tax node start (each worker
            # spawn is a full interpreter + jax import)
            n_prestart = int(min(self.total.quantities.get("CPU", 1.0), 2))
        self._prestart_n = min(n_prestart, self.cfg.max_workers_per_node)
        for _ in range(self._prestart_n):
            loop.create_task(self._start_worker())
        return addr

    async def _heartbeat_loop(self):
        period = self.cfg.health_check_period_s / 2
        gcs = self.pool.get(self.gcs_addr)
        while not self._stopping:
            self._hb_seq += 1
            infeasible, self._infeasible = self._infeasible, []
            try:
                r = await gcs.call("heartbeat", node_id=self.node_id,
                                   seqno=self._hb_seq,
                                   available=self.available,
                                   pending_leases=len(self.pending),
                                   infeasible=infeasible or None,
                                   timeout=5.0)
                if r.get("reregister"):
                    # GCS restarted without membership (fresh or restored
                    # snapshot): re-announce this node, including the actors
                    # it hosts, so the control plane rebuilds its view
                    # without double-creating (ref: GCS failover).
                    await gcs.call("register_node", info=self._node_info,
                                   hosted=self._hosted_actors(), timeout=5.0)
            except (ConnectionLost, RemoteError, OSError):
                # requeue undelivered infeasible rows for the next beat
                self._infeasible = infeasible + self._infeasible
                del self._infeasible[:-32]
            await asyncio.sleep(period)

    async def _agent_loop(self):
        """Embedded dashboard agent (ref: dashboard/agent.py + reporter
        module): push node+host stats to GCS KV so the dashboard head
        aggregates with one KV scan instead of per-node fan-out."""
        from ray_tpu.dashboard.agent import run_agent

        gcs = self.pool.get(self.gcs_addr)

        async def gcs_call_async(method, **kw):
            return await gcs.call(method, timeout=5.0, **kw)

        await run_agent(self, gcs_call_async,
                        self.cfg.metrics_report_interval_s,
                        stop_fn=lambda: self._stopping)

    async def _reap_loop(self):
        """Detect worker deaths; free leases; report to GCS
        (ref: NodeManager worker failure path / HandleUnexpectedWorkerFailure).
        Also reaps store buffers orphaned in kCreating by a producer that
        died mid-write — without this the object id is permanently
        unfetchable on this node (create always sees 'exists')."""
        last_orphan_scan = time.time()
        while not self._stopping:
            await asyncio.sleep(0.1)
            now = time.time()
            if now - last_orphan_scan > 30.0:
                last_orphan_scan = now
                try:
                    n = self.store.reap_creating(
                        self.cfg.creating_orphan_age_s)
                    if n:
                        logger.warning(
                            "reaped %d orphaned in-creation store "
                            "buffers", n)
                except Exception:
                    pass
            for w in list(self.workers.values()):
                if w.state == "dead":
                    continue
                rc = w.proc.poll()
                if rc is not None:
                    was = w.state
                    self._on_worker_dead(w)
                    if was in ("leased", "actor"):
                        await self._report_worker_death(w, f"exit code {rc}")
                elif (w.state == "idle"
                      and now - w.last_idle > self.cfg.worker_idle_timeout_s
                      and len(self.workers) > getattr(self, "_prestart_n",
                                                      0)):
                    self._kill_worker(w, "idle timeout")

    async def _log_loop(self):
        """Tail worker stdout/stderr files and publish new lines to the
        driver via GCS pubsub (ref: _private/log_monitor.py:102 → driver
        print_to_stdstream worker.py:1758)."""
        offsets: Dict[str, int] = {}
        gcs = self.pool.get(self.gcs_addr)
        logdir = os.path.join(self.session_dir, "logs")
        import glob

        while not self._stopping:
            await asyncio.sleep(0.5)
            lines = []
            for path in glob.glob(os.path.join(logdir, "worker-*.out")) + \
                    glob.glob(os.path.join(logdir, "worker-*.err")):
                try:
                    size = os.path.getsize(path)
                    off = offsets.get(path, 0)
                    if size > off:
                        with open(path, "rb") as f:
                            f.seek(off)
                            chunk = f.read(min(size - off, 1 << 20))
                        offsets[path] = off + len(chunk)
                        stream = "err" if path.endswith(".err") else "out"
                        src = os.path.basename(path).rsplit(".", 1)[0]
                        for ln in chunk.decode(errors="replace").splitlines():
                            lines.append({"source": src, "stream": stream,
                                          "line": ln})
                except OSError:
                    continue
            if lines:
                try:
                    await gcs.call("publish", channel="log",
                                   message={"node": self.node_id.hex()[:8],
                                            "lines": lines}, timeout=5.0)
                except Exception:
                    pass

    def _on_worker_dead(self, w: WorkerRecord):
        w.state = "dead"
        self.workers.pop(w.worker_id, None)
        if w.lease_id is not None:
            self._release_lease(w.lease_id)
        # a dead lane host gives back every lane's fractional resources
        for res in w.lanes.values():
            self.available.add(res)
        w.lanes = {}
        if w.lane_host:
            self._drain_pending()
        # a death frees a pool slot: wake saturated lease waiters so a
        # replacement spawns now, not at the 0.5 s wait cap
        self._worker_idle.set()

    # ---------------------------------------------------------------- workers

    async def _start_worker(self, env_vars=None) -> Optional[WorkerRecord]:
        worker_id = os.urandom(20)
        log_base = os.path.join(self.session_dir, "logs", f"worker-{worker_id.hex()[:12]}")
        os.makedirs(os.path.dirname(log_base), exist_ok=True)
        out = open(log_base + ".out", "ab")
        err = open(log_base + ".err", "ab")
        env = dict(os.environ)
        env["RAY_TPU_SESSION_DIR"] = self.session_dir
        if env_vars:
            # runtime-env-keyed pool: these must exist before the worker
            # interpreter imports anything (JAX_PLATFORMS, XLA_FLAGS, ...)
            # (ref: worker_pool.h:156 runtime-env-keyed worker pools)
            env.update(env_vars)
        cmd = [sys.executable, "-m", "ray_tpu.core.worker",
               "--nodelet", f"{self.server.host}:{self.server.port}",
               "--gcs", f"{self.gcs_addr[0]}:{self.gcs_addr[1]}",
               "--store", self.store_name,
               "--node-id", self.node_id.hex(),
               "--worker-id", worker_id.hex(),
               "--config", self.cfg.to_json()]
        proc = subprocess.Popen(cmd, stdout=out, stderr=err, env=env,
                                start_new_session=True)
        out.close(); err.close()
        w = WorkerRecord(worker_id, proc, env_key=_env_key(env_vars))
        self.workers[worker_id] = w
        try:
            await asyncio.wait_for(w.ready.wait(), self.cfg.worker_start_timeout_s)
        except asyncio.TimeoutError:
            self._kill_worker(w, "startup timeout")
            return None
        return w

    def _kill_worker(self, w: WorkerRecord, reason: str):
        logger.info("killing worker %s: %s", w.worker_id.hex()[:8], reason)
        was = w.state
        try:
            w.proc.terminate()
        except Exception:
            pass
        self._on_worker_dead(w)
        if was in ("leased", "actor"):
            # Deliberate kills of busy workers (OOM, shutdown, requested)
            # must reach the control plane so actor FSMs restart / owners
            # learn the death reason (ref: NodeManager worker failure path).
            try:
                asyncio.get_running_loop().create_task(
                    self._report_worker_death(w, reason))
            except RuntimeError:
                pass

    def _hosted_actors(self) -> dict:
        out = {}
        for w in self.workers.values():
            if w.state != "actor" or w.addr is None:
                continue
            if w.lane_host:
                for aid in w.lanes:
                    out[aid.hex()] = {"addr": w.addr,
                                      "worker_id": w.worker_id}
            elif w.actor_id is not None:
                out[w.actor_id.hex()] = {"addr": w.addr,
                                         "worker_id": w.worker_id}
        return out

    async def _report_worker_death(self, w: WorkerRecord, reason: str,
                                   actor_id=None):
        # Durable best-effort: the GCS may be mid-restart; keep retrying
        # through the failover window so actor FSMs see the death
        # (ref: raylet death reports + GCS reconnect). actor_id scopes the
        # report to ONE lane of a surviving lane-host worker. Jittered
        # exponential backoff: every worker of a dead node reports at
        # once, and fixed sleeps would herd them against the restarting
        # GCS in lockstep.
        bo = Backoff(base_s=0.1, cap_s=2.0,
                     deadline_s=time.time() + self.cfg.gcs_reconnect_timeout_s)
        while not self._stopping:
            try:
                await self.pool.get(self.gcs_addr).call(
                    "report_worker_death", worker_id=w.worker_id,
                    node_id=self.node_id, reason=reason,
                    actor_id=actor_id, timeout=5.0)
                return
            except Exception:
                if bo.expired():
                    return
                await asyncio.sleep(bo.next_delay())

    async def _memory_monitor_loop(self):
        """Kill a worker when host memory crosses the threshold
        (ref: memory_monitor.h:52 polling + worker_killing_policy*.h)."""
        mm = self.memory_monitor
        period = self.cfg.memory_monitor_refresh_ms / 1000.0
        while not self._stopping:
            await asyncio.sleep(period)
            try:
                if not mm.above_threshold():
                    continue
                cands = [KillCandidate(w.worker_id, w.job_id,
                                       w.state == "actor",
                                       w.retriable and w.state == "leased",
                                       w.lease_time)
                         for w in self.workers.values()
                         if w.state in ("leased", "actor")]
                victim = pick_worker_to_kill(
                    cands, self.cfg.memory_monitor_kill_policy)
                if victim is None:
                    continue
                w = self.workers.get(victim.worker_id)
                if w is not None:
                    mm.kills += 1
                    self._kill_worker(
                        w, f"OOM: node memory usage "
                        f"{mm.usage_fraction():.2f} > {mm.threshold:.2f} "
                        "(memory monitor)")
            except Exception:
                logger.exception("memory monitor pass failed")

    async def rpc_register_worker(self, worker_id: bytes, addr: Address) -> dict:
        w = self.workers.get(worker_id)
        if w is None:
            return {"ok": False}
        w.addr = tuple(addr)
        w.state = "idle"
        w.last_idle = time.time()
        w.ready.set()
        self._worker_idle.set()
        from ray_tpu.devtools.chaos import note_peer
        note_peer(w.addr, "worker")
        return {"ok": True}

    async def rpc_worker_blocked(self, worker_id: bytes) -> dict:
        """A leased worker is blocking in get(): give its lease's
        resources back to the pool so what it waits on can schedule
        (ref: NotifyDirectCallTaskBlocked -> raylet releases CPU)."""
        w = self.workers.get(worker_id)
        # actors too: an actor blocking in get() holds its creation
        # resources; releasing them is what prevents actor-getter fleets
        # from deadlocking the node
        if w is None or w.state not in ("leased", "actor") \
                or w.lease_id is None or w.resources_released:
            return {"ok": False}
        entry = self.lease_resources.get(w.lease_id)
        if entry is None:
            return {"ok": False}
        resources, pg = entry
        pool = self._resource_pool(pg)
        if pool is not None:
            pool.add(resources)
        w.resources_released = True
        self._drain_pending()
        return {"ok": True}

    async def rpc_worker_unblocked(self, worker_id: bytes) -> dict:
        """Re-subtract on unblock; transient oversubscription is allowed
        (the reference reacquires the same way)."""
        w = self.workers.get(worker_id)
        if w is None or not w.resources_released or w.lease_id is None:
            return {"ok": False}
        entry = self.lease_resources.get(w.lease_id)
        if entry is not None:
            resources, pg = entry
            pool = self._resource_pool(pg)
            if pool is not None:
                pool.subtract(resources)
        w.resources_released = False
        return {"ok": True}

    async def rpc_dump_worker_stacks(self) -> dict:
        """Fan a stack-dump request to every live worker on this node,
        concurrently — hung workers (the thing `ray stack` debugs) must
        cost one timeout total, not one each."""
        live = [w for w in self.workers.values()
                if w.addr is not None and w.state != "dead"]

        async def dump(w):
            try:
                r = await self.pool.get(tuple(w.addr)).call(
                    "dump_stacks", timeout=5.0)
                r["state"] = w.state
                return r
            except Exception as e:
                return {"error": str(e), "state": w.state}

        results = await asyncio.gather(*(dump(w) for w in live))
        return {"node_id": self.node_id.hex(),
                "workers": {w.worker_id.hex()[:12]: r
                            for w, r in zip(live, results)}}

    async def rpc_kill_worker(self, worker_id: bytes, reason: str = "",
                              actor_id=None) -> dict:
        w = self.workers.get(worker_id)
        if w is None:
            return {"ok": True}
        if actor_id is not None and w.lane_host:
            # lane-scoped kill: only this actor dies, the host (and its
            # other lanes) lives on
            res = w.lanes.pop(actor_id, None)
            if res is not None:
                self.available.add(res)
                self._drain_pending()
            try:
                await self.pool.get(tuple(w.addr)).call(
                    "destroy_actor", actor_id=actor_id, timeout=10.0)
            except (ConnectionLost, RemoteError, OSError) as e:
                self._kill_worker(w, f"lane destroy failed: {e}")
                return {"ok": True}
            # actor-scoped death report so the GCS actor FSM sees it
            # (the host process survives, so no worker-death event fires)
            loop = asyncio.get_running_loop()
            loop.create_task(self._report_worker_death(
                w, reason or "requested", actor_id=actor_id))
            self._lane_host_maybe_idle(w)
            return {"ok": True}
        self._kill_worker(w, reason or "requested")
        return {"ok": True}

    def _countable_workers(self) -> int:
        """Pool occupancy for the max_workers cap. Workers blocked in
        get() don't count — their resources are released and the work
        they wait on may need a fresh worker here (the reference's pool
        grows past the soft cap for exactly this reason; a hard cap
        would deadlock getter fleets)."""
        return sum(1 for w in self.workers.values()
                   if not w.resources_released)

    async def _pop_worker(self, env_vars=None) -> Optional[WorkerRecord]:
        """Pop an idle worker from the pool keyed by the process-env hash
        (ref: worker_pool.h:156 runtime-env-keyed pools). Workers from a
        different pool are never handed out — their process env was fixed
        at spawn."""
        key = _env_key(env_vars)
        for w in self.workers.values():
            if w.state == "idle" and w.env_key == key:
                return w
        if self._countable_workers() < self.cfg.max_workers_per_node:
            return await self._start_worker(env_vars)
        # Saturated: evict an idle worker from another pool to make room
        # (the reference kills idle workers of stale envs under pressure).
        for w in list(self.workers.values()):
            if w.state == "idle" and w.env_key != key:
                self._kill_worker(w, "evicted for runtime-env pool")
                return await self._start_worker(env_vars)
        # Otherwise wait for a matching worker to go idle — or for ANY
        # idle worker we can evict (a lease released mid-wait from another
        # pool must not stall this request for the full timeout).
        # Event-driven: the idle pulse wakes every waiter; each re-scans
        # and losers re-arm (ref: worker_pool callbacks on PushWorker).
        deadline = time.time() + self.cfg.worker_lease_timeout_s
        while True:
            remaining = deadline - time.time()
            if remaining <= 0:
                return None
            self._worker_idle.clear()
            for w in self.workers.values():
                if w.state == "idle" and w.env_key == key:
                    return w
            if self._countable_workers() < self.cfg.max_workers_per_node:
                return await self._start_worker(env_vars)
            for w in list(self.workers.values()):
                if w.state == "idle" and w.env_key != key:
                    self._kill_worker(w, "evicted for runtime-env pool")
                    return await self._start_worker(env_vars)
            try:
                await asyncio.wait_for(self._worker_idle.wait(),
                                       min(remaining, 0.5))
            except asyncio.TimeoutError:
                pass

    # ----------------------------------------------------------------- leases

    def _resource_pool(self, pg: Optional[Tuple]) -> Optional[ResourceSet]:
        """The pool a lease draws from: node-available or a committed bundle."""
        if pg is None:
            return self.available
        pg_id, bundle_index = pg
        bundles = self.pg_bundles.get(pg_id)
        if not bundles:
            return None
        if bundle_index >= 0:
            b = bundles.get(bundle_index)
            return b["available"] if b and b["committed"] else None
        for b in bundles.values():
            if b["committed"]:
                return b["available"]
        return None

    async def rpc_request_lease(self, resources: ResourceSet,
                                pg: Optional[Tuple] = None,
                                grant_or_reject: bool = False,
                                job_id: Optional[bytes] = None,
                                retriable: bool = True,
                                env_vars: Optional[dict] = None,
                                idem: Optional[str] = None) -> dict:
        """``idem``: caller-minted idempotency token. A duplicated frame
        replays the recorded grant instead of leasing a second worker;
        non-granted verdicts (retry/spillback/infeasible) are never
        cached, so a genuine retry with a fresh token re-attempts."""
        return await self._idem_lease.run(
            idem,
            lambda: self._request_lease(resources, pg, grant_or_reject,
                                        job_id, retriable, env_vars),
            cache_if=lambda r: r.get("status") == "granted")

    async def _request_lease(self, resources: ResourceSet,
                             pg: Optional[Tuple] = None,
                             grant_or_reject: bool = False,
                             job_id: Optional[bytes] = None,
                             retriable: bool = True,
                             env_vars: Optional[dict] = None) -> dict:
        pool = self._resource_pool(pg)
        if pool is None:
            return {"status": "infeasible", "error": "placement group bundle not here"}
        if pg is None and not resources.fits_in(self.total):
            # Permanently infeasible on this node → spillback advice
            # (ref: cluster_task_manager.cc infeasible queue + spillback reply).
            target = await self._ask_spillback(resources)
            if target is not None and target["node_id"] != self.node_id:
                return {"status": "spillback", "addr": target["addr"],
                        "node_id": target["node_id"]}
            # cluster-wide infeasible: queue for the heartbeat so the
            # autoscaler learns the shape even when the driver's
            # pick_node path (GCS-side recording) was never involved
            self._infeasible.append({"resources": dict(resources.quantities),
                                     "ts": time.time()})
            del self._infeasible[:-32]
            return {"status": "infeasible",
                    "error": f"no node can satisfy {resources.quantities}"}
        if resources.fits_in(pool):
            return await self._grant(resources, pg, job_id, retriable,
                                     env_vars)
        if grant_or_reject:
            return {"status": "rejected"}
        # Feasible but busy → try spillback to an idle peer, else queue here
        # (ref: hybrid policy prefers local until spread threshold).
        if pg is None:
            target = await self._ask_spillback(resources)
            if target is not None and target["node_id"] != self.node_id:
                return {"status": "spillback", "addr": target["addr"],
                        "node_id": target["node_id"]}
        fut = asyncio.get_running_loop().create_future()
        self.pending.append(_PendingLease(resources, pg, fut, job_id,
                                          retriable, env_vars))
        try:
            return await asyncio.wait_for(fut, self.cfg.worker_lease_timeout_s)
        except asyncio.TimeoutError:
            return {"status": "retry"}

    async def _ask_spillback(self, resources: ResourceSet) -> Optional[dict]:
        gcs = self.pool.get(self.gcs_addr)
        try:
            return await gcs.call("pick_node", resources=resources,
                                  strategy_kind="DEFAULT", timeout=5.0)
        except (ConnectionLost, RemoteError, OSError):
            return None

    async def _grant(self, resources: ResourceSet, pg: Optional[Tuple],
                     job_id: Optional[bytes] = None,
                     retriable: bool = True,
                     env_vars: Optional[dict] = None,
                     reserved: bool = False) -> dict:
        pool = self._resource_pool(pg)
        if not reserved:
            pool.subtract(resources)
        w = await self._pop_worker(env_vars)
        if w is None:
            pool.add(resources)
            return {"status": "retry", "error": "no worker available"}
        lease_id = os.urandom(16)
        w.state = "leased"
        w.lease_id = lease_id
        w.job_id = job_id
        w.lease_time = time.time()
        w.retriable = retriable
        self.leases[lease_id] = w
        self.lease_resources[lease_id] = (resources, pg)
        return {"status": "granted", "lease_id": lease_id,
                "worker_addr": w.addr, "worker_id": w.worker_id}

    async def rpc_return_lease(self, lease_id: bytes) -> dict:
        self._release_lease(lease_id)
        return {"ok": True}

    def _release_lease(self, lease_id: bytes):
        w = self.leases.pop(lease_id, None)
        entry = self.lease_resources.pop(lease_id, None)
        if entry is not None and not (
                w is not None and getattr(w, "resources_released", False)):
            # skip the add if the blocked-get path already returned them
            resources, pg = entry
            pool = self._resource_pool(pg)
            if pool is not None:
                pool.add(resources)
        if w is not None:
            w.resources_released = False
        if w is not None and w.state == "leased":
            w.state = "idle"
            w.lease_id = None
            w.last_idle = time.time()
            self._worker_idle.set()
        self._drain_pending()

    def _drain_pending(self):
        if not self.pending:
            return
        loop = asyncio.get_running_loop()
        still = deque()
        while self.pending:
            p = self.pending.popleft()
            pool = self._resource_pool(p.pg)
            if p.fut.done():
                continue
            if pool is not None and p.resources.fits_in(pool):
                # Reserve SYNCHRONOUSLY: the grant runs as a task, and
                # deferring the subtract would admit every pending lease
                # against the same un-decremented pool (one freed CPU
                # must grant one lease, not the whole queue).
                pool.subtract(p.resources)

                async def _do(p=p):
                    r = await self._grant(p.resources, p.pg, p.job_id,
                                          p.retriable, p.env_vars,
                                          reserved=True)
                    if not p.fut.done():
                        p.fut.set_result(r)
                    elif r.get("status") == "granted":
                        # requester gave up (timeout): hand the lease back
                        self._release_lease(r["lease_id"])
                loop.create_task(_do())
            else:
                still.append(p)
        self.pending = still

    # ----------------------------------------------------------------- actors

    def _laneable(self, spec: TaskSpec) -> bool:
        """Lane-host candidates: strictly fractional CPU, nothing else.
        num_cpus>=1 and custom/TPU-resource actors keep dedicated workers
        (process isolation + the lease protocol's accounting); PG actors
        keep the bundle-accounted lease path."""
        if self.cfg.actor_lanes_per_worker <= 0:
            return False
        if spec.scheduling.kind == "PLACEMENT_GROUP":
            return False
        q = spec.resources.quantities
        cpu = q.get("CPU", 0.0)
        return 0.0 < cpu < 1.0 and all(
            v == 0 for k, v in q.items() if k != "CPU")

    async def _create_actor_lane(self, spec: TaskSpec) -> dict:
        """Pack a fractional-CPU actor into a shared lane-host worker
        (one spawn amortizes over actor_lanes_per_worker actors — the
        density path the reference reaches with 0.001-CPU actors across
        its prestarted per-CPU worker fleet)."""
        from ray_tpu.runtime_env import process_env

        env_vars = process_env(spec.runtime_env)
        key = _env_key(env_vars)
        # serialize host acquisition per pool key: a burst of concurrent
        # creates must PACK into one spawning host, not each spawn its own
        lock = self._lane_locks.setdefault(key, asyncio.Lock())
        async with lock:
            if not spec.resources.fits_in(self.available):
                return {"ok": False, "retryable": True,
                        "error": "insufficient node resources for actor "
                                 "lane"}
            host = None
            for w in self.workers.values():
                if (w.state == "actor" and w.lane_host and w.env_key == key
                        and w.job_id == spec.job_id.binary()
                        and len(w.lanes) < self.cfg.actor_lanes_per_worker):
                    host = w
                    break
            if host is None:
                # fail fast at the worker cap instead of waiting inside
                # the lane lock (the GCS retries at 0.2 s; a long wait
                # here would head-of-line-block creates that could fill
                # lanes freed in the meantime). ANY idle worker counts:
                # _pop_worker evicts mismatched-env idles immediately.
                has_idle = any(w.state == "idle"
                               for w in self.workers.values())
                if not has_idle and self._countable_workers() >= \
                        self.cfg.max_workers_per_node:
                    return {"ok": False, "retryable": True,
                            "error": "lane capacity exhausted "
                                     "(max_workers_per_node x "
                                     "actor_lanes_per_worker); retry"}
                host = await self._pop_worker(env_vars)
                if host is None:
                    return {"ok": False, "retryable": True,
                            "error": "no worker available for lane host"}
                # the admission check above is stale after the await
                # (leases draw on the same pool concurrently): re-check
                # before reserving, or available goes negative
                if not spec.resources.fits_in(self.available):
                    self._worker_idle.set()   # host stays idle in pool
                    return {"ok": False, "retryable": True,
                            "error": "insufficient node resources for "
                                     "actor lane"}
                host.state = "actor"
                host.lane_host = True
                host.job_id = spec.job_id.binary()
            # reserve under the lock; the creation RPC itself runs outside
            # it so lane ctors still overlap
            self.available.subtract(spec.resources)
            host.lanes[spec.actor_id] = spec.resources.copy()
        client = self.pool.get(tuple(host.addr))
        try:
            res = await client.call("create_actor", spec=spec,
                                    timeout=self.cfg.worker_start_timeout_s)
        except ConnectionLost as e:
            # transport broke: the host process is gone/wedged — killing
            # it death-reports every lane for restart
            self._lane_rollback(host, spec.actor_id)
            self._kill_worker(host, f"lane creation rpc failed: {e}")
            return {"ok": False, "retryable": True, "error": str(e)}
        except (RemoteError, OSError) as e:
            # THIS lane's creation failed (ctor hang past the deadline,
            # or a handler error); sibling lanes are healthy — tombstone
            # the lane worker-side so a late-finishing ctor can't install
            # a zombie, and keep the host
            self._lane_rollback(host, spec.actor_id)
            try:
                await client.call("destroy_actor", actor_id=spec.actor_id,
                                  timeout=5.0)
            except Exception:
                pass
            self._lane_host_maybe_idle(host)
            return {"ok": False, "retryable": True, "error": str(e)}
        if not res.get("ok"):
            # ctor raised: the host process is healthy — only the lane dies
            self._lane_rollback(host, spec.actor_id)
            self._lane_host_maybe_idle(host)
            return {"ok": False, "retryable": False,
                    "error": res.get("error")}
        return {"ok": True, "worker_addr": host.addr,
                "worker_id": host.worker_id}

    def _lane_rollback(self, host: WorkerRecord, actor_id):
        """Return a reserved lane's resources exactly once: if the host
        died mid-create, _on_worker_dead already cleared w.lanes and
        refunded them — a defaulted pop would double-add and inflate
        self.available past the node total."""
        res = host.lanes.pop(actor_id, None)
        if res is not None:
            self.available.add(res)

    def _lane_host_maybe_idle(self, w: WorkerRecord):
        """An empty lane host returns to the idle pool (reusable by any
        lease, reclaimed by the idle reaper) instead of sitting in state
        'actor' forever holding a max_workers_per_node slot."""
        if w.lane_host and not w.lanes and w.state == "actor":
            w.state = "idle"
            w.lane_host = False
            w.actor_id = None
            w.job_id = None
            w.last_idle = time.time()
            self._worker_idle.set()

    async def rpc_create_actor(self, spec: TaskSpec,
                               idem: Optional[str] = None) -> dict:
        """Lease a dedicated worker and run the creation task on it
        (ref: gcs_actor_scheduler leases from raylet + pushes creation).
        Fractional-CPU actors take the lane path instead.

        ``idem`` is the GCS's token, stable across its retries of one
        (actor, incarnation): a retry after a dropped response replays
        the recorded placement instead of leasing a second worker and
        running ``__init__`` twice. Failures are not cached — the retry
        exists to attempt creation again."""
        return await self._idem_create.run(
            idem, lambda: self._create_actor(spec),
            cache_if=lambda r: r.get("ok"))

    async def _create_actor(self, spec: TaskSpec) -> dict:
        if self._laneable(spec):
            return await self._create_actor_lane(spec)
        pg = None
        if spec.scheduling.kind == "PLACEMENT_GROUP":
            pg = (spec.scheduling.pg_id, spec.scheduling.bundle_index)
        from ray_tpu.runtime_env import process_env

        r = await self.rpc_request_lease(
            resources=spec.resources, pg=pg, job_id=spec.job_id.binary(),
            retriable=False, env_vars=process_env(spec.runtime_env))
        if r["status"] != "granted":
            return {"ok": False, "retryable": r["status"] in ("retry", "spillback"),
                    "error": r.get("error", r["status"])}
        w = self.leases[r["lease_id"]]
        w.state = "actor"
        w.job_id = spec.job_id.binary()
        w.actor_id = spec.actor_id
        client = self.pool.get(tuple(w.addr))
        try:
            res = await client.call("create_actor", spec=spec,
                                    timeout=self.cfg.worker_start_timeout_s)
        except (ConnectionLost, RemoteError, OSError) as e:
            self._kill_worker(w, f"actor creation rpc failed: {e}")
            return {"ok": False, "retryable": True, "error": str(e)}
        if not res.get("ok"):
            self._kill_worker(w, "actor __init__ failed")
            return {"ok": False, "retryable": False, "error": res.get("error")}
        return {"ok": True, "worker_addr": w.addr, "worker_id": w.worker_id}

    # ------------------------------------------------------- placement groups

    async def rpc_pg_prepare(self, pg_id: PlacementGroupID, bundle_index: int,
                             resources: ResourceSet) -> dict:
        if not resources.fits_in(self.available):
            return {"ok": False}
        self.available.subtract(resources)
        self.pg_bundles.setdefault(pg_id, {})[bundle_index] = {
            "resources": resources.copy(), "available": resources.copy(),
            "committed": False}
        return {"ok": True}

    async def rpc_pg_commit(self, pg_id: PlacementGroupID, bundle_index: int) -> dict:
        b = self.pg_bundles.get(pg_id, {}).get(bundle_index)
        if b is None:
            return {"ok": False}
        b["committed"] = True
        self._drain_pending()
        return {"ok": True}

    async def rpc_pg_return(self, pg_id: PlacementGroupID, bundle_index: int) -> dict:
        b = self.pg_bundles.get(pg_id, {}).pop(bundle_index, None)
        if b is not None:
            self.available.add(b["resources"])
            self._drain_pending()
        return {"ok": True}

    # ----------------------------------------------------------- object plane
    #
    # Spilling (ref: local_object_manager.h:41 spill-under-pressure +
    # external_storage.py FileSystemStorage): a background pass copies sealed
    # LRU objects to disk *before* native eviction could drop them, then
    # frees the unpinned ones. Pinned primaries are only dropped after their
    # owner releases the pin (rpc_free_space reply → owner unpins → native
    # LRU eviction reclaims, with the disk copy as the durable tier).

    def _spill_usage(self) -> float:
        cap = self.store.capacity() or 1
        return self.store.bytes_in_use() / cap

    async def _spill_loop(self):
        period = 0.2
        while not self._stopping:
            try:
                if self._spill_usage() > self.cfg.object_spill_threshold:
                    low = int(self.cfg.object_spill_low_water
                              * self.store.capacity())
                    target = self.store.bytes_in_use() - low
                    await self._spill_pass(target)
            except Exception:
                logger.exception("spill pass failed")
            await asyncio.sleep(period)

    async def _spill_pass(self, target_bytes: int) -> dict:
        """Spill sealed LRU objects until ~target_bytes are freed.

        An object is freeable once its only pin is the nodelet's own
        primary pin (reader pins block freeing but not the disk copy).
        Freeing uses the atomic evict-if-unpinned native primitive so a
        reader pinning after our snapshot is never invalidated."""
        freed = 0
        for oid, size, _pins in self.store.list_objects():
            if freed >= target_bytes:
                break
            if not self.spill.contains(oid):
                view = self.store.get_view(oid)
                if view is None:
                    continue
                try:
                    data = bytes(view)
                finally:
                    del view
                    self.store.release(oid)
                await asyncio.to_thread(self.spill.spill, oid, data)
                self._spill_bytes_total += len(data)
            our_pin = 1 if oid in self.primary_pins else 0
            if self.store.evict_if_unpinned(oid, max_pins=our_pin):
                self.primary_pins.discard(oid)
                _memattr().release(oid)   # left shm; the spill tier holds it
                self._spilled_then_dropped += 1
                freed += size
        return {"freed": freed}

    async def rpc_free_space(self, need_bytes: int, **_compat) -> dict:
        """Make room for an incoming allocation (owner-side put retry path)."""
        if self.spill is None:
            return {"ok": False, "freed": 0, "error": "spilling disabled"}
        r = await self._spill_pass(need_bytes)
        r["ok"] = True
        return r

    async def rpc_pin_object(self, oid: ObjectID) -> dict:
        """Pin a primary copy on behalf of its owner (ref: raylet
        PinObjectIDs). Idempotent; the pin lives until delete or spill."""
        if oid in self.primary_pins:
            return {"ok": True}
        view = self.store.get_view(oid)
        if view is None:
            # Already only on disk (or gone); the spill tier is the pin.
            ok = self.spill is not None and self.spill.contains(oid)
            return {"ok": ok}
        size = len(view)
        del view  # keep the refcount from ts_get; release happens at unpin
        self.primary_pins.add(oid)
        mem = _memattr()
        mem.attribute(oid, "user", size, owner=self.node_id.hex()[:12])
        mem.pin(oid, "primary")
        return {"ok": True}

    async def rpc_pin_objects(self, oids: List[ObjectID]) -> dict:
        """Batched rpc_pin_object: one RPC pins a whole wave of primaries.
        The collective zero-copy transport puts pipeline_chunks sub-chunk
        objects per ring step, and a KV handoff (serve/kv_transfer.py)
        pins one object per page group; pinning them individually would
        pay one awaited store transaction plus two memattr lock rounds
        per object. One synchronous store sweep (the leaked ts_get
        refcount IS the pin, exactly as rpc_pin_object) and a single
        memattr batch instead."""
        pinned, ok = 0, True
        batch = []
        for oid in oids:
            if oid in self.primary_pins:
                pinned += 1
                continue
            view = self.store.get_view(oid)
            if view is None:
                # already only on disk (or gone); the spill tier is the pin
                if self.spill is not None and self.spill.contains(oid):
                    pinned += 1
                else:
                    ok = False
                continue
            size = len(view)
            del view  # keep the refcount from ts_get; release at unpin
            self.primary_pins.add(oid)
            batch.append((oid, size))
            pinned += 1
        if batch:
            _memattr().attribute_pin_many(
                batch, reason="primary", owner=self.node_id.hex()[:12])
        return {"ok": ok, "pinned": pinned}

    async def _restore_local(self, oid: ObjectID) -> bool:
        """Disk → shm (ref: restore_spilled_object). False if absent/full."""
        if self.spill is None or not self.spill.contains(oid):
            return False
        if self.store.contains(oid):
            return True
        data = await asyncio.to_thread(self.spill.restore, oid)
        if data is None:
            return False
        view = self.store.create_view(oid, len(data))
        if view is None:
            # Make room (other spilled-but-resident objects can go).
            await self._spill_pass(len(data))
            view = self.store.create_view(oid, len(data))
        if view is None:
            return self.store.contains(oid)
        try:
            view[:] = data
        except BaseException:
            del view
            self.store.abort(oid)
            raise
        del view
        self.store.seal(oid)
        self._restored += 1
        self._restore_bytes_total += len(data)
        return True

    async def rpc_has_object(self, oid: ObjectID) -> bool:
        return self.store.contains(oid) or (
            self.spill is not None and self.spill.contains(oid))

    async def rpc_read_chunk(self, oid: ObjectID, offset: int, size: int) -> Optional[dict]:
        """Serve one chunk of a local sealed object (ref: HandlePush chunks).
        Falls back to the spill tier, streaming straight off disk."""
        view = self.store.get_view(oid)
        if view is None:
            if self.spill is not None:
                r = await asyncio.to_thread(self.spill.read_range, oid,
                                            offset, size)
                if r is not None:
                    return {"total": r[0], "data": r[1]}
            return None
        try:
            total = len(view)
            data = bytes(view[offset:offset + size])
        finally:
            del view
            self.store.release(oid)
        return {"total": total, "data": data}

    async def rpc_xfer_addr(self) -> dict:
        """The native transfer plane's endpoint (xfer.cc), or port -1 if
        it did not start (pullers then use the chunk RPC path)."""
        return {"host": self.server.host, "port": self.xfer_port}

    async def _xfer_port_for(self, key: Tuple) -> int:
        """Cached peer xfer port. Failures are cached only briefly (a
        peer busy at startup must not disable the native plane forever)
        and successes expire too (a restarted peer binds a new port)."""
        cached = self._xfer_ports.get(key)
        now = time.time()
        if cached is not None and now < cached[1]:
            return cached[0]
        try:
            r = await self.pool.get(key).call("xfer_addr", timeout=10.0)
            port = int(r["port"])
            ttl = 300.0
        except (ConnectionLost, RemoteError, OSError, KeyError):
            port, ttl = -1, 15.0
        self._xfer_ports[key] = (port, now + ttl)
        return port

    async def _pull_native(self, oid: ObjectID, source: Address) -> str:
        """Try the zero-staging native plane first. Returns "ok" (sealed
        locally), "busy" (source at its serve cap — the puller should
        retry, ideally at another holder), or "fallback" (use chunk
        RPC)."""
        key = tuple(source)
        port = await self._xfer_port_for(key)
        if port <= 0:
            return "fallback"
        host = source[0]
        rc, total = await asyncio.to_thread(self.store.xfer_fetch, host,
                                            port, oid)
        if rc == 3 and self.spill is not None:
            # allocation failed: free exactly what the object needs (the
            # source already told us) plus slack, then retry
            await self._spill_pass(max(total,
                                       self.cfg.object_store_memory // 8))
            rc, total = await asyncio.to_thread(self.store.xfer_fetch, host,
                                                port, oid)
        if rc == 5:
            # A racing pull/producer owns the buffer: wait for its seal
            # instead of transferring a second copy. Bounded: a racer
            # SIGKILLed mid-write leaves the entry kCreating forever (no
            # progress signal is exposed), so after the io-timeout window
            # the native path gives up and the chunk-RPC fallback's own
            # create/contains logic takes over.
            deadline = time.time() + 150.0
            while time.time() < deadline:
                if self.store.contains(oid):
                    return "ok"
                st = self.store.state(oid)
                if st == 0:   # racer aborted; retry once natively
                    rc2, _ = await asyncio.to_thread(self.store.xfer_fetch,
                                                     host, port, oid)
                    if rc2 == 0:
                        self._native_pulls += 1
                        return "ok"
                    if rc2 == 6:
                        return "busy"
                    if rc2 != 5:
                        return "fallback"
                await asyncio.sleep(0.05)
            return "fallback"
        if rc == 6:
            return "busy"
        if rc == 2:
            # io error: peer may have restarted on a new port — requery
            self._xfer_ports.pop(key, None)
            return "fallback"
        if rc == 0:
            self._native_pulls += 1
            return "ok"
        return "fallback"

    def _object_nbytes(self, oid: ObjectID) -> int:
        """Size of a sealed local object (edge-telemetry stamping)."""
        view = self.store.get_view(oid)
        if view is None:
            return 0
        try:
            return view.nbytes
        finally:
            del view
            self.store.release(oid)

    async def rpc_pull_object(self, oid: ObjectID, source: Address) -> dict:
        """Pull a remote object into the local store: native zero-staging
        plane (xfer.cc) when the source runs one, chunked RPC otherwise
        (ref: PullManager pull_manager.h:52 + ObjectManager::Push).
        `nbytes` is present ONLY when bytes actually crossed the wire —
        already-local / restored hits omit it so pullers don't record
        phantom transfer edges."""
        if self.store.contains(oid):
            return {"ok": True}
        if await self._restore_local(oid):
            return {"ok": True}
        if tuple(source) == (self.server.host, self.server.port):
            return {"ok": False, "error": "object not at source"}
        native = await self._pull_native(oid, source)
        if native == "ok":
            return {"ok": True, "nbytes": self._object_nbytes(oid)}
        if native == "busy":
            # do NOT fall through to chunk RPC: that would route the
            # same bytes through the same saturated source, just slower.
            # The caller retries — against a peer once one registers.
            return {"ok": False, "busy": True, "error": "source busy"}
        src = self.pool.get(tuple(source))
        chunk = self.cfg.object_transfer_chunk_bytes
        try:
            first = await src.call("read_chunk", oid=oid, offset=0, size=chunk)
        except (ConnectionLost, RemoteError, OSError) as e:
            return {"ok": False, "error": f"source unreachable: {e}"}
        if first is None:
            return {"ok": False, "error": "object not at source"}
        total = first["total"]
        view = self.store.create_view(oid, total)
        if view is None and self.spill is not None:
            await self._spill_pass(total)
            view = self.store.create_view(oid, total)
        if view is None:
            if self.store.contains(oid):
                return {"ok": True}
            return {"ok": False, "error": "local store full"}
        try:
            data = first["data"]
            view[0:len(data)] = data
            off = len(data)
            while off < total:
                r = await src.call("read_chunk", oid=oid, offset=off, size=chunk)
                if r is None:
                    raise ConnectionLost("object vanished at source mid-pull")
                view[off:off + len(r["data"])] = r["data"]
                off += len(r["data"])
        except Exception as e:
            del view
            self.store.abort(oid)
            return {"ok": False, "error": str(e)}
        del view
        self.store.seal(oid)
        return {"ok": True, "nbytes": total}

    async def rpc_delete_objects(self, oids: List[ObjectID]) -> dict:
        for oid in oids:
            if oid in self.primary_pins:
                self.store.release(oid)
                self.primary_pins.discard(oid)
            self.store.delete(oid)
            _memattr().release(oid)
            if self.spill is not None:
                self.spill.delete(oid)
        return {"ok": True}

    # ------------------------------------------------------------------- misc

    async def rpc_job_finished(self, job_id: bytes) -> dict:
        for w in list(self.workers.values()):
            if w.job_id == job_id:
                self._kill_worker(w, "job finished")
        return {"ok": True}

    async def rpc_node_stats(self) -> dict:
        return {
            "node_id": self.node_id,
            "workers": {w.worker_id.hex()[:8]: w.state for w in self.workers.values()},
            "available": self.available.quantities,
            "total": self.total.quantities,
            "store_bytes": self.store.bytes_in_use(),
            "store_capacity": self.store.capacity(),
            "store_objects": self.store.num_objects(),
            "store_evictions": self.store.num_evictions(),
            # spilling-readiness: occupancy + pinned (unspillable) share
            # + pin-count distribution (object_store.pin_summary)
            **{f"store_{k}": v for k, v in self.store.pin_summary().items()},
            "spilled_objects": (self.spill.num_spilled()
                                if self.spill is not None else 0),
            "spilled_bytes": (self.spill.bytes_spilled()
                              if self.spill is not None else 0),
            "restored_objects": self._restored,
            # spill-tier lifecycle: objects dropped from shm after their
            # disk copy became the pin, plus cumulative disk traffic
            "spilled_then_dropped": self._spilled_then_dropped,
            "spill_bytes_total": self._spill_bytes_total,
            "restore_bytes_total": self._restore_bytes_total,
            "native_pulls": self._native_pulls,
            "serve_busy_rejections": (self.store.xfer_busy_rejections()
                                      if self.xfer_port > 0 else 0),
            "xfer_port": self.xfer_port,
            "pending_leases": len(self.pending),
            "oom_kills": self.memory_monitor.kills,
            # Memory-attribution snapshot rides the node_stats KV push
            # (the nodelet has no TelemetryAgent); the GCS folds it at
            # memory_report() read time.
            "memory": self._memory_snapshot(),
        }

    def _memory_snapshot(self):
        try:
            from ray_tpu.observability import memory as _mem
            return _mem.snapshot_for_report(self.store)
        except Exception:
            return None

    async def rpc_ping(self) -> dict:
        return {"ok": True}

    async def rpc_shutdown(self) -> dict:
        self._stopping = True
        for w in list(self.workers.values()):
            self._kill_worker(w, "nodelet shutdown")
        if self.store is not None:
            self.store.xfer_serve_stop()
            # keep the segment mapped until os._exit: a live xfer thread
            # mid-transfer must fault on a closed socket, not on munmap
            self.store.close(destroy=True, unmap=False)
        asyncio.get_running_loop().call_later(0.05, lambda: os._exit(0))
        return {"ok": True}


def main():
    import argparse
    import json

    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--gcs", required=True)
    parser.add_argument("--session-dir", required=True)
    parser.add_argument("--resources", default="{}")
    parser.add_argument("--labels", default="{}")
    parser.add_argument("--config", default="{}")
    parser.add_argument("--ready-fd", type=int, default=-1)
    args = parser.parse_args()

    logging.basicConfig(level=logging.INFO,
                        format="[nodelet] %(asctime)s %(levelname)s %(message)s")
    cfg = Config.from_json(args.config)
    gh, gp = args.gcs.rsplit(":", 1)

    async def run():
        nodelet = Nodelet(cfg, (gh, int(gp)), args.session_dir,
                          resources=json.loads(args.resources),
                          labels=json.loads(args.labels))
        host, port = await nodelet.start(args.host, args.port)
        if args.ready_fd >= 0:
            os.write(args.ready_fd,
                     f"{host}:{port}:{nodelet.node_id.hex()}:{nodelet.store_name}\n".encode())
            os.close(args.ready_fd)
        logger.info("nodelet %s on %s:%d", nodelet.node_id.hex()[:8], host, port)
        while True:
            await asyncio.sleep(3600)

    asyncio.run(run())


if __name__ == "__main__":
    main()
