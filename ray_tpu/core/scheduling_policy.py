"""Standalone scheduling-policy suite.

Reference: src/ray/raylet/scheduling/policy/ — the pluggable node-picking
policies behind ClusterResourceScheduler::GetBestSchedulableNode
(cluster_resource_scheduler.cc:129):

- HybridPolicy     (hybrid_scheduling_policy.{h,cc}: two-tier
                    available/feasible ranking, critical-resource
                    utilization score truncated below the spread
                    threshold, preferred-node priority, uniform pick
                    among the top-k best)
- SpreadPolicy     (scheduling_policy spread: round-robin)
- RandomPolicy     (random_scheduling_policy)
- NodeAffinityPolicy (node_affinity_scheduling_policy: hard/soft)
- pack_bundles     (bundle_scheduling_policy.cc: placement-group bundle
                    packing for PACK / SPREAD / STRICT_PACK /
                    STRICT_SPREAD)

Pure functions over a snapshot of node states — no GCS/nodelet coupling,
so the suite is unit-testable exactly like the reference's
scheduling_policy_test.cc / hybrid_scheduling_policy_test.cc. The GCS
spillback RPC (`gcs.py rpc_pick_node`) and placement-group scheduler
drive these.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ray_tpu.core.common import ResourceSet


def _id_key(node_id) -> str:
    """Stable sort key for node ids (plain strings in tests, NodeID
    objects — which define no ordering — in the live GCS)."""
    h = getattr(node_id, "hex", None)
    return h() if callable(h) else str(node_id)


@dataclass
class SchedNode:
    """One node's view for a scheduling decision."""
    node_id: str
    total: ResourceSet
    available: ResourceSet
    alive: bool = True

    def feasible_for(self, request: ResourceSet) -> bool:
        """Could EVER run the request (capacity check; ref:
        IsNodeFeasible — total, not currently-available)."""
        return self.alive and request.fits_in(self.total)

    def available_for(self, request: ResourceSet) -> bool:
        return self.alive and request.fits_in(self.available)


def critical_utilization(node: SchedNode) -> float:
    """Max over resources of used/total (ref: NodeResources::
    CalculateCriticalResourceUtilization — memory/object-store style
    resources count too; zero-capacity resources are skipped)."""
    worst = 0.0
    for k, total in node.total.quantities.items():
        if total <= 0:
            continue
        avail = node.available.quantities.get(k, 0.0)
        worst = max(worst, 1.0 - avail / total)
    return worst


def hybrid_score(node: SchedNode, spread_threshold: float) -> float:
    """Utilization truncated to 0 below the threshold — nodes under the
    threshold tie at 0 so the deterministic id order packs onto them,
    past it the least-utilized wins (ref: ComputeNodeScoreImpl)."""
    u = critical_utilization(node)
    return 0.0 if u < spread_threshold else u


class HybridPolicy:
    """ref: hybrid_scheduling_policy.cc ScheduleImpl. Two-tier ranking
    (available nodes always beat merely-feasible ones), score ties
    broken by node id for determinism, preferred node short-circuits
    when it holds the best score, then a uniform pick among the top-k."""

    def __init__(self, spread_threshold: float = 0.5,
                 top_k_absolute: int = 1, top_k_fraction: float = 0.2,
                 seed: Optional[int] = None):
        self.spread_threshold = spread_threshold
        self.top_k_absolute = top_k_absolute
        self.top_k_fraction = top_k_fraction
        self._rng = random.Random(seed)

    def schedule(self, request: ResourceSet, nodes: Sequence[SchedNode],
                 preferred_node_id: Optional[str] = None,
                 require_node_available: bool = True,
                 force_spillback: bool = False) -> Optional[str]:
        available: List[Tuple[str, float]] = []
        feasible: List[Tuple[str, float]] = []
        preferred_available = preferred_feasible = False
        preferred_score = float("inf")
        for node in nodes:
            if force_spillback and node.node_id == preferred_node_id:
                continue
            if not node.feasible_for(request):
                continue
            score = hybrid_score(node, self.spread_threshold)
            is_avail = node.available_for(request)
            if node.node_id == preferred_node_id:
                preferred_feasible = True
                preferred_available = is_avail
                preferred_score = score
            (available if is_avail else feasible).append(
                (node.node_id, score))
        k = max(self.top_k_absolute,
                int(len(nodes) * self.top_k_fraction))
        if available:
            prefer = (not force_spillback) and preferred_available
            return self._best(available, k,
                              preferred_node_id if prefer else None,
                              preferred_score)
        if feasible and not require_node_available:
            prefer = (not force_spillback) and preferred_feasible
            return self._best(feasible, k,
                              preferred_node_id if prefer else None,
                              preferred_score)
        return None

    def _best(self, scored: List[Tuple[str, float]], k: int,
              preferred_node_id: Optional[str],
              preferred_score: float) -> str:
        # id sort first so equal scores resolve identically every time
        scored.sort(key=lambda p: _id_key(p[0]))
        scored.sort(key=lambda p: p[1])          # stable on score
        if preferred_node_id is not None and \
                preferred_score <= scored[0][1]:
            return preferred_node_id
        return scored[self._rng.randrange(min(k, len(scored)))][0]


class SpreadPolicy:
    """Round-robin over feasible+available nodes in id order (ref:
    scheduling_policy.cc Spread — rotates a starting offset)."""

    def __init__(self):
        self._next = 0

    def schedule(self, request: ResourceSet,
                 nodes: Sequence[SchedNode]) -> Optional[str]:
        cands = sorted((n.node_id for n in nodes
                        if n.available_for(request)), key=_id_key)
        if not cands:
            return None
        choice = cands[self._next % len(cands)]
        self._next += 1
        return choice


class RandomPolicy:
    """Uniform over available nodes (ref: random_scheduling_policy.cc)."""

    def __init__(self, seed: Optional[int] = None):
        self._rng = random.Random(seed)

    def schedule(self, request: ResourceSet,
                 nodes: Sequence[SchedNode]) -> Optional[str]:
        cands = [n.node_id for n in nodes if n.available_for(request)]
        return self._rng.choice(cands) if cands else None


class NodeAffinityPolicy:
    """Pin to one node; `soft` falls back to hybrid when it's gone
    (ref: node_affinity_scheduling_policy.cc)."""

    def __init__(self, node_id: str, soft: bool = False,
                 fallback: Optional[HybridPolicy] = None):
        self.node_id = node_id
        self.soft = soft
        self.fallback = fallback or HybridPolicy()

    def schedule(self, request: ResourceSet,
                 nodes: Sequence[SchedNode]) -> Optional[str]:
        for node in nodes:
            if node.node_id == self.node_id and \
                    node.available_for(request):
                return node.node_id
        if self.soft:
            return self.fallback.schedule(request, nodes)
        return None


# --- placement-group bundle packing ------------------------------------------


def pack_bundles(bundles: Sequence[ResourceSet],
                 nodes: Sequence[SchedNode], strategy: str,
                 exclude_nodes: Optional[set] = None
                 ) -> Optional[List[str]]:
    """Assign every bundle to a node per the PG strategy, or None if the
    gang can't be placed (all-or-nothing, like the reference's 2PC
    prepare phase; ref: bundle_scheduling_policy.cc
    BundlePackSchedulingPolicy / BundleSpreadSchedulingPolicy /
    BundleStrictPackSchedulingPolicy / BundleStrictSpreadSchedulingPolicy).

    Returns a node_id per bundle. Capacity is tracked against a scratch
    copy of each node's availability so multi-bundle-per-node packing is
    honest."""
    scratch: Dict[str, ResourceSet] = {}
    by_id: Dict[str, SchedNode] = {}
    for n in sorted(nodes, key=lambda n: _id_key(n.node_id)):
        if not n.alive or (exclude_nodes and n.node_id in exclude_nodes):
            continue
        scratch[n.node_id] = n.available.copy()
        by_id[n.node_id] = n

    def fits(nid: str, req: ResourceSet) -> bool:
        return req.fits_in(scratch[nid])

    def take(nid: str, req: ResourceSet):
        scratch[nid].subtract(req)

    if strategy == "STRICT_PACK":
        # every bundle on ONE node
        for nid in scratch:
            s = scratch[nid].copy()
            ok = True
            for b in bundles:
                if b.fits_in(s):
                    s.subtract(b)
                else:
                    ok = False
                    break
            if ok:
                return [nid] * len(bundles)
        return None

    # sort bundles largest-first for better first-fit packing (ref:
    # bundle_scheduling_policy.cc sorts by resource size descending)
    order = sorted(range(len(bundles)),
                   key=lambda i: -sum(bundles[i].quantities.values()))
    placement: List[Optional[str]] = [None] * len(bundles)

    if strategy in ("STRICT_SPREAD", "SPREAD"):
        used: set = set()
        for i in order:
            b = bundles[i]
            fresh = [nid for nid in scratch
                     if nid not in used and fits(nid, b)]
            reuse = [nid for nid in scratch
                     if nid in used and fits(nid, b)]
            if fresh:
                nid = min(fresh,
                          key=lambda x: critical_utilization(by_id[x]))
            elif reuse and strategy == "SPREAD":
                nid = min(reuse,
                          key=lambda x: critical_utilization(by_id[x]))
            else:
                return None          # STRICT_SPREAD: distinct or fail
            placement[i] = nid
            used.add(nid)
            take(nid, b)
        return placement  # type: ignore[return-value]

    # PACK: minimize node count — first-fit onto already-used nodes
    used_order: List[str] = []
    for i in order:
        b = bundles[i]
        nid = next((u for u in used_order if fits(u, b)), None)
        if nid is None:
            fresh = [n for n in scratch if fits(n, b)]
            if not fresh:
                return None
            # least-utilized fresh node hosts the next clique
            nid = min(fresh, key=lambda x: critical_utilization(by_id[x]))
            used_order.append(nid)
        placement[i] = nid
        take(nid, b)
    return placement  # type: ignore[return-value]
