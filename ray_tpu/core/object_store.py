"""Node object plane: native shm store binding + in-process memory store.

Two tiers, mirroring the reference's two store providers
(src/ray/core_worker/store_provider/):

- `SharedMemoryStore` — ctypes binding over the native C++ segment
  (ray_tpu/native/objstore.cc; plasma-equivalent). All processes on a node
  attach to one segment named after the session; puts/gets are zero-copy
  in shared memory.
- `MemoryStore` — per-process dict of small/direct-return objects with
  asyncio-friendly waiters (ref: CoreWorkerMemoryStore,
  store_provider/memory_store/).

The HBM tier (device-resident jax.Array values) is deliberately per-process:
XLA owns device allocations, so cross-process object exchange always goes
through host bytes; `ray_tpu.util.device.device_put_ref` offers the
device-placement fast path on the consuming side.
"""

from __future__ import annotations

import ctypes
import mmap
import os
import threading
from typing import Any, Dict, List, Optional

from ray_tpu.core import serialization
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.status import ObjectStoreFullError

_memory_mod = None


def _memattr():
    """Cached import of the attribution tracker: observability.memory is
    stdlib-only, but its package __init__ pulls util.metrics -> runtime,
    which must not load while THIS module is mid-import (cycle)."""
    global _memory_mod
    if _memory_mod is None:
        from ray_tpu.observability import memory
        _memory_mod = memory.tracker()
    return _memory_mod


class _Lib:
    _lib = None
    _lock = threading.Lock()

    @classmethod
    def get(cls):
        with cls._lock:
            if cls._lib is None:
                from ray_tpu.native import ensure_built

                lib = ctypes.CDLL(ensure_built())
                lib.ts_create.restype = ctypes.c_void_p
                lib.ts_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint32]
                lib.ts_attach.restype = ctypes.c_void_p
                lib.ts_attach.argtypes = [ctypes.c_char_p]
                lib.ts_detach.argtypes = [ctypes.c_void_p]
                lib.ts_destroy.argtypes = [ctypes.c_char_p]
                lib.ts_total_size.restype = ctypes.c_uint64
                lib.ts_total_size.argtypes = [ctypes.c_void_p]
                lib.ts_create_buf.restype = ctypes.c_uint64
                lib.ts_create_buf.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
                lib.ts_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
                lib.ts_abort.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
                lib.ts_put.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_uint64]
                lib.ts_get.restype = ctypes.c_uint64
                lib.ts_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64)]
                lib.ts_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
                lib.ts_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
                lib.ts_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
                lib.ts_bytes_in_use.restype = ctypes.c_uint64
                lib.ts_bytes_in_use.argtypes = [ctypes.c_void_p]
                lib.ts_capacity.restype = ctypes.c_uint64
                lib.ts_capacity.argtypes = [ctypes.c_void_p]
                lib.ts_num_objects.restype = ctypes.c_uint32
                lib.ts_num_objects.argtypes = [ctypes.c_void_p]
                lib.ts_num_evictions.restype = ctypes.c_uint64
                lib.ts_num_evictions.argtypes = [ctypes.c_void_p]
                lib.ts_list.restype = ctypes.c_uint32
                lib.ts_list.argtypes = [
                    ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8),
                    ctypes.POINTER(ctypes.c_uint64),
                    ctypes.POINTER(ctypes.c_int64), ctypes.c_uint32]
                lib.ts_evict.restype = ctypes.c_int
                lib.ts_evict.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                         ctypes.c_int64]
                lib.ts_state.restype = ctypes.c_int
                lib.ts_state.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
                lib.ts_reap_creating.restype = ctypes.c_int
                lib.ts_reap_creating.argtypes = [ctypes.c_void_p,
                                                 ctypes.c_uint64]
                lib.ts_xfer_serve_start.restype = ctypes.c_int
                lib.ts_xfer_serve_start.argtypes = [
                    ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
                lib.ts_xfer_serve_stop.restype = ctypes.c_int
                lib.ts_xfer_serve_stop.argtypes = []
                lib.ts_xfer_fetch.restype = ctypes.c_int
                lib.ts_xfer_fetch.argtypes = [
                    ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
                    ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64)]
                lib.ts_xfer_set_serve_cap.restype = None
                lib.ts_xfer_set_serve_cap.argtypes = [ctypes.c_int]
                lib.ts_xfer_busy_rejections.restype = ctypes.c_uint64
                lib.ts_xfer_busy_rejections.argtypes = []
                cls._lib = lib
            return cls._lib


class SharedMemoryStore:
    """One per process; attaches to the node's shm segment."""

    def __init__(self, name: str, capacity: int = 0, max_objects: int = 1 << 15,
                 create: bool = False):
        self._lib = _Lib.get()
        self.name = name
        cname = name.encode()
        if create:
            self._h = self._lib.ts_create(cname, capacity, max_objects)
        else:
            self._h = self._lib.ts_attach(cname)
            if not self._h:
                # transient insurance: creator publishes the magic last,
                # so an attach racing the tail of creation can miss it
                import time as _time

                for _ in range(20):
                    _time.sleep(0.05)
                    self._h = self._lib.ts_attach(cname)
                    if self._h:
                        break
        if not self._h:
            raise RuntimeError(f"object store {'create' if create else 'attach'} failed: {name}")
        total = self._lib.ts_total_size(self._h)
        # Map the same segment in Python for zero-copy views.
        fd = os.open(f"/dev/shm{name}", os.O_RDWR)
        try:
            self._mm = mmap.mmap(fd, total)
        finally:
            os.close(fd)
        self._view = memoryview(self._mm)
        self._created = create

    @property
    def closed(self) -> bool:
        return not self._h

    # -- raw byte API --------------------------------------------------------

    def put_bytes(self, oid: ObjectID, data: bytes) -> bool:
        if not self._h:
            raise RuntimeError("object store closed")
        rc = self._lib.ts_put(self._h, oid.binary(), data, len(data))
        if rc == -2:
            raise ObjectStoreFullError(
                f"object of {len(data)} bytes does not fit (store {self.name})")
        return rc == 0  # False => already present (idempotent put)

    def create_view(self, oid: ObjectID, size: int) -> Optional[memoryview]:
        if not self._h:
            return None
        off = self._lib.ts_create_buf(self._h, oid.binary(), size)
        if off == 0:
            return None
        return self._view[off:off + size]

    def seal(self, oid: ObjectID) -> None:
        if not self._h:
            return
        self._lib.ts_seal(self._h, oid.binary())

    def abort(self, oid: ObjectID) -> None:
        if not self._h:
            return
        self._lib.ts_abort(self._h, oid.binary())

    def get_view(self, oid: ObjectID) -> Optional[memoryview]:
        """Pins the object; caller must release(oid) when the view is dropped."""
        if not self._h:
            return None
        size = ctypes.c_uint64()
        off = self._lib.ts_get(self._h, oid.binary(), ctypes.byref(size))
        if off == 0:
            return None
        _memattr().touch(oid)   # temperature: every pin is an access
        return self._view[off:off + size.value]

    def release(self, oid: ObjectID) -> None:
        if not self._h:
            return
        self._lib.ts_release(self._h, oid.binary())

    def contains(self, oid: ObjectID) -> bool:
        if not self._h:
            return False
        return bool(self._lib.ts_contains(self._h, oid.binary()))

    def state(self, oid: ObjectID) -> int:
        """0 = absent, 1 = creating (mid-write), 2 = sealed."""
        if not self._h:
            return 0
        return int(self._lib.ts_state(self._h, oid.binary()))

    def reap_creating(self, max_age_s: float) -> int:
        """Free kCreating entries orphaned by a dead producer; returns
        the count freed."""
        if not self._h:
            return 0
        return int(self._lib.ts_reap_creating(self._h, int(max_age_s)))

    def delete(self, oid: ObjectID) -> None:
        if not self._h:
            return
        self._lib.ts_delete(self._h, oid.binary())

    # -- object API ----------------------------------------------------------

    def put(self, oid: ObjectID, value: Any) -> bool:
        """Serialize straight into the store (single copy for oob buffers)."""
        meta, bufs = serialization.serialize(value)
        size = serialization.serialized_size(meta, bufs)
        view = self.create_view(oid, size)
        if view is None:
            if self.contains(oid):
                return False
            raise ObjectStoreFullError(
                f"object of {size} bytes does not fit (store {self.name})")
        try:
            serialization.write_to(view, meta, bufs)
        except BaseException:
            self.abort(oid)
            raise
        finally:
            del view
        self.seal(oid)
        return True

    def get(self, oid: ObjectID, *, copy: bool = True) -> Any:
        """Deserialize. copy=False returns buffers aliasing shm (caller keeps
        the pin until it drops the value — we release immediately after
        materializing when copy=True)."""
        view = self.get_view(oid)
        if view is None:
            raise KeyError(oid)
        try:
            if copy:
                data = bytes(view)
                return serialization.unpack(data)
            return serialization.read_from(view)
        finally:
            if copy:
                del view
                self.release(oid)

    def evict_if_unpinned(self, oid: ObjectID, max_pins: int = 0) -> bool:
        """Atomically free a sealed object iff refcount <= max_pins (the
        caller's own pins). The safe spill-eviction primitive: decision and
        free happen under one native lock."""
        if not self._h:
            return False
        return self._lib.ts_evict(self._h, oid.binary(), max_pins) == 1

    def list_objects(self, max_entries: int = 4096
                     ) -> List[tuple]:
        """Sealed objects LRU-first as (ObjectID, size, pin_count) — the
        spill-candidate order (ref: eviction_policy.h LRU cache)."""
        if not self._h:
            return []
        ids = (ctypes.c_uint8 * (20 * max_entries))()
        sizes = (ctypes.c_uint64 * max_entries)()
        pins = (ctypes.c_int64 * max_entries)()
        n = self._lib.ts_list(
            self._h, ids, sizes, pins, max_entries)
        raw = bytes(ids)
        return [(ObjectID(raw[i * 20:(i + 1) * 20]), int(sizes[i]),
                 int(pins[i])) for i in range(n)]

    def pin_summary(self, max_entries: int = 4096) -> dict:
        """Spilling-readiness view: how much of the store is pinned (and
        so unspillable) and how contended the pins are. Buckets are pin
        counts; "unpinned" objects are the spill/evict headroom.
        (ref: local_object_manager.h — spilling skips pinned primaries)."""
        objs = self.list_objects(max_entries)
        pinned_bytes = 0
        pinned_objects = 0
        dist: Dict[str, int] = {}
        for _oid, size, pins in objs:
            key = str(pins) if pins < 3 else "3+"
            dist[key] = dist.get(key, 0) + 1
            if pins > 0:
                pinned_bytes += size
                pinned_objects += 1
        cap = self.capacity()
        return {
            "occupancy": (self.bytes_in_use() / cap) if cap else 0.0,
            "pinned_bytes": pinned_bytes,
            "pinned_objects": pinned_objects,
            "pin_count_distribution": dist,
        }

    # -- stats ---------------------------------------------------------------

    # ---- native transfer plane (xfer.cc) -----------------------------------

    def xfer_serve_start(self, host: str = "127.0.0.1") -> int:
        """Start the zero-staging TCP transfer server; returns the bound
        port (-1 if it could not start — callers fall back to the chunk
        RPC path)."""
        return int(self._lib.ts_xfer_serve_start(self._h, host.encode(), 0))

    def xfer_serve_stop(self) -> int:
        """Stop the transfer server, draining in-flight sender threads.
        Returns the count of threads still live after the drain window
        (0 = fully drained). Nonzero poisons close(): the segment must
        not be munmapped under a wedged sender thread."""
        leftover = int(self._lib.ts_xfer_serve_stop())
        if leftover:
            self._xfer_undrained = True
        return leftover

    def xfer_set_serve_cap(self, cap: int) -> None:
        """Cap concurrent outbound serves PER OBJECT from this process's
        transfer server (0 = unlimited; distinct objects multiplex
        freely). Over-cap pullers get a busy reply and retry — against a
        peer holder once one registers (the broadcast distribution tree,
        ref: pull_manager.h:52 holder fan-out)."""
        self._lib.ts_xfer_set_serve_cap(int(cap))

    def xfer_busy_rejections(self) -> int:
        """Count of pulls this server answered 'busy' (serve-cap hits)."""
        return int(self._lib.ts_xfer_busy_rejections())

    def xfer_fetch(self, host: str, port: int,
                   oid: ObjectID) -> "tuple[int, int]":
        """Blocking fetch of one remote object straight into this store.
        Returns (rc, total_bytes): rc 0=ok 1=absent-at-source 2=io-error
        3=alloc-failed 4=protocol 5=already-local/arriving 6=source-busy
        (at its serve cap — retry, ideally at another holder). total is
        the source-reported size (0 when unknown) — on rc=3 it tells the
        caller exactly how much space to free."""
        total = ctypes.c_uint64(0)
        rc = int(self._lib.ts_xfer_fetch(
            self._h, host.encode(), port, oid.binary(),
            ctypes.byref(total)))
        return rc, int(total.value)

    def bytes_in_use(self) -> int:
        return self._lib.ts_bytes_in_use(self._h)

    def capacity(self) -> int:
        return self._lib.ts_capacity(self._h)

    def num_objects(self) -> int:
        return self._lib.ts_num_objects(self._h)

    def num_evictions(self) -> int:
        return self._lib.ts_num_evictions(self._h)

    def close(self, destroy: bool = False, unmap: bool = True) -> None:
        """unmap=False unlinks the shm name without munmapping — the path
        for process exit while native transfer threads may still touch
        the segment (the mapping dies with the process; munmapping under
        a live xfer.cc thread would SIGSEGV it mid-transfer)."""
        if getattr(self, "_xfer_undrained", False):
            unmap = False  # a wedged xfer thread may still touch the map
        if self._h and unmap:
            try:
                self._view.release()
                self._mm.close()
            except BufferError:
                pass  # outstanding zero-copy views; leak the map, not the shm
            self._lib.ts_detach(self._h)
            self._h = None
        if destroy:
            _Lib.get().ts_destroy(self.name.encode())


class MemoryStore:
    """In-process store for small/direct-return objects.

    Thread-safe; get() blocks on a per-object event until the value arrives
    (the reference's GetAsync callback chain, memory_store.cc).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._objects: Dict[ObjectID, Any] = {}
        self._events: Dict[ObjectID, threading.Event] = {}

    def put(self, oid: ObjectID, value: Any) -> None:
        with self._lock:
            self._objects[oid] = value
            ev = self._events.pop(oid, None)
        if ev:
            ev.set()

    def contains(self, oid: ObjectID) -> bool:
        with self._lock:
            return oid in self._objects

    def get_if_exists(self, oid: ObjectID):
        with self._lock:
            return self._objects.get(oid, _MISSING)

    def wait_for(self, oid: ObjectID, timeout: Optional[float]) -> bool:
        with self._lock:
            if oid in self._objects:
                return True
            ev = self._events.get(oid)
            if ev is None:
                ev = self._events[oid] = threading.Event()
        return ev.wait(timeout)

    def delete(self, oid: ObjectID) -> None:
        with self._lock:
            self._objects.pop(oid, None)

    def keys(self) -> List[ObjectID]:
        with self._lock:
            return list(self._objects.keys())


_MISSING = object()
