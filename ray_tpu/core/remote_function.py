"""@ray_tpu.remote for functions.

Reference: python/ray/remote_function.py:245 (RemoteFunction._remote → core
worker submit at :391) and option resolution in _private/ray_option_utils.py.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional

from ray_tpu.core.common import ResourceSet, SchedulingStrategy
from ray_tpu.core import runtime as rt


_TASK_OPTIONS = {
    "num_cpus", "num_tpus", "memory", "resources", "num_returns",
    "max_retries", "retry_exceptions", "scheduling_strategy", "name",
    "runtime_env", "generator_backpressure", "generator_backpressure_bytes",
}


class RemoteFunction:
    def __init__(self, fn: Callable, options: Optional[Dict[str, Any]] = None):
        self._fn = fn
        self._options = dict(options or {})
        # options are immutable per RemoteFunction: build the ResourceSet
        # once, not per .remote() call (deep queues submit millions; the
        # spec pickles a copy on the wire, nothing mutates it owner-side)
        self._resources = ResourceSet.from_options(
            self._options.get("num_cpus"), self._options.get("num_tpus"),
            self._options.get("memory"), self._options.get("resources"))
        functools.update_wrapper(self, fn)

    def options(self, **opts) -> "RemoteFunction":
        bad = set(opts) - _TASK_OPTIONS
        if bad:
            raise ValueError(f"invalid task options: {sorted(bad)}")
        merged = dict(self._options)
        merged.update(opts)
        return RemoteFunction(self._fn, merged)

    def remote(self, *args, **kwargs):
        from ray_tpu.core.common import STREAMING

        o = self._options
        runtime = rt.get_runtime()
        nr = o.get("num_returns", 1)
        if nr in ("streaming", "dynamic"):
            nr = STREAMING   # generator task (ref: num_returns="dynamic")
        refs = runtime.submit_task(
            self._fn, args, kwargs,
            name=o.get("name") or getattr(self._fn, "__name__", "task"),
            num_returns=nr,
            resources=self._resources,
            max_retries=o.get("max_retries"),
            retry_exceptions=o.get("retry_exceptions", False),
            scheduling=o.get("scheduling_strategy"),
            runtime_env=o.get("runtime_env"),
            generator_backpressure=o.get("generator_backpressure"),
            generator_backpressure_bytes=o.get(
                "generator_backpressure_bytes"))
        if nr == STREAMING:
            return refs   # an ObjectRefGenerator
        if nr == 1:
            return refs[0]
        return refs

    def bind(self, *args, **kwargs):
        """Build a lazy DAG node (ref: ray.dag — DAGNode via .bind())."""
        from ray_tpu.dag import FunctionNode

        return FunctionNode(self, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function '{getattr(self._fn, '__name__', '?')}' cannot be "
            "called directly; use .remote().")
