"""Worker process: executes tasks and hosts actors.

Reference: the execution half of src/ray/core_worker/ — scheduling queues
(transport/normal_scheduling_queue.h, actor_scheduling_queue.h), concurrency
groups/fibers for async actors (fiber.h), and the Python task execution
handler in _raylet.pyx. One process == one Worker; the asyncio loop runs in
the main thread (RPC serving + async actor methods), synchronous task/actor
code runs on executor threads (1 thread => FIFO ordered actor semantics;
max_concurrency > 1 => threaded actor).
"""

from __future__ import annotations

import asyncio
import inspect
import logging
import os
import sys
import threading
import traceback
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, List, Optional, Tuple

from ray_tpu.core import serialization
from ray_tpu.core.channels import ChannelHost
from ray_tpu.core.common import ObjectRef, RuntimeAddress, TaskResult, TaskSpec
from ray_tpu.core.config import Config
from ray_tpu.core.ids import JobID, NodeID, ObjectID, TaskID
from ray_tpu.core.runtime import Runtime, set_runtime
from ray_tpu.core.serialization import SerializedException

logger = logging.getLogger("ray_tpu.worker")


class _SerialLaneExecutor:
    """FIFO serial execution multiplexed onto a SHARED thread pool:
    per-lane actor ordering without a dedicated OS thread per lane (256
    lanes/process would otherwise pin 256 permanently idle threads once
    each actor has run a method). At most one submission per lane runs
    at a time; drains chain through the shared pool."""

    def __init__(self, pool: ThreadPoolExecutor):
        self._pool = pool
        self._q: deque = deque()
        self._running = False
        self._lock = threading.Lock()

    def submit(self, fn, *args, **kw) -> Future:
        fut: Future = Future()
        with self._lock:
            self._q.append((fut, fn, args, kw))
            if not self._running:
                self._running = True
                self._pool.submit(self._drain)
        return fut

    def _drain(self):
        while True:
            with self._lock:
                if not self._q:
                    self._running = False
                    return
                fut, fn, args, kw = self._q.popleft()
            if not fut.set_running_or_notify_cancel():
                continue
            try:
                fut.set_result(fn(*args, **kw))
            except BaseException as e:
                fut.set_exception(e)

    def shutdown(self, wait: bool = False, cancel_futures: bool = False):
        if cancel_futures:
            with self._lock:
                q, self._q = list(self._q), deque()
            for fut, *_ in q:
                fut.cancel()


class _ActorLane:
    """One hosted actor: instance + its own serial executor lane, so N
    fractional-CPU actors can share a worker process while each keeps the
    FIFO ordering (or max_concurrency pool) of a dedicated worker (ref:
    worker_pool.h one-process-per-actor; the lane design trades process
    isolation for spawn-free density on num_cpus<1 actors)."""

    def __init__(self, spec: TaskSpec, shared_pool: ThreadPoolExecutor):
        self.spec = spec
        self.instance: Any = None
        if spec.max_concurrency > 1:
            self.executor: Any = ThreadPoolExecutor(
                max_workers=spec.max_concurrency,
                thread_name_prefix=f"actor-{spec.actor_id.hex()[:8]}")
        else:
            self.executor = _SerialLaneExecutor(shared_pool)
        self.async_sem = asyncio.Semaphore(max(1, spec.max_concurrency))
        self.executing: set = set()       # task ids currently in _execute


class Worker:
    """RPC handler for the worker process; delegates ownership-protocol
    methods to the embedded Runtime (every worker is also an owner)."""

    def __init__(self, runtime: Runtime):
        self.runtime = runtime
        self.task_executor = ThreadPoolExecutor(max_workers=1,
                                                thread_name_prefix="task-exec")
        # hosted actors by id — a dedicated actor worker is simply a
        # one-lane host. Serial lanes share this pool; its cap matches
        # lanes-per-worker so every lane can hold a thread even when all
        # of them block in ray_tpu.get() simultaneously (a smaller cap
        # could deadlock lanes that produce each other's results).
        # Threads spawn on demand, so the resident count tracks the
        # high-water mark of CONCURRENT lane work, not the lane count.
        self.lanes: dict = {}
        self._lane_pool = ThreadPoolExecutor(
            max_workers=max(32, runtime.cfg.actor_lanes_per_worker),
            thread_name_prefix="lane-exec")
        # ids destroyed mid-creation: a create whose ctor outlives the
        # destroy must not install a zombie lane
        self._destroyed: set = set()
        # cancellation (ref: core worker CancelTask -> KeyboardInterrupt
        # in the executing thread): task_id -> executing thread ident,
        # plus the set of ids whose interrupt means CANCELLED, not ctrl-C
        self._exec_threads: dict = {}
        self._cancelled: set = set()
        self._cancel_lock = threading.Lock()
        # standing channels of compiled DAGs whose nodes live on this
        # worker's lanes (dag.compiled); negotiated once at channel_open
        self.channels = ChannelHost(self)

    async def rpc_channel_open(self, spec) -> dict:
        return await self.channels.rpc_channel_open(spec)

    def rpc_channel_push(self, channel_id, seq, slot, kind,
                         payload) -> dict:
        return self.channels.push(channel_id, seq, slot, kind, payload)

    rpc_channel_push._rpc_inline = True   # sync + non-blocking: ONEWAY
    # frames dispatch inline in the server reader loop (rpc.py)

    async def rpc_channel_close(self, channel_id) -> dict:
        return await self.channels.rpc_channel_close(channel_id)

    def __getattr__(self, name):
        # Delegate rpc_wait_object / rpc_locate / rpc_add_borrow / ... to the
        # runtime so one server serves both protocols.
        return getattr(self.runtime, name)

    # ---------------------------------------------------------------- execute

    def _resolve_args(self, spec: TaskSpec) -> Tuple[list, dict]:
        args: List[Any] = []
        kwargs: dict = {}
        ref_args: List[Tuple[int, ObjectRef]] = []
        for kind, payload in spec.args:
            if kind == "v":
                args.append(serialization.unpack(payload))
            elif kind == "ref":
                oid, owner = payload
                args.append(ObjectRef(oid, owner))
            elif kind == "kw":
                for k, (kk, pv) in payload.items():
                    if kk == "v":
                        kwargs[k] = serialization.unpack(pv)
                    else:
                        oid, owner = pv
                        kwargs[k] = ObjectRef(oid, owner)
        # Dependency resolution: refs are fetched before user code runs,
        # in ONE batched get so borrowed args share round-trips (ref:
        # _raylet.pyx deserializes args via plasma before execution).
        refs = [a for a in args if isinstance(a, ObjectRef)]
        refs += [v for v in kwargs.values() if isinstance(v, ObjectRef)]
        if refs:
            vals = iter(self.runtime.get(refs))
            args = [next(vals) if isinstance(a, ObjectRef) else a
                    for a in args]
            kwargs = {k: (next(vals) if isinstance(v, ObjectRef) else v)
                      for k, v in kwargs.items()}
        return args, kwargs

    def _package_returns(self, spec: TaskSpec, values: Any) -> TaskResult:
        n = spec.num_returns
        if n == 0:
            return TaskResult(spec.task_id, [])
        if n == 1:
            values = (values,)
        elif not isinstance(values, tuple) or len(values) != n:
            raise ValueError(
                f"task {spec.name} declared num_returns={n} but returned "
                f"{type(values).__name__}")
        returns = []
        for i, v in enumerate(values):
            returns.append(self._package_one(spec.return_ids()[i], v))
        return TaskResult(spec.task_id, returns)

    def _package_one(self, rid, v) -> Tuple[str, Any]:
        """Serialize one return/stream item: inline when small, into the
        node store (nodelet-pinned) when large."""
        meta, bufs = serialization.serialize(v)
        size = serialization.serialized_size(meta, bufs)
        if size <= self.runtime.cfg.max_direct_call_object_size:
            packed = bytearray(size)
            serialization.write_to(memoryview(packed), meta, bufs)
            return ("inline", bytes(packed))
        store = self.runtime.store
        view = self.runtime._create_view_with_spill(rid, size)
        if view is not None:
            serialization.write_to(view, meta, bufs)
            del view
            store.seal(rid)
            self.runtime._attribute_put(rid, size)
            self.runtime._pin_primary(rid)  # nodelet owns the pin
        elif not store.contains(rid):
            raise MemoryError(
                f"object store full storing {rid.hex()[:12]}")
        return ("store", {"addr": self.runtime.nodelet_addr, "size": size})

    def _stream_item_coro(self, spec: TaskSpec, idx: int, kind, payload):
        """The one report-item RPC both streaming drivers share. With
        backpressure the owner deliberately withholds the ack until the
        consumer catches up — that call gets a generous deadline."""
        owner = self.runtime.pool.get(spec.owner.addr)
        bp = spec.generator_backpressure
        bpb = spec.generator_backpressure_bytes
        return owner.call(
            "stream_item", task_id=spec.task_id, index=idx, kind=kind,
            payload=payload, backpressure=bp, backpressure_bytes=bpb,
            timeout=3600.0 if (bp is not None or bpb is not None) else 30.0)

    def _stream_done_coro(self, spec: TaskSpec, total: int):
        return self.runtime.pool.get(spec.owner.addr).call(
            "stream_done", task_id=spec.task_id, total=total, timeout=30.0)

    def _stream_returns(self, spec: TaskSpec, gen) -> TaskResult:
        """Drive a generator task: report each yielded item to the owner
        as it is produced (ref: task_manager.h:143-171 streaming returns /
        ReportGeneratorItemReturns). Runs on an executor thread; RPCs
        bridge onto the runtime loop."""
        if not hasattr(gen, "__iter__") or isinstance(gen, (str, bytes,
                                                            list, tuple,
                                                            dict)):
            raise TypeError(
                f"task {spec.name} declared num_returns='streaming' but "
                f"returned {type(gen).__name__}, not a generator/iterator")
        idx = 0
        for item in gen:
            idx += 1
            kind, payload = self._package_one(
                ObjectID.for_return(spec.task_id, idx), item)
            r = self.runtime._run(self._stream_item_coro(spec, idx, kind,
                                                         payload))
            if not r.get("ok"):
                # owner dropped the stream (scope exit / shutdown): stop
                # producing and let generator cleanup run
                if hasattr(gen, "close"):
                    gen.close()
                break
        self.runtime._run(self._stream_done_coro(spec, idx))
        return TaskResult(spec.task_id, [])

    def _execute(self, spec: TaskSpec, fn=None) -> TaskResult:
        """Runs on an executor thread — NEVER on the asyncio loop: it blocks
        on GCS KV fetches and dependency gets, which are loop-driven."""
        from ray_tpu.runtime_env import TaskEnvContext

        # Actor methods inherit the actor's creation env (ref: actor-level
        # runtime_env applies to all its tasks).
        lane = (self.lanes.get(spec.actor_id)
                if spec.is_actor_call else None)
        env = spec.runtime_env or (lane.spec.runtime_env if lane else None)
        self.runtime.set_exec_context(
            spec.task_id, runtime_env=env,
            actor_id=spec.actor_id if spec.is_actor_call else None)
        with self._cancel_lock:
            self._exec_threads[spec.task_id] = threading.get_ident()
            if lane is not None:
                lane.executing.add(spec.task_id)
        try:
            from ray_tpu.util.tracing import continue_trace

            span_name = (f"actor::{spec.method_name}" if spec.is_actor_call
                         else f"task::{spec.name}")
            with TaskEnvContext(self.runtime, spec.runtime_env), \
                    continue_trace(spec.trace_ctx, span_name,
                                   {"task_id": spec.task_id.hex()}):
                if fn is None:
                    fn = self.runtime.load_function(spec.func_id)
                args, kwargs = self._resolve_args(spec)
                value = fn(*args, **kwargs)
                if spec.is_streaming:
                    # stream inside the env/trace context: the generator
                    # body runs lazily, during iteration
                    return self._stream_returns(spec, value)
            return self._package_returns(spec, value)
        except BaseException as e:
            tb = traceback.format_exc()
            with self._cancel_lock:
                was_cancelled = spec.task_id in self._cancelled
            if was_cancelled and isinstance(e, KeyboardInterrupt):
                # the interrupt was OUR injected cancellation, not ctrl-C
                from ray_tpu.core.status import TaskCancelledError

                ser = SerializedException(
                    TaskCancelledError(
                        f"task {spec.name} cancelled while running"),
                    tb, wrap=False)
            else:
                ser = SerializedException(e, tb)
            return TaskResult(spec.task_id,
                              [("err", ser)] * max(1, spec.num_returns))
        finally:
            with self._cancel_lock:
                self._exec_threads.pop(spec.task_id, None)
                self._cancelled.discard(spec.task_id)
                if lane is not None:
                    lane.executing.discard(spec.task_id)
            self.runtime.clear_exec_context()

    # ------------------------------------------------------------ rpc surface

    async def rpc_push_task(self, spec: TaskSpec) -> TaskResult:
        loop = asyncio.get_running_loop()
        result = await loop.run_in_executor(self.task_executor,
                                            self._execute, spec)
        # worker-side task events are tracing spans — ship them promptly
        # so `ray_tpu.timeline()` sees fresh traces
        self.runtime.flush_task_events()
        return result

    async def rpc_create_actor(self, spec: TaskSpec) -> dict:
        self._destroyed.discard(spec.actor_id)   # fresh incarnation
        lane = _ActorLane(spec, self._lane_pool)

        def _ctor():
            from ray_tpu.runtime_env import TaskEnvContext
            from ray_tpu.util.tracing import continue_trace

            self.runtime.set_exec_context(spec.task_id,
                                          runtime_env=spec.runtime_env,
                                          actor_id=spec.actor_id)
            try:
                # The actor owns its lane: its runtime env persists for
                # the actor's lifetime (entered, never exited — ref: actors
                # run in env-dedicated workers; lane hosts are pooled by
                # the same process-env key, so lanes never need
                # conflicting process envs).
                TaskEnvContext(self.runtime, spec.runtime_env).__enter__()
                cls = self.runtime.load_function(spec.func_id)
                args, kwargs = self._resolve_args(spec)
                with continue_trace(spec.trace_ctx,
                                    f"actor::{spec.name}.__init__",
                                    {"actor_id": spec.actor_id.hex()}):
                    lane.instance = cls(*args, **kwargs)
                self.runtime.flush_task_events()
                return {"ok": True}
            except BaseException:
                return {"ok": False, "error": traceback.format_exc()}
            finally:
                self.runtime.clear_exec_context()

        loop = asyncio.get_running_loop()
        res = await loop.run_in_executor(lane.executor, _ctor)
        if spec.actor_id in self._destroyed:
            # destroyed while the ctor ran (creation-timeout path): do
            # not install a zombie lane the control plane stopped tracking
            self._destroyed.discard(spec.actor_id)
            lane.executor.shutdown(wait=False)
            lane.instance = None
            return {"ok": False, "error": "actor destroyed during creation"}
        if res.get("ok"):
            self.lanes[spec.actor_id] = lane
        else:
            lane.executor.shutdown(wait=False)
        return res

    async def rpc_destroy_actor(self, actor_id) -> dict:
        """Tear down ONE lane without touching the process (the lane twin
        of kill_worker): interrupt its executing sync methods, cancel its
        queue, drop the instance. Other lanes are unaffected. Async
        methods already past their semaphore run to completion (kill
        races execution the same way on a dedicated worker); sem-queued
        ones fail the post-acquire liveness check."""
        import ctypes

        lane = self.lanes.pop(actor_id, None)
        if lane is None:
            # creation may still be in flight: tombstone it
            self._destroyed.add(actor_id)
            return {"ok": False, "error": "no such lane"}
        with self._cancel_lock:
            for tid in list(lane.executing):
                ident = self._exec_threads.get(tid)
                if ident is not None:
                    self._cancelled.add(tid)
                    ctypes.pythonapi.PyThreadState_SetAsyncExc(
                        ctypes.c_ulong(ident),
                        ctypes.py_object(KeyboardInterrupt))
        lane.executor.shutdown(wait=False, cancel_futures=True)
        lane.instance = None
        # lane death is NOT process death: per-actor module state (e.g.
        # util/collective's group clients) must be released explicitly
        from ray_tpu.core.runtime import actor_teardown_hooks
        for hook in list(actor_teardown_hooks):
            try:
                hook(actor_id.hex())
            except Exception:
                logger.exception("actor teardown hook failed")
        return {"ok": True}

    async def rpc_push_actor_task(self, spec: TaskSpec) -> TaskResult:
        lane = self.lanes.get(spec.actor_id)
        if lane is None or lane.instance is None:
            raise RuntimeError("no actor hosted here")
        method = getattr(lane.instance, spec.method_name, None)
        if method is None:
            def method(*a, **k):
                raise AttributeError(
                    f"actor has no method {spec.method_name!r}")
        if inspect.isasyncgenfunction(method) and spec.is_streaming:
            # async-generator streaming method (the Serve token-streaming
            # path): items are produced and reported on the loop;
            # serialization hops to an executor thread because packaging
            # large items blocks on the nodelet pin RPC.
            async with lane.async_sem:
                if self.lanes.get(spec.actor_id) is not lane or \
                        lane.instance is None:
                    raise RuntimeError("no actor hosted here")
                loop = asyncio.get_running_loop()
                try:
                    args, kwargs = await loop.run_in_executor(
                        lane.executor, self._resolve_args, spec)
                    self.runtime.set_exec_context(spec.task_id,
                                                  actor_id=spec.actor_id)
                    agen = method(*args, **kwargs)
                    idx = 0
                    async for item in agen:
                        idx += 1
                        kind, payload = await loop.run_in_executor(
                            None, self._package_one,
                            ObjectID.for_return(spec.task_id, idx), item)
                        r = await self._stream_item_coro(spec, idx, kind,
                                                         payload)
                        if not r.get("ok"):
                            await agen.aclose()
                            break
                    await self._stream_done_coro(spec, idx)
                    return TaskResult(spec.task_id, [])
                except BaseException as e:
                    ser = SerializedException(e, traceback.format_exc())
                    return TaskResult(spec.task_id, [("err", ser)])
                finally:
                    self.runtime.clear_exec_context()
        if inspect.iscoroutinefunction(method):
            # async actor: method coroutine runs on the loop (ref: fibers,
            # fiber.h); arg resolution still happens off-loop because it may
            # block on remote gets.
            async with lane.async_sem:
                if self.lanes.get(spec.actor_id) is not lane or \
                        lane.instance is None:
                    raise RuntimeError("no actor hosted here")
                loop = asyncio.get_running_loop()
                try:
                    args, kwargs = await loop.run_in_executor(
                        lane.executor, self._resolve_args, spec)
                    self.runtime.set_exec_context(spec.task_id,
                                                  actor_id=spec.actor_id)
                    value = await method(*args, **kwargs)
                    return self._package_returns(spec, value)
                except BaseException as e:
                    ser = SerializedException(e, traceback.format_exc())
                    return TaskResult(spec.task_id,
                                      [("err", ser)] * max(1, spec.num_returns))
                finally:
                    self.runtime.clear_exec_context()
        loop = asyncio.get_running_loop()
        result = await loop.run_in_executor(lane.executor, self._execute,
                                            spec, method)
        self.runtime.flush_task_events()
        return result

    async def rpc_cancel_task(self, task_id: TaskID) -> dict:
        """Cancel an executing task by injecting KeyboardInterrupt into
        its executor thread (ref: core worker CancelTask -> SIGINT in the
        worker). The interrupt lands at the next bytecode boundary; a
        task blocked in C (e.g. a long XLA compile) is interrupted when
        it returns to Python — same limitation as the reference."""
        import ctypes

        with self._cancel_lock:
            # inject UNDER the lock: _execute's finally pops the entry
            # under this same lock, so a present entry proves the thread
            # is still inside _execute for THIS task — the interrupt can
            # never land in a pool thread that moved on to other work
            # (or sits idle in queue.get, where a stray KI would kill
            # the executor's only thread permanently)
            ident = self._exec_threads.get(task_id)
            if ident is None:
                return {"status": "not_running"}
            self._cancelled.add(task_id)
            n = ctypes.pythonapi.PyThreadState_SetAsyncExc(
                ctypes.c_ulong(ident), ctypes.py_object(KeyboardInterrupt))
            if n != 1:   # thread gone (cannot happen while entry present)
                self._cancelled.discard(task_id)
                return {"status": "not_running"}
        return {"status": "cancelling"}

    async def rpc_dump_stacks(self) -> dict:
        """All-thread stack dump (ref: `ray stack` scripts.py:1789 —
        py-spy over workers; here the worker self-reports, no ptrace)."""
        import threading

        names = {t.ident: t.name for t in threading.enumerate()}
        parts = []
        for tid, frame in sys._current_frames().items():
            parts.append(f"--- thread {names.get(tid, '?')} ({tid}) ---\n"
                         + "".join(traceback.format_stack(frame)))
        return {"pid": os.getpid(), "stacks": "\n".join(parts)}

    async def rpc_exit_worker(self, reason: str = "") -> dict:
        logger.info("worker exiting: %s", reason)
        asyncio.get_running_loop().call_later(0.05, lambda: os._exit(0))
        return {"ok": True}


def _install_flight_hooks(runtime) -> None:
    """Uncaught exceptions (main thread, lane threads, daemon helpers)
    write the flight recorder on the way down — the last thing a dying
    worker does is label its own black box. Task-raised exceptions are
    NOT uncaught (they travel as typed error results) and don't trip
    this."""
    import sys
    import threading as _threading

    prev_sys = sys.excepthook
    prev_thread = _threading.excepthook

    def _dump(where: str, exc_type, exc) -> None:
        try:
            runtime.flight.dump(
                f"uncaught:{exc_type.__name__}",
                extra={"where": where, "error": repr(exc)}, force=True)
        except Exception:
            pass

    def _sys_hook(exc_type, exc, tb):
        _dump("main", exc_type, exc)
        prev_sys(exc_type, exc, tb)

    def _thread_hook(hook_args):
        if not issubclass(hook_args.exc_type, SystemExit):
            _dump(hook_args.thread.name if hook_args.thread else "thread",
                  hook_args.exc_type, hook_args.exc_value)
        prev_thread(hook_args)

    sys.excepthook = _sys_hook
    _threading.excepthook = _thread_hook


async def worker_main(args):
    cfg = Config.from_json(args.config)
    gh, gp = args.gcs.rsplit(":", 1)
    nh, np_ = args.nodelet.rsplit(":", 1)
    loop = asyncio.get_running_loop()
    runtime = Runtime(cfg, (gh, int(gp)), (nh, int(np_)), args.store,
                      JobID.nil(), mode="worker", loop=loop,
                      worker_id=bytes.fromhex(args.worker_id),
                      node_id=args.node_id)
    set_runtime(runtime)
    _install_flight_hooks(runtime)
    worker = Worker(runtime)
    runtime.server.handler = worker
    host, port = await runtime.server.start()
    runtime.address = RuntimeAddress(host, port, runtime.worker_id)
    r = await runtime.pool.get(runtime.nodelet_addr).call(
        "register_worker", worker_id=runtime.worker_id, addr=(host, port),
        timeout=cfg.rpc_connect_timeout_s)
    if not r.get("ok"):
        logger.error("nodelet rejected registration; exiting")
        return
    logger.info("worker %s serving on %s:%d", args.worker_id[:8], host, port)
    # Exit if the nodelet disappears (parent supervision).
    nodelet = runtime.pool.get(runtime.nodelet_addr)
    while True:
        await asyncio.sleep(5.0)
        try:
            await nodelet.call("ping", timeout=5.0)
        except Exception:
            logger.warning("nodelet unreachable; worker exiting")
            return


def main():
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--nodelet", required=True)
    parser.add_argument("--gcs", required=True)
    parser.add_argument("--store", required=True)
    parser.add_argument("--node-id", required=True)
    parser.add_argument("--worker-id", required=True)
    parser.add_argument("--config", default="{}")
    args = parser.parse_args()
    logging.basicConfig(
        level=logging.INFO,
        format=f"[worker {args.worker_id[:8]}] %(asctime)s %(levelname)s %(message)s")
    asyncio.run(worker_main(args))


if __name__ == "__main__":
    main()
