"""Runtime configuration flags.

Reference: src/ray/common/ray_config_def.h:18 — a single macro table of
RAY_CONFIG(type, name, default) entries, overridable via RAY_<NAME> env vars
or a serialized system-config dict handed down from `init()`. We reproduce
the same three-layer precedence (default < env RAY_TPU_<NAME> < explicit
_system_config) with a plain dataclass-of-record table.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field, fields
from typing import Any, Dict


@dataclass
class Config:
    # --- object store -------------------------------------------------------
    object_store_memory: int = 2 * 1024**3       # host shm tier bytes
    object_store_max_objects: int = 1 << 15
    # Objects <= this many bytes take the in-process memory-store path and are
    # inlined into task replies (ref: RayConfig max_direct_call_object_size).
    max_direct_call_object_size: int = 100 * 1024
    object_transfer_chunk_bytes: int = 8 * 1024**2  # ref: 64MiB gRPC chunks; we
                                                    # default smaller for 1-host
    # Native zero-staging transfer plane (native/xfer.cc); off -> always
    # use the portable chunk-RPC pull path.
    native_transfer_enabled: bool = True
    # Max concurrent outbound serves PER OBJECT per node (0 = unlimited;
    # pulls of distinct objects always multiplex freely). Over-cap
    # pullers get "busy" and retry against whichever holders have
    # registered copies by then — a fan-in broadcast of one hot object
    # cascades through peers instead of serializing behind one source
    # (ref: pull_manager.h:52 pulls spread across every holder).
    object_serve_concurrency: int = 2
    # kCreating store entries older than this are orphans of a dead
    # producer and get reaped. The transfer plane heartbeats the entry
    # per read() batch while bytes flow, and each read() is bounded by
    # the 120 s socket timeout — so a live pull's touch interval never
    # exceeds ~120 s and a stalled one aborts. MUST stay comfortably
    # above that 120 s bound or the reaper can free a buffer an active
    # (trickling) receive is still writing into.
    creating_orphan_age_s: float = 300.0
    # --- HBM device object tier (SURVEY §7 step 2; core/device_store.py) ----
    # put(jax.Array) keeps the buffer device-resident; D2H happens only on
    # first remote need or on HBM pressure (spill chain HBM->shm->disk).
    device_object_tier: bool = True
    device_object_store_bytes: int = 2 * 1024**3
    # --- object spilling (ref: local_object_manager.h:41 + external_storage) -
    object_spill_enabled: bool = True
    object_spill_threshold: float = 0.8          # spill when usage crosses this
    object_spill_low_water: float = 0.5          # ...down to this fraction
    object_spill_dir: str = ""                   # default: <session>/spill
    # --- data streaming executor (ray_tpu/data/execution/) ------------------
    # Share of object_store_memory the executor may hold in unconsumed
    # operator outputs (ResourceManager budget; split evenly across the
    # pipeline's budgetable operators). Also bounds the fused path's
    # generator byte backpressure.
    data_execution_budget_fraction: float = 0.25
    # Max concurrent tasks a single physical operator keeps in flight.
    data_execution_max_tasks_per_op: int = 4
    # --- scheduler / raylet -------------------------------------------------
    worker_lease_timeout_s: float = 30.0
    # -1 = auto: min(node CPU total, 2) workers spawn at node start (ref:
    # worker_pool.h prestart — the reference raylet prestarts num_cpus
    # python workers; a cold pool makes the first task waves pay worker
    # spawn + the lease-grant race serially)
    worker_pool_prestart: int = -1
    max_workers_per_node: int = 8
    # Fractional-CPU actors (0 < num_cpus < 1, no other resources) pack
    # into shared lane-host workers, this many per process — density
    # without a 0.5+ s interpreter spawn per actor (ref: the reference's
    # one-process-per-actor model tops out at worker-spawn rate; its 40k
    # actor benchmark uses num_cpus=0.001). 0 disables lane packing.
    # SEMANTIC TRADE: lane-packed actors share an interpreter, so
    # per-PROCESS state (module globals, class attributes) is shared
    # across them where the reference isolates it. Actor code needing
    # "which actor am I" must use get_runtime_context().get_actor_id()
    # (per lane thread), as util/collective does; actors needing real
    # process isolation should request num_cpus>=1.
    actor_lanes_per_worker: int = 16
    worker_idle_timeout_s: float = 300.0
    scheduler_spread_threshold: float = 0.5      # ref: RAY_scheduler_spread_threshold
    scheduler_top_k_fraction: float = 0.2        # ref: hybrid_scheduling_policy.h:29
    # --- OOM defense (ref: memory_monitor.h:52, ray_config_def.h:74) --------
    memory_monitor_refresh_ms: int = 0           # 0 disables (ref default 250)
    memory_usage_threshold: float = 0.95
    memory_monitor_kill_policy: str = "group_by_owner"  # | "retriable_fifo"
    memory_monitor_test_usage_file: str = ""     # tests: file with fake fraction
    # --- health / failure detection -----------------------------------------
    health_check_period_s: float = 1.0           # ref: ray_config_def.h:793-799
    health_check_timeout_s: float = 5.0
    health_check_failure_threshold: int = 5
    actor_max_restarts_default: int = 0
    task_max_retries_default: int = 3
    # --- gcs ----------------------------------------------------------------
    gcs_storage: str = "memory"                  # "memory" | "file" (ft restart)
    gcs_file_storage_path: str = ""
    # How long clients retry GCS calls across a restart (ref:
    # gcs_failover_worker_reconnect_timeout ray_config_def.h:62).
    gcs_reconnect_timeout_s: float = 30.0
    # --- timeouts -----------------------------------------------------------
    rpc_connect_timeout_s: float = 10.0
    # Default transport deadline for every control-plane RpcClient.call()
    # that does not pass its own: a gray-failed peer (black-holed link,
    # wedged handler) surfaces as a typed RpcTimeout instead of hanging
    # the caller forever. Long-running data-plane calls (push_task) opt
    # out with an explicit, lint-allowlisted timeout=None.
    rpc_call_timeout_s: float = 60.0
    # Application-level keepalive: each RpcClient pings its server every
    # interval; a connection that stays rx-silent past the timeout is
    # aborted, converting a black-holed link into ConnectionLost (TCP
    # alone buffers writes for minutes before noticing — the gray
    # failure mode of Huang et al. HotOS'17). 0 disables pinging.
    rpc_keepalive_interval_s: float = 5.0
    rpc_keepalive_timeout_s: float = 20.0
    # Serialized devtools.chaos.FaultPlan (JSON) — when non-empty, every
    # process in the session installs the same seeded fault-injection
    # interposer into its transport at startup (the plan inherits through
    # the spawned-process --config chain, so one plan governs the whole
    # cluster and one seed reproduces one fault sequence).
    chaos_plan: str = ""
    get_timeout_warn_s: float = 10.0
    # --- workers ------------------------------------------------------------
    worker_start_timeout_s: float = 60.0
    # A pump whose queue drained holds its lease parked for this grace
    # window before returning it; a task submitted within the window is
    # pushed straight to the already-leased worker — no acquire/return
    # RPC pair (ref: worker lease reuse / idle-worker keep-alive,
    # direct_task_transport.cc pipelining). Sequential submit->get loops
    # go from 3 RPCs/task to 1.
    lease_reuse_grace_s: float = 0.025
    # --- host collectives (ray_tpu/collective/) -----------------------------
    # Per-hop blocks below this go as ONE inline mailbox message with no
    # chunking or sub-chunk pipelining — at small sizes the per-chunk
    # fixed costs (actor RPC + pickle) dominate and pipelining only
    # multiplies them (the eager tier).
    collective_eager_threshold_bytes: int = 64 * 1024
    # Chunks at or above this are put() into the object store once and
    # only the ObjectRef is mailed; the receiver resolves it via the
    # pinned zero-copy local read (the zero-copy tier). Must stay above
    # max_direct_call_object_size or the "store" copy is just an inline
    # blob riding the ref. 0 disables (everything rides the mailbox).
    collective_zerocopy_threshold_bytes: int = 256 * 1024
    # --- tpu ----------------------------------------------------------------
    # Logical chip resource name; slice-aware gang scheduling reserves whole
    # ICI-connected shapes (SURVEY.md section 7 "hard parts").
    chip_resource: str = "TPU"
    # --- LLM serving (ray_tpu/serve/llm_router.py) --------------------------
    # Prompt tokens hashed for prefix-affinity routing: streams sharing at
    # least this many leading tokens rendezvous onto the same replica, so
    # its paged-KV prefix cache (llm.py PrefixCache) actually gets hits.
    llm_router_prefix_tokens: int = 32
    # Router-wide in-flight bound; admissions beyond it shed with
    # LLMQueueFull + Retry-After instead of queueing unboundedly.
    llm_router_max_inflight: int = 256
    # Affinity override point: when the prefix-preferred replica's
    # pressure exceeds overload_factor x the fleet mean, fall through to
    # the next replica in rendezvous order (cache locality is not worth
    # an unbounded hot spot).
    llm_router_overload_factor: float = 2.0
    # Background poll period for per-replica LLMServer.stats() feeding
    # the pressure score (busy-fraction EWMA).
    llm_router_stats_interval_s: float = 1.0
    # Drive the router->replica stream-frame hop through a compiled
    # two-node graph (dag/compiled.py standing channels) instead of
    # per-call handle_request_streaming.remote() dispatch; falls back to
    # the legacy path per replica on compile failure.
    llm_router_compiled_hop: bool = True
    # Scale-down grace: a draining replica is unpublished from routers
    # immediately, then given this long to finish in-flight streams
    # before the controller kills it.
    serve_drain_timeout_s: float = 10.0
    # --- model multiplexing (ray_tpu/serve/multiplex.py) --------------------
    # Per-replica LRU bound on concurrently-loaded models; loading one
    # past the bound evicts the least-recently-used model through the
    # cache's unloader hook (engine teardown + page-pool release).
    serve_max_models_per_replica: int = 4
    # Weighted-fair tenant admission: JSON map of tenant -> weight, e.g.
    # '{"free": 1, "pro": 4}'. "" means every tenant weighs 1. A tenant
    # absent from the map gets weight 1. Shares of the router's
    # max_inflight are split by weight over the tenants active at
    # admission time; a tenant is always admitted up to its guaranteed
    # share and may borrow idle capacity up to the global cap. (A JSON
    # string, not a dict field: RAY_TPU_* env overrides parse by field
    # type and only bool/int/float/str survive that path.)
    serve_tenant_weights: str = ""
    # Per-model autoscaling target: desired mean per-model queue depth
    # per replica serving that model. The controller sizes each model's
    # replica set to ceil(model_load / this) within the deployment's
    # model_autoscaling_config bounds.
    serve_model_target_load: float = 2.0
    # --- disaggregated serving (ray_tpu/serve/disagg.py) --------------------
    # Tokens per KV page for the handoff/prefix-directory hashing (the
    # sim granularity; the real engine hashes at its own page_size).
    serve_disagg_page_tokens: int = 16
    # Full KV pages per handoff chunk: the prefill replica put()s one
    # store object per GROUP of pages, so the prefill->decode envelope
    # carries O(prompt/group) refs instead of O(prompt/page).
    serve_disagg_group_pages: int = 4
    # Prefill-replica retention of directory-registered page groups
    # (local LRU): evicting one drops its global-directory entry too.
    # Retention past store capacity rides the nodelet spill tier.
    serve_disagg_retained_groups: int = 512
    # GCS global prefix directory LRU capacity (page-group entries).
    gcs_prefix_dir_capacity: int = 4096
    # --- observability ------------------------------------------------------
    task_event_buffer_size: int = 10000          # ref: task_event_buffer.h:199
    metrics_report_interval_s: float = 5.0       # nodelet node-stats agent
    # Per-process TelemetryAgent batching window: metric deltas, task
    # events, spans, and edge observations accumulate locally and ship in
    # ONE GCS report per interval (ref: metrics_agent.py batched push).
    telemetry_report_interval_s: float = 1.0
    # --- health plane (observability/health.py) -----------------------------
    # Flight recorder: bounded per-process ring of recent task events /
    # spans / channel-frame metadata, dumped to a post-mortem JSON under
    # flight_recorder_dir ("" -> /tmp/ray_tpu/flight) on stall detection,
    # uncaught worker exception, or CollectiveError. 0 disables.
    flight_recorder_size: int = 2048
    flight_recorder_dir: str = ""
    # A RUNNING task older than straggler_k x p95 of its completed
    # same-name peers (needs >= straggler_min_peers completions) raises a
    # straggler event in health_report() and a timeline instant.
    straggler_k: float = 3.0
    straggler_min_peers: int = 5
    # Collective recv/coordination waits arm a progress beacon with this
    # deadline; the GCS watchdog emits a StallEvent (naming the suspect
    # rank) once it passes without progress — typically long before the
    # collective's own timeout would fire.
    collective_stall_deadline_s: float = 10.0
    # --- elastic training (ray_tpu/train/elastic.py) -------------------------
    # Monitor beat: how often the ElasticCoordinator polls every rank for
    # reports / liveness while a gang attempt runs.
    elastic_poll_interval_s: float = 0.25
    # How often the coordinator pulls the GCS health report to fold
    # StallEvents (wedged rank, stuck collective) into suspect ranks.
    elastic_health_poll_interval_s: float = 1.0
    # Report-cadence straggler demotion: once every rank has filed at
    # least elastic_straggler_min_reports reports, a rank whose
    # inter-report EWMA exceeds elastic_straggler_k x the gang median is
    # quarantined. (The task-level straggler_k above can't see actor
    # loops — report cadence is the trainer-level analog.)
    elastic_straggler_k: float = 3.0
    elastic_straggler_min_reports: int = 4
    # Grow path: how often a shrunken gang probes the cluster for the
    # capacity to refill/grow toward its target world size.
    elastic_grow_check_interval_s: float = 5.0
    # Placement-group reservation wait used by elastic refill/grow
    # attempts (short on purpose: a failed attempt reports gang demand
    # and retries next probe instead of blocking the monitor).
    elastic_reserve_timeout_s: float = 10.0
    # Grace window before remediation kills surviving ranks: the monitor
    # keeps polling rank 0 until one more report lands (a report entry
    # appends only AFTER its checkpoint save commits, so one fresh
    # report == a complete checkpoint to resume from) or this expires.
    # Without it a death seconds into a run can kill rank 0 mid-first-
    # save and resume from scratch.
    elastic_drain_grace_s: float = 10.0
    # --- memory attribution plane (observability/memory.py) -----------------
    # Per-object ownership/pin/temperature records riding the batched
    # telemetry report; False strips the put/get hot-path hooks to bare
    # dict probes (bench.py --bench memory measures the difference).
    memory_attribution: bool = True
    # A record still pinned this long after its last owner ref died is a
    # leak suspect in memory_report() (ref: `ray memory` leak triage).
    memory_leak_suspect_s: float = 60.0
    # An unpinned non-primary record idle this long is a spill candidate
    # (the eviction shortlist the spilling pass will consume).
    memory_cold_after_s: float = 30.0
    log_to_driver: bool = True

    def override(self, d: Dict[str, Any]) -> "Config":
        for k, v in d.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown config key: {k}")
            setattr(self, k, v)
        return self

    @classmethod
    def load(cls, system_config: Dict[str, Any] | None = None) -> "Config":
        cfg = cls()
        for f in fields(cls):
            env = os.environ.get(f"RAY_TPU_{f.name.upper()}")
            if env is not None:
                cur = getattr(cfg, f.name)
                if isinstance(cur, bool):
                    setattr(cfg, f.name, env.lower() in ("1", "true", "yes"))
                elif isinstance(cur, int):
                    setattr(cfg, f.name, int(env))
                elif isinstance(cur, float):
                    setattr(cfg, f.name, float(env))
                else:
                    setattr(cfg, f.name, env)
        if system_config:
            cfg.override(system_config)
        return cfg

    def to_json(self) -> str:
        return json.dumps({f.name: getattr(self, f.name) for f in fields(self)})

    @classmethod
    def from_json(cls, s: str) -> "Config":
        return cls().override(json.loads(s))


GLOBAL_CONFIG: Config = Config.load()
