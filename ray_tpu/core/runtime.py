"""In-process runtime: the core-worker equivalent embedded in every driver and
worker process.

Reference: src/ray/core_worker/core_worker.cc — one object that does task
submission (SubmitTask :1855, CreateActor :1922, SubmitActorTask :2156),
object management (Put :1119 / Get :1331 over memory-store + plasma
providers), ownership (ReferenceCounter reference_count.h:59, TaskManager
task_manager.h:173 with retries :234 and lineage), and serves the ownership
protocol over its own RPC server (every worker is also a server).

Differences from the reference, deliberate:
- The submission path keeps the lease-reuse/pipelining idea
  (direct_task_transport.cc:24,346,588) with one queue + leased-worker set
  per scheduling class.
- Borrower registration is borrower-initiated (see refcount.py).
- The "plasma" tier is the node shm segment (native/objstore.cc); gets pin
  the object for zero-copy numpy views, released when the local ref dies.
"""

from __future__ import annotations

import asyncio
import hashlib
import os
import random
import logging
import contextvars
import threading
import time
import traceback
import weakref
from collections import OrderedDict, defaultdict, deque
from concurrent.futures import Future as SyncFuture
from typing import Any, Callable, Dict, List, Optional, Sequence, Set, Tuple

import cloudpickle

from ray_tpu.core import serialization
from ray_tpu.core.common import (STREAMING, Address, ObjectRef,
                                 ObjectRefGenerator, ResourceSet,
                                 RuntimeAddress, SchedulingStrategy,
                                 TaskResult, TaskSpec)
from ray_tpu.core.config import Config
from ray_tpu.core.ids import ActorID, JobID, ObjectID, TaskID, WorkerID
from ray_tpu.core.object_store import MemoryStore, SharedMemoryStore, _MISSING
from ray_tpu.core.refcount import ReferenceCounter
from ray_tpu.core.rpc import (ClientPool, ConnectionLost, EventLoopThread,
                              RemoteError, RpcServer)
from ray_tpu.core.status import (ActorDiedError, ActorUnavailableError,
                                 GetTimeoutError, ObjectLostError,
                                 TaskCancelledError, TaskError,
                                 WorkerCrashedError)
from ray_tpu.runtime_env import process_env as _process_env

logger = logging.getLogger("ray_tpu.runtime")

#: _fetch_from_locations result: every reachable copy answered "busy"
#: (source serve cap) — retry with refreshed locations; NOT lost.
_BUSY = object()

#: Called with the actor id (hex) when a LANE actor is torn down without
#: its process dying — modules holding per-actor state (util/collective)
#: register a pruner here, since lane packing breaks the reference's
#: "actor death == process death" cleanup.
actor_teardown_hooks: list = []

_runtime_lock = threading.Lock()
_global_runtime: Optional["Runtime"] = None


def get_runtime() -> "Runtime":
    if _global_runtime is None:
        raise RuntimeError("ray_tpu.init() has not been called")
    return _global_runtime


def current_runtime_or_none() -> Optional["Runtime"]:
    return _global_runtime


def set_runtime(rt: Optional["Runtime"]):
    global _global_runtime
    with _runtime_lock:
        _global_runtime = rt


class _LazyEvent:
    """threading.Event's API with the Condition materialized only when a
    thread actually blocks: most owner entries complete without a
    blocking waiter, and a real Event costs ~1 KB (condition + lock +
    waiter deque) — the dominant term of deep-queue driver RSS (1M
    queued tasks held ~3.9 GB in r4, mostly entry events)."""

    __slots__ = ("_flag", "_ev")
    _mat_lock = threading.Lock()

    def __init__(self):
        self._flag = False
        self._ev: Optional[threading.Event] = None

    def is_set(self) -> bool:
        return self._flag

    def set(self):
        self._flag = True
        ev = self._ev
        if ev is not None:
            ev.set()
            # blocked waiters hold their own reference and have been
            # woken; every future wait() takes the flag fast path — keep
            # none of the ~1 KB Condition machinery on completed entries
            self._ev = None

    def clear(self):
        self._flag = False
        ev = self._ev
        if ev is not None:
            ev.clear()

    def wait(self, timeout: Optional[float] = None) -> bool:
        if self._flag:
            return True
        with _LazyEvent._mat_lock:
            ev = self._ev
            if ev is None:
                ev = self._ev = threading.Event()
        if self._flag:
            # a set() raced materialization: it may have read _ev before
            # the store above — settle the real event ourselves
            ev.set()
            return True
        return ev.wait(timeout)


class _ObjectEntry:
    """Owner-side directory entry (ref: ObjectDirectory + memory store).

    Location sets and the waiter list materialize on first use: deep
    queues create millions of entries whose inline fast path never
    touches them (~650 B/entry saved). Hot per-task paths read the
    _-prefixed slots directly to avoid materializing empties."""

    __slots__ = ("state", "inline", "_locations", "error", "event", "spec",
                 "size", "_primaries", "_waiters")

    def __init__(self):
        self.state = "pending"        # pending | ready | error | lost
        self.inline: Optional[bytes] = None
        self._locations: Optional[Set[Address]] = None
        # locations written at produce/put time; pinned on their nodes,
        # never pruned on an unverified claim (secondaries are evictable
        # and get dropped when a pull misses)
        self._primaries: Optional[Set[Address]] = None
        self.error = None             # SerializedException
        self.event = _LazyEvent()
        self.spec: Optional[TaskSpec] = None   # lineage for reconstruction
        self.size = 0                 # stored bytes (locality scheduling)
        # completion callbacks (ref: wait_manager.h WaitRequest — waits
        # are notified, never polled). Persistent: they survive an
        # event.clear() on lineage reconstruction and fire again at the
        # next completion; registrants remove them when done.
        self._waiters: Optional[List[Any]] = None

    @property
    def locations(self) -> Set[Address]:
        s = self._locations
        if s is None:
            s = self._locations = set()
        return s

    @locations.setter
    def locations(self, v: Set[Address]):
        self._locations = v

    @property
    def primaries(self) -> Set[Address]:
        s = self._primaries
        if s is None:
            s = self._primaries = set()
        return s

    @primaries.setter
    def primaries(self, v: Set[Address]):
        self._primaries = v

    @property
    def waiters(self) -> List[Any]:
        w = self._waiters
        if w is None:
            w = self._waiters = []
        return w

    @waiters.setter
    def waiters(self, v: List[Any]):
        self._waiters = v


class _LeasedWorker:
    def __init__(self, lease_id: bytes, worker_addr: Address, nodelet_addr: Address,
                 worker_id: bytes):
        self.lease_id = lease_id
        self.worker_addr = tuple(worker_addr)
        self.nodelet_addr = tuple(nodelet_addr)
        self.worker_id = worker_id


class _PendingTask:
    def __init__(self, spec: TaskSpec, retries_left: int):
        self.spec = spec
        self.retries_left = retries_left


class _ExecCtxVar:
    """Execution-context slot with threading.local's attribute interface
    but contextvars storage: per-THREAD for sync executors (as before)
    AND per-asyncio-TASK on the loop, so concurrent streaming actor
    coroutines (Serve max_concurrency) can't clobber each other's
    task_id/put_index across awaits."""

    __slots__ = ("_var",)

    def __init__(self):
        object.__setattr__(self, "_var", contextvars.ContextVar(
            "ray_tpu_exec_ctx"))

    def _dict(self) -> dict:
        d = self._var.get(None)
        if d is None:
            d = {}
            self._var.set(d)
        return d

    def _replace(self, d: dict):
        """Install a FRESH dict for this task/thread. Tasks copy their
        context at creation, so mutating an inherited dict would leak
        across sibling tasks — entering an execution context must
        replace, not update."""
        self._var.set(d)

    def __getattr__(self, name):
        try:
            return self._dict()[name]
        except KeyError:
            raise AttributeError(name) from None

    def __setattr__(self, name, value):
        self._dict()[name] = value


_MEMTRACK = None


def _memtrack():
    """Cached attribution tracker (observability/memory.py). Lazy for the
    same import-cycle reason as the TelemetryAgent import in __init__."""
    global _MEMTRACK
    if _MEMTRACK is None:
        from ray_tpu.observability import memory
        _MEMTRACK = memory.tracker()
    return _MEMTRACK


class _ReadPin:
    """Holds one store read pin for exactly as long as any zero-copy value
    derived from the object's bytes is alive. Deserialized arrays export
    their buffers from THIS object (PEP 688 __buffer__), so the buffer
    chain keeps the pin alive and the release fires when the LAST value
    dies — not when the ObjectRef does. Without this, `get(ref)` followed
    by dropping the ref frees the store region while the returned numpy
    view still aliases it, and the next allocation silently rewrites the
    value's bytes (ref: plasma buffers hold a client reference until the
    Python buffer object is destroyed)."""

    __slots__ = ("_store", "_oid", "_view", "__weakref__")

    def __init__(self, store, oid, view):
        self._store = store
        self._oid = oid
        self._view = view

    def __buffer__(self, flags):
        return memoryview(self._view)

    @property
    def __array_interface__(self):
        # Python < 3.12 has no PEP 688, so memoryview(pin) cannot export
        # from this object directly. numpy can: np.asarray(pin) reads this
        # interface and keeps the pin as the array's base, so slices of
        # memoryview(np.asarray(pin)) carry the same keeps-the-pin chain.
        import numpy as np

        base = np.frombuffer(self._view, dtype=np.uint8)
        ptr, _ = base.__array_interface__["data"]
        return {"shape": base.shape, "typestr": "|u1",
                "data": (ptr, True), "version": 3}

    def buffer(self) -> memoryview:
        """A memoryview whose derived slices keep THIS pin alive (works on
        interpreters without PEP 688 __buffer__ support)."""
        import numpy as np

        return memoryview(np.asarray(self))

    def __del__(self):
        self._view = None
        try:
            self._store.release(self._oid)
            _memtrack().unpin(self._oid, "read")
        except Exception:
            pass   # interpreter/store teardown


class _StreamState:
    """Owner-side record of one streaming-generator task (ref:
    task_manager.h:143-171 ObjectRefStream): item entries live in the
    ordinary object directory under ObjectID.for_return(task_id, index);
    this tracks end-of-stream and wakes blocked consumers."""

    __slots__ = ("produced", "total", "error", "kick", "consumed",
                 "abandoned", "consumed_waiters", "item_bytes",
                 "ahead_bytes")

    def __init__(self):
        self.produced = 0     # highest item index reported ready
        self.total = None     # item count once the generator finished
        self.error = None     # SerializedException raised after last item
        self.kick = threading.Event()   # pulsed on every stream update
        self.consumed = 0     # highest index handed to the consumer
        # no consumer exists (lineage re-execution of a GC'd stream):
        # items are still accepted but nothing backpressures
        self.abandoned = False
        # (release_cond, asyncio.Future) pairs: backpressured item acks
        # waiting for consumption; cond() re-evaluated under
        # Runtime._stream_lock at every consumption advance
        self.consumed_waiters: List[Tuple[Any, Any]] = []
        # unconsumed item sizes (byte-budget backpressure, ref: the data
        # layer's admission by object-store memory)
        self.item_bytes: Dict[int, int] = {}
        self.ahead_bytes = 0


class Runtime:
    """One per process. mode: "driver" | "worker"."""

    def __init__(self, cfg: Config, gcs_addr: Address, nodelet_addr: Address,
                 store_name: str, job_id: JobID, mode: str = "driver",
                 loop: Optional[asyncio.AbstractEventLoop] = None,
                 worker_id: Optional[bytes] = None,
                 node_id: Optional[str] = None):
        self.cfg = cfg
        self.mode = mode
        self.job_id = job_id
        self.worker_id = worker_id or WorkerID.from_random().binary()
        self.gcs_addr = tuple(gcs_addr)
        self.nodelet_addr = tuple(nodelet_addr)
        self.store_name = store_name
        self.node_id = node_id    # hex of the co-located nodelet's node

        # Partition-tolerance deadlines (rpc_call_timeout_s, keepalive)
        # and the optional chaos interposition layer bind per-process
        # from this Config — the driver's _system_config and the
        # spawned daemons' --config chain carry the same values, so one
        # FaultPlan and one set of deadlines govern the whole cluster.
        from ray_tpu.core import rpc as _rpc
        from ray_tpu.devtools import chaos as _chaos
        _rpc.configure(cfg)
        _chaos.maybe_install(cfg, role=mode)   # "driver" | "worker"
        _chaos.note_peer(self.gcs_addr, "gcs")
        _chaos.note_peer(self.nodelet_addr, "nodelet")

        if loop is None:
            self.loop_thread: Optional[EventLoopThread] = EventLoopThread()
            self.loop = self.loop_thread.loop
        else:
            self.loop_thread = None
            self.loop = loop

        self.pool = ClientPool()
        self.server = RpcServer(self)
        self.memory_store = MemoryStore()
        self.store = SharedMemoryStore(store_name)
        from ray_tpu.core.device_store import DeviceStore

        # HBM tier (SURVEY §7 step 2): device arrays put() here stay
        # on-device; D2H staging is lazy (first remote need / pressure)
        self.device_store = DeviceStore(cfg.device_object_store_bytes)
        self._stage_lock = threading.Lock()
        self.refs = ReferenceCounter(self._self_addr, self._free_object,
                                     self._notify_owner,
                                     on_borrow_zero=self._free_borrow_caches)
        self.directory: Dict[ObjectID, _ObjectEntry] = {}
        self._dir_lock = threading.Lock()
        # Read pins backing zero-copy values handed to the user; weakrefs
        # to _ReadPin guards, which release when the last derived value
        # dies. Spill safety against these pins lives in the native store
        # (ts_evict frees only when refcount is the nodelet's own pin).
        self._pinned: Dict[ObjectID, Any] = {}

        # submission state, per scheduling class
        self._queues: Dict[Tuple, deque] = defaultdict(deque)
        # concurrent lease-requesting pumps per class (ref: the reference's
        # max_pending_lease_requests_per_scheduling_category)
        self._max_pumps = max(8, int(cfg.max_workers_per_node))
        self._class_leases: Dict[Tuple, List[_LeasedWorker]] = defaultdict(list)
        self._class_pending_lease: Dict[Tuple, int] = defaultdict(int)
        # pumps holding a lease parked in the reuse-grace window + the
        # event a new enqueue pulses to hand them work without a fresh
        # lease RPC (ref: idle leased-worker reuse)
        self._class_parked: Dict[Tuple, int] = defaultdict(int)
        self._class_work: Dict[Tuple, asyncio.Event] = {}
        self._inflight: Dict[TaskID, _PendingTask] = {}
        # interned per-submit defaults: a deep queue must not allocate a
        # fresh ResourceSet + SchedulingStrategy per task (owner-side
        # nothing mutates them; the wire pickles copies)
        self._default_resources = ResourceSet({"CPU": 1.0})
        self._default_scheduling = SchedulingStrategy()
        # cancellation state: executing task -> worker addr (set around
        # the push), and ids whose cancel was requested (suppresses the
        # crash-retry path when force-cancel kills the worker)
        self._task_worker: Dict[TaskID, Address] = {}
        self._cancel_requested: Set[TaskID] = set()
        # pull bookkeeping: which holder served each remote pull
        # (observability — the broadcast bench/tests assert peer-sourcing
        # with it; bounded, oldest evicted), and sources that recently
        # answered "busy" (sorted last on retry so fresh holders are
        # tried first; bounded by cluster size)
        self._pull_sources: "OrderedDict[ObjectID, Address]" = OrderedDict()
        self._busy_sources: Dict[Address, float] = {}
        # streaming-generator tasks owned here (ref: task_manager.h:143-171)
        self._streams: Dict[TaskID, _StreamState] = {}
        self._stream_lock = threading.Lock()

        # actor client state
        self._actor_addr: Dict[ActorID, Optional[Address]] = {}
        self._actor_state: Dict[ActorID, dict] = {}
        self._actor_seq: Dict[ActorID, int] = defaultdict(int)
        self._actor_events: Dict[ActorID, threading.Event] = {}
        self._actor_queues: Dict[ActorID, deque] = {}
        self._actor_sending: Dict[ActorID, bool] = {}

        # execution context (worker mode): thread-local so concurrent actor
        # threads get distinct put-id spaces (ref: TaskID-scoped put indices)
        self.current_task_id: TaskID = TaskID.for_driver(job_id)
        self._exec_ctx = _ExecCtxVar()
        self._put_index = 0
        self._put_lock = threading.Lock()
        self._fn_cache: Dict[bytes, Any] = {}
        self._exported: Set[bytes] = set()
        # weak identity cache fn-object -> fid (dead functions drop out)
        self._fid_by_obj: Any = weakref.WeakKeyDictionary()
        # (pg_id, bundle_index) -> nodelet addr; placement is static
        # after CREATED (invalidated on infeasible replies)
        self._pg_addr_cache: Dict[Tuple, Address] = {}
        self.default_runtime_env: Optional[dict] = None  # job-level env
        self._renv_cache: Dict[str, dict] = {}
        # Per-process telemetry agent: task events, spans, metric deltas
        # and edge observations batch into ONE GCS report per
        # telemetry_report_interval_s (ref: metrics_agent.py). Imported
        # lazily — observability.agent pulls in util.metrics which
        # imports this module.
        from ray_tpu.observability.agent import TelemetryAgent
        self.telemetry = TelemetryAgent(self)
        # Per-process flight recorder: bounded ring of recent task
        # events/spans/channel frames, dumped as a post-mortem on stall
        # detection, uncaught worker exceptions, or CollectiveError
        # (observability/flight.py; rendered by `cli blackbox`).
        from ray_tpu.observability.flight import FlightRecorder
        self.flight = FlightRecorder(self)
        # Memory attribution plane (observability/memory.py): per-object
        # ownership/pin/temperature records; snapshots ride the telemetry
        # report above. Same lazy-import rule as the agent.
        from ray_tpu.observability import memory as _memory
        _memory.set_enabled(bool(cfg.memory_attribution))
        self._memattr = _memory.tracker()
        self._worker_hex = self.worker_id.hex()[:12]
        if cfg.memory_attribution:
            # the reporter otherwise starts on the first task event — a
            # process that only put/get's would never ship its read-pin
            # and orphan records (empty reports are still skipped)
            self.telemetry.ensure_started()
        # compiled-DAG output sinks by id: channel_result frames from the
        # leaf workers land here (core/channels.py, dag/compiled.py)
        self._channel_sinks: Dict[str, Any] = {}
        self._gcs_subs: Set[str] = set()  # channels to restore on failover
        self._recon_lock = threading.Lock()  # serializes reconstructions
        self._gcs_sub_gen: Optional[int] = None  # conn generation at last sub
        self.address: Optional[RuntimeAddress] = None
        self._started = False
        self._shutdown = False

    # ------------------------------------------------------------------ boot

    def start(self) -> RuntimeAddress:
        host, port = self._run(self._start_server())
        self.address = RuntimeAddress(host, port, self.worker_id)
        self._started = True
        return self.address

    async def _start_server(self):
        return await self.server.start()

    def _run(self, coro, timeout: Optional[float] = None):
        """Bridge a coroutine onto the runtime loop from any thread."""
        try:
            if asyncio.get_running_loop() is self.loop:
                raise RuntimeError(
                    "Runtime blocking call issued from the event-loop thread; "
                    "this would deadlock — move the call to an executor thread")
        except RuntimeError as e:
            if "would deadlock" in str(e):
                raise
        if self.loop_thread is not None:
            return self.loop_thread.run(coro, timeout)
        # worker mode: called from executor threads, loop runs in main thread
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def _spawn(self, coro):
        if self._shutdown:
            # late GC callbacks (ref drops during teardown) must not
            # enqueue work a stopping loop will never run — an enqueued-
            # but-never-created task leaks an un-awaited coroutine
            coro.close()
            return
        try:
            if self.loop_thread is not None:
                self.loop_thread.spawn(coro)
            else:
                self.loop.call_soon_threadsafe(
                    lambda: self.loop.create_task(coro))
        except RuntimeError:
            coro.close()  # loop already shut down (late GC callbacks)

    def _self_addr(self) -> Optional[RuntimeAddress]:
        return self.address

    def shutdown(self):
        try:
            # final batched report BEFORE tearing the loop down — the
            # flush-on-shutdown half of the agent contract
            self.telemetry.stop(flush=True)
        except Exception:
            pass
        self._shutdown = True
        try:
            self._run(self.server.stop(), timeout=2)
        except Exception:
            pass
        if self.loop_thread:
            self.loop_thread.stop()
        try:
            self.store.close()
        except Exception:
            pass
        set_runtime(None)

    # ------------------------------------------------------------ gcs helpers

    def node_call(self, addr, method: str,
                  rpc_timeout: Optional[float] = 30.0, **kw):
        """Synchronous RPC to an arbitrary daemon (nodelet/worker) —
        observability fan-outs (`ray_tpu.stack()`, internal stats)."""
        return self._run(self.pool.get(tuple(addr)).call(
            method, timeout=rpc_timeout, **kw))

    def gcs_call(self, method: str, rpc_timeout: Optional[float] = 60.0,
                 clamp_attempt: bool = True, **kw):
        """kw may itself contain a `timeout` destined for the handler;
        `rpc_timeout` is the transport deadline. ``clamp_attempt=False``
        is for long-poll calls (wait_placement_group) whose handler
        legitimately blocks longer than a clamped attempt would allow.

        Retries across GCS restarts (ref: GcsClient auto-reconnect,
        _raylet.pyx:2111 _auto_reconnect) until gcs_reconnect_timeout_s.
        RpcTimeout rides the OSError family, so a gray-failed GCS (black-
        holed link, wedged handler) is retried like a lost connection and
        surfaces typed once the reconnect window closes. Jittered
        exponential backoff: every driver and worker hammers a restarting
        GCS at once, and fixed sleeps herd them into lockstep waves."""
        # lazy: ray_tpu.util's package init needs ray_tpu fully loaded,
        # and this module is imported during ray_tpu/__init__
        from ray_tpu.util.backoff import Backoff
        window = self.cfg.gcs_reconnect_timeout_s
        # Clamp the per-attempt transport deadline so a single lost
        # request frame (no connection error — just silence) can't burn
        # the whole reconnect window in one attempt: the loop gets at
        # least ~4 tries inside the window. GCS control-plane handlers
        # are idempotent by design, so re-sending after silence is safe.
        if clamp_attempt and rpc_timeout is not None:
            rpc_timeout = min(rpc_timeout, max(2.0, window / 4.0))
        bo = Backoff(base_s=0.1, cap_s=2.0,
                     deadline_s=time.time() + window)
        client = self.pool.get(self.gcs_addr)
        while True:
            try:
                out = self._run(client.call(method, timeout=rpc_timeout,
                                            **kw))
                # Resubscribe when the call ran over a NEWER connection
                # than the last subscribe batch — catches failovers that
                # happened while we were idle (the reconnect is silent;
                # ConnectionLost may never surface to any caller).
                if self._gcs_sub_gen is None:
                    self._gcs_sub_gen = client.generation
                elif client.generation != self._gcs_sub_gen:
                    self._gcs_sub_gen = client.generation
                    self._resubscribe_all()
                return out
            except (ConnectionLost, OSError):
                if self._shutdown or bo.expired():
                    raise
                time.sleep(bo.next_delay())

    def kv_put(self, ns: str, key: bytes, value: bytes, overwrite: bool = True) -> bool:
        return self.gcs_call("kv_put", ns=ns, key=key, value=value, overwrite=overwrite)

    def kv_get(self, ns: str, key: bytes) -> Optional[bytes]:
        return self.gcs_call("kv_get", ns=ns, key=key)

    # ---------------------------------------------------------------- objects

    def set_exec_context(self, task_id: TaskID,
                         runtime_env: Optional[dict] = None,
                         actor_id=None):
        # Nested submissions from inside this task inherit its env
        # (ref: runtime_env inheritance parent → child). actor_id rides
        # along so get_runtime_context().get_actor_id() works per LANE
        # thread — lane-packed actors share one process, so process
        # identity no longer identifies the actor.
        self._exec_ctx._replace({"task_id": task_id, "put_index": 0,
                                 "runtime_env": runtime_env,
                                 "actor_id": actor_id})

    def clear_exec_context(self):
        self._exec_ctx._replace({})

    def get_current_task_id(self) -> TaskID:
        tid = getattr(self._exec_ctx, "task_id", None)
        return tid if tid is not None else self.current_task_id

    def _next_put_id(self) -> ObjectID:
        tid = getattr(self._exec_ctx, "task_id", None)
        if tid is not None:
            self._exec_ctx.put_index += 1
            return ObjectID.for_put(tid, self._exec_ctx.put_index)
        with self._put_lock:
            self._put_index += 1
            return ObjectID.for_put(self.current_task_id, self._put_index)

    def _entry(self, oid: ObjectID) -> _ObjectEntry:
        with self._dir_lock:
            e = self.directory.get(oid)
            if e is None:
                e = self.directory[oid] = _ObjectEntry()
            return e

    def object_nbytes(self, ref: ObjectRef) -> Optional[int]:
        """Stored size of a READY object known to this runtime, else
        None — no fetch, no RPC (the data streaming executor budgets
        queued operator outputs from owner-side directory sizes)."""
        with self._dir_lock:
            e = self.directory.get(ref.id)
        if e is None or e.state != "ready":
            return None
        if e.size:
            return int(e.size)
        if e.inline is not None:
            return len(e.inline)
        return None

    def put(self, value: Any, _pin: bool = True) -> ObjectRef:
        """ref: CoreWorker::Put core_worker.cc:1119 — plus the HBM tier:
        a device array skips serialization entirely (no D2H, no shm
        write); same-process get returns the identical jax.Array, and
        _stage_device_object demotes it to shm only when a remote
        consumer or HBM pressure demands host bytes."""
        from ray_tpu.core.device_store import try_device_snapshot

        if self.cfg.device_object_tier:
            snap = try_device_snapshot(
                value, self.cfg.max_direct_call_object_size)
            if snap is not None:
                value, nbytes = snap   # fresh containers, shared buffers
                oid = self._next_put_id()
                e = self._entry(oid)
                self.refs.register_owned(oid)
                e.size = self.device_store.put(oid, value, nbytes)
                self.memory_store.put(oid, value)
                e.state = "ready"
                self._complete_entry(e)
                self._enforce_device_capacity()
                return ObjectRef(oid, self.address)
        oid = self._next_put_id()
        meta, bufs = serialization.serialize(value)
        size = serialization.serialized_size(meta, bufs)
        e = self._entry(oid)
        self.refs.register_owned(oid)
        if size <= self.cfg.max_direct_call_object_size:
            packed = bytearray(size)
            serialization.write_to(memoryview(packed), meta, bufs)
            e.inline = bytes(packed)
            self.memory_store.put(oid, value)
        else:
            view = self._create_view_with_spill(oid, size)
            if view is None:
                if not self.store.contains(oid):
                    from ray_tpu.core.status import ObjectStoreFullError

                    raise ObjectStoreFullError(f"cannot store {size} bytes")
            else:
                serialization.write_to(view, meta, bufs)
                del view
                self.store.seal(oid)
            self._attribute_put(oid, size)
            if _pin:
                self._pin_primary(oid)
            e.locations.add(self.nodelet_addr)
            e.primaries.add(self.nodelet_addr)
            e.size = size
        e.state = "ready"
        self._complete_entry(e)
        return ObjectRef(oid, self.address)

    def _attribute_put(self, oid: ObjectID, size: int):
        """Attribution record for a store-resident object this process
        just wrote: default holder "user" (subsystems retag their own),
        owner worker + creating task for the memory_report() lineage."""
        tid = getattr(self._exec_ctx, "task_id", None)
        self._memattr.attribute(
            oid, "user", size, owner=self._worker_hex,
            task=tid.hex()[:16] if hasattr(tid, "hex") else None)

    def put_batch(self, values: Sequence[Any]) -> List[ObjectRef]:
        """Batched put(): serialize every value into the store first, then
        pin the whole wave with ONE nodelet RPC instead of one blocking
        pin round-trip per object (the collective zero-copy transport
        puts pipeline_chunks sub-chunk objects per ring step). Values
        that take the device-tier or inline path fall back to put()
        per-value — there is no pin RPC to batch on those paths."""
        from ray_tpu.core.device_store import try_device_snapshot

        refs: List[ObjectRef] = []
        pend: List[tuple] = []       # (oid, seal->pin guard view)
        try:
            for value in values:
                if self.cfg.device_object_tier and try_device_snapshot(
                        value, self.cfg.max_direct_call_object_size) is not None:
                    refs.append(self.put(value))
                    continue
                oid = self._next_put_id()
                meta, bufs = serialization.serialize(value)
                size = serialization.serialized_size(meta, bufs)
                e = self._entry(oid)
                self.refs.register_owned(oid)
                if size <= self.cfg.max_direct_call_object_size:
                    packed = bytearray(size)
                    serialization.write_to(memoryview(packed), meta, bufs)
                    e.inline = bytes(packed)
                    self.memory_store.put(oid, value)
                else:
                    view = self._create_view_with_spill(oid, size)
                    if view is None:
                        if not self.store.contains(oid):
                            from ray_tpu.core.status import ObjectStoreFullError

                            raise ObjectStoreFullError(
                                f"cannot store {size} bytes")
                    else:
                        serialization.write_to(view, meta, bufs)
                        del view
                        self.store.seal(oid)
                    self._attribute_put(oid, size)
                    pend.append((oid, self.store.get_view(oid)))
                    e.locations.add(self.nodelet_addr)
                    e.primaries.add(self.nodelet_addr)
                    e.size = size
                e.state = "ready"
                self._complete_entry(e)
                refs.append(ObjectRef(oid, self.address))
            if pend:
                try:
                    self._run(self.pool.get(self.nodelet_addr).call(
                        "pin_objects", oids=[oid for oid, _ in pend],
                        timeout=60.0))
                    for oid, _ in pend:
                        self._memattr.pin(oid, "primary")
                except (ConnectionLost, RemoteError, OSError) as err:
                    logger.warning("pin_objects(%d) failed: %s",
                                   len(pend), err)
        finally:
            for oid, guard in pend:
                if guard is not None:
                    del guard
                    self.store.release(oid)
        return refs

    def _pin_primary(self, oid: ObjectID):
        """Ask the nodelet to pin the primary copy (ref: raylet
        PinObjectIDs). A guard pin bridges the seal→nodelet-pin window so
        eviction cannot race the handoff."""
        guard = self.store.get_view(oid)
        try:
            self._run(self.pool.get(self.nodelet_addr).call(
                "pin_object", oid=oid, timeout=30.0))
            self._memattr.pin(oid, "primary")
        except (ConnectionLost, RemoteError, OSError) as e:
            logger.warning("pin_object(%s) failed: %s", oid.hex()[:12], e)
        finally:
            if guard is not None:
                del guard
                self.store.release(oid)

    def _create_view_with_spill(self, oid: ObjectID, size: int):
        """create_view, asking the nodelet to spill for room on failure
        (ref: local_object_manager spill-on-pressure — the nodelet may
        spill even pinned primaries, since it owns those pins)."""
        view = self.store.create_view(oid, size)
        if view is not None or self.store.contains(oid):
            return view
        for _ in range(3):
            try:
                r = self._run(self.pool.get(self.nodelet_addr).call(
                    "free_space", need_bytes=size, timeout=60.0))
            except (ConnectionLost, RemoteError, OSError) as e:
                logger.warning("free_space failed: %s", e)
                return None
            view = self.store.create_view(oid, size)
            if view is not None or self.store.contains(oid):
                return view
            if r.get("freed", 0) <= 0:
                return None  # nothing left to spill; store genuinely full
        return None

    # --- HBM device tier (core/device_store.py; SURVEY §7 step 2) -----------

    def _stage_device_object(self, oid: ObjectID, drop: bool = False) -> bool:
        """Demote a device-tier object to the host shm tier: D2H +
        serialize + seal + pin, then advertise this node as a location —
        from here the existing transfer/spill machinery applies. With
        drop=True the device copy is released (pressure spill); without,
        the device copy stays the same-process fast path. Returns False
        only if the shm store cannot hold the bytes."""
        with self._stage_lock:
            arr = self.device_store.get(oid)
            if arr is None:
                return self.store.contains(oid)
            from ray_tpu.core.device_store import any_leaf_deleted

            e = self._entry(oid)
            if any_leaf_deleted(arr):
                # the user donated the live buffer without take(): the
                # bytes are unrecoverable. Mark lost (an explicit error
                # on get) instead of letting the deleted-array raise
                # escape from an unrelated put()'s capacity sweep.
                self.device_store.delete(oid)
                self.memory_store.delete(oid)
                e.state = "lost"
                logger.warning(
                    "device object %s was deleted under the tier "
                    "(donated without take()?) — marked lost",
                    oid.hex()[:12])
                return False
            if not self.store.contains(oid):
                try:
                    meta, bufs = serialization.serialize(arr)  # the D2H copy
                except Exception:   # deletion raced the check above
                    self.device_store.delete(oid)
                    self.memory_store.delete(oid)
                    e.state = "lost"
                    return False
                size = serialization.serialized_size(meta, bufs)
                view = self._create_view_with_spill(oid, size)
                if view is None and not self.store.contains(oid):
                    return False
                if view is not None:
                    serialization.write_to(view, meta, bufs)
                    del view
                    self.store.seal(oid)
                self._attribute_put(oid, size)
                self._pin_primary(oid)
                with self._dir_lock:
                    e.locations.add(self.nodelet_addr)
                    e.primaries.add(self.nodelet_addr)
                e.size = size
            if drop:
                self.device_store.delete(oid)
                self.memory_store.delete(oid)
            return True

    def _enforce_device_capacity(self):
        """HBM watermark: stage LRU device objects down to shm until the
        tier fits its budget (the shm tier then spills to disk under its
        own watermarks — the full HBM->host->disk chain)."""
        over = self.device_store.over_capacity()
        if over <= 0:
            return
        for victim in self.device_store.victims(over):
            if not self._stage_device_object(victim, drop=True):
                logger.warning(
                    "device tier over budget but shm cannot absorb %s",
                    victim.hex()[:12])

    def take(self, ref: ObjectRef):
        """Donation-aware get (train/serve hot path): returns the device
        array AND withdraws it from the object tiers, transferring buffer
        ownership to the caller — safe to donate into a jit without
        invalidating a stored copy behind other readers' backs. Only the
        owner may take, and the ref must still be device-resident.
        Subsequent gets raise ObjectLostError (put objects have no
        lineage)."""
        oid = ref.id
        if not self.refs.is_owned(oid):
            raise ValueError("take() requires the owning process "
                             "(borrowers hold host copies)")
        arr = self.device_store.get(oid)
        if arr is None:
            raise ValueError(
                f"object {oid.hex()[:12]} is not device-resident "
                "(already staged, spilled, or not a device put)")
        self.device_store.delete(oid)
        self.memory_store.delete(oid)
        e = self._entry(oid)
        e.state = "lost"
        return arr

    def _free_borrow_caches(self, oid: ObjectID):
        """Last local borrow of a remote-owned object died: drop OUR
        caches only (the owner's copy is none of our business)."""
        self.memory_store.delete(oid)
        self._pinned.pop(oid, None)
        # attribution: our local record dies with the borrow (a live
        # _ReadPin keeps it visible as an orphan — the leak signature)
        self._memattr.owner_ref_dead(oid)

    def _free_object(self, oid: ObjectID):
        """All refs gone: drop every copy (ref: ReferenceCounter on-zero →
        delete from plasma + local memory store; lineage released)."""
        self.memory_store.delete(oid)
        self.device_store.delete(oid)
        # NOT store.release here: live zero-copy values hold their own
        # pin via _ReadPin and release when the last one dies
        self._pinned.pop(oid, None)
        # the delete below drops the nodelet's primary pin; a record that
        # keeps OTHER pins past this point (a still-alive zero-copy view,
        # an unacked collective chunk) becomes a leak-suspect orphan
        self._memattr.unpin(oid, "primary")
        self._memattr.owner_ref_dead(oid)
        with self._dir_lock:
            e = self.directory.pop(oid, None)
        if e is not None and e._locations:
            for addr in e._locations:
                self._spawn(self._delete_remote(addr, [oid]))

    async def _delete_remote(self, addr: Address, oids: List[ObjectID]):
        try:
            await self.pool.get(addr).call("delete_objects", oids=oids, timeout=5.0)
        except Exception:
            pass

    def _notify_owner(self, owner: RuntimeAddress, op: str, oid: ObjectID):
        async def _send():
            try:
                await self.pool.get(owner.addr).call(
                    op, oid=oid, borrower_id=self.worker_id, timeout=5.0)
            except Exception:
                pass
        self._spawn(_send())

    # --- get ----------------------------------------------------------------

    def get(self, refs: Sequence[ObjectRef], timeout: Optional[float] = None) -> List[Any]:
        """ref: CoreWorker::Get core_worker.cc:1331."""
        deadline = None if timeout is None else time.time() + timeout
        depth = getattr(self._exec_ctx, "get_depth", 0)
        self._exec_ctx.get_depth = depth + 1
        try:
            if len(refs) > 1:
                self._prefetch_borrowed(refs, deadline)
            return [self._get_one(r, deadline) for r in refs]
        finally:
            self._exec_ctx.get_depth = depth
            if depth == 0:
                self._end_block()

    def _prefetch_borrowed(self, refs: Sequence[ObjectRef],
                           deadline: Optional[float]):
        """Batch resolution of borrowed refs: ONE wait_objects RPC per
        distinct owner instead of a serial wait_object round-trip per ref
        (a task taking N object args would otherwise pay N round-trips —
        ref: the plasma provider's batched GetObjects). Inline results are
        cached into the memory store; everything else falls back to the
        ordinary per-ref path, which this pass only warms."""
        groups: Dict[Address, List[ObjectID]] = {}
        for r in refs:
            oid = r.id
            if self.refs.is_owned(oid) or self.memory_store.contains(oid) \
                    or self.store.contains(oid):
                continue
            groups.setdefault(tuple(r.owner.addr), []).append(oid)
        if not groups:
            return
        rem = self._remaining(deadline)
        step = min(rem, 30.0) if rem is not None else 30.0
        self._ensure_blocked()

        async def _bulk():
            async def one(addr, oids):
                try:
                    return await self.pool.get(addr).call(
                        "wait_objects", oids=oids, wait_timeout=step,
                        timeout=step + 10.0)
                except Exception:
                    return None
            return await asyncio.gather(
                *(one(a, oids) for a, oids in groups.items()))

        try:
            replies = self._run(_bulk(), timeout=step + 15.0)
        except Exception:
            return   # warming only; the per-ref path is authoritative
        for (addr, oids), reply in zip(groups.items(), replies):
            if not reply:
                continue
            for oid, r in zip(oids, reply["results"]):
                if r.get("status") == "ready" and r.get("inline") is not None:
                    try:
                        self.memory_store.put(
                            oid, serialization.unpack(r["inline"]))
                    except Exception:
                        pass

    def _ensure_blocked(self):
        """Called LAZILY from the wait paths, just before the first
        actual block: a worker blocking in get() releases its lease's
        resources so the tasks it waits on can schedule — without this a
        fleet of getters deadlocks the cluster (ref:
        NotifyDirectCallTaskBlocked). Gets that resolve locally never
        notify, keeping the hot path RPC-free."""
        if self.mode != "worker" \
                or getattr(self._exec_ctx, "task_id", None) is None \
                or getattr(self._exec_ctx, "block_notified", False):
            return
        self._exec_ctx.block_notified = True
        try:
            self.node_call(self.nodelet_addr, "worker_blocked",
                           worker_id=self.worker_id, rpc_timeout=5.0)
        except Exception:
            pass

    def _end_block(self):
        if not getattr(self._exec_ctx, "block_notified", False):
            return
        self._exec_ctx.block_notified = False
        try:
            self.node_call(self.nodelet_addr, "worker_unblocked",
                           worker_id=self.worker_id, rpc_timeout=5.0)
        except Exception:
            pass

    def _remaining(self, deadline: Optional[float]) -> Optional[float]:
        if deadline is None:
            return None
        rem = deadline - time.time()
        if rem <= 0:
            raise GetTimeoutError("ray_tpu.get timed out")
        return rem

    def _get_one(self, ref: ObjectRef, deadline: Optional[float], _depth: int = 0) -> Any:
        oid = ref.id
        # 1. in-process memory store
        v = self.memory_store.get_if_exists(oid)
        if v is not _MISSING:
            if isinstance(v, serialization.SerializedException):
                raise v.to_exception()
            return v
        # 2. local shm store (pin + zero-copy)
        if self.store.contains(oid):
            val = self._read_local(oid)
            if val is not _MISSING:
                return val
        if self.refs.is_owned(oid) or (self.address is not None
                                       and ref.owner.worker_id == self.worker_id):
            return self._get_owned(ref, deadline, _depth)
        return self._get_borrowed(ref, deadline, _depth)

    def _read_local(self, oid: ObjectID):
        wr = self._pinned.get(oid)
        pin = wr() if wr is not None else None
        if pin is not None and pin._view is None:
            # CPython runs tp_finalize (__del__) BEFORE clearing weakrefs,
            # so a concurrent final-deref can let wr() resurrect a pin
            # whose __del__ already ran: _view is gone and the store pin
            # released. Such a zombie must not serve reads — re-pin.
            pin = None
        if pin is None:
            view = self.store.get_view(oid)   # +1 store refcount
            if view is None:
                self._pinned.pop(oid, None)
                return _MISSING
            pin = _ReadPin(self.store, oid, view)
            self._pinned[oid] = weakref.ref(
                pin, lambda r, oid=oid: (
                    self._pinned.pop(oid, None)
                    if self._pinned.get(oid) is r else None))
            # attribute-if-missing covers copies this process did not
            # write (borrowed pulls landed by the nodelet); then count
            # the zero-copy reader against the record
            self._memattr.attribute(oid, "user", len(view),
                                    owner=self._worker_hex, copy="read")
            self._memattr.pin(oid, "read")
        else:
            self._memattr.touch(oid)   # temperature on the pinned fast path
        # values deserialize out of the pin's buffer: their buffer chains
        # keep the pin (and thus the store region) alive
        value = serialization.read_from(pin.buffer())
        if isinstance(value, serialization.SerializedException):
            raise value.to_exception()
        return value

    def _get_owned(self, ref: ObjectRef, deadline: Optional[float], _depth: int) -> Any:
        oid = ref.id
        e = self._entry(oid)
        if not e.event.is_set():
            self._ensure_blocked()
        while True:
            rem = self._remaining(deadline)
            if not e.event.wait(timeout=rem if rem is not None else 1.0):
                if rem is not None:
                    raise GetTimeoutError(f"object {oid.hex()[:12]} not ready in time")
                continue
            break
        if e.state == "error":
            raise e.error.to_exception()
        if e.state == "lost":
            return self._try_reconstruct(ref, deadline, _depth)
        v = self.memory_store.get_if_exists(oid)
        if v is not _MISSING:
            if isinstance(v, serialization.SerializedException):
                raise v.to_exception()
            return v
        if e.inline is not None:
            return serialization.unpack(e.inline)
        # value lives in some node store (snapshot under the lock:
        # puller registrations mutate the set concurrently)
        busy_rounds = 0
        while True:
            with self._dir_lock:
                locs = list(e._locations or ())
            val = self._fetch_from_locations(oid, locs, owner=self.address)
            if val is not _BUSY:
                break
            # every holder is at its serve cap: back off (escalating, so
            # a wedged source is not hammered with 20 connects/s) until a
            # slot frees or a new copy registers. _remaining raises
            # GetTimeoutError at the get deadline.
            rem = self._remaining(deadline)
            delay = min(0.5, 0.05 * (1 << min(busy_rounds, 4)))
            busy_rounds += 1
            time.sleep(min(delay, rem) if rem is not None else delay)
        if val is _MISSING:
            return self._try_reconstruct(ref, deadline, _depth)
        return val

    def _get_borrowed(self, ref: ObjectRef, deadline: Optional[float], _depth: int) -> Any:
        oid = ref.id
        owner = ref.owner
        # Local-store fast path: a sealed copy on this node is immutable
        # and valid regardless of owner state — read it with zero owner
        # RPCs. This is the hot case for same-node task fan-outs (50
        # borrowers of one driver-put arg would otherwise each queue a
        # wait_object round-trip behind the owner's busy submission loop;
        # measured 46/s -> owner-RPC-free). ref: plasma borrowers read
        # shm directly, only missing objects consult the directory.
        if self.store.contains(oid):
            val = self._read_local(oid)
            if val is not _MISSING:
                return val
        self._ensure_blocked()
        busy_rounds = 0
        while True:
            rem = self._remaining(deadline)
            step = min(rem, 5.0) if rem is not None else 5.0
            try:
                r = self._run(self.pool.get(owner.addr).call(
                    "wait_object", oid=oid, wait_timeout=step, timeout=step + 10.0), timeout=step + 15.0)
            except (ConnectionLost, RemoteError, OSError, TimeoutError) as err:
                raise ObjectLostError(
                    f"owner of {oid.hex()[:12]} unreachable: {err}") from None
            status = r["status"]
            if status == "pending":
                continue
            if status == "error":
                raise r["error"].to_exception()
            if status == "lost":
                raise ObjectLostError(f"object {oid.hex()[:12]} lost at owner")
            if r.get("inline") is not None:
                return serialization.unpack(r["inline"])
            locs = [tuple(a) for a in r["locations"]]
            val = self._fetch_from_locations(oid, locs, owner=owner)
            if val is _BUSY:
                # all holders at their serve cap: re-poll the owner —
                # the refreshed location set includes any copy a winning
                # puller registered meanwhile (the distribution tree).
                # Escalating backoff; the loop-top _remaining raises at
                # the get deadline.
                time.sleep(min(0.5, 0.05 * (1 << min(busy_rounds, 4))))
                busy_rounds += 1
                continue
            if val is _MISSING:
                # Every advertised copy is gone (their nodes died). Tell
                # the owner so it prunes the locations and re-executes
                # lineage; then retry the wait — bounded by the get
                # deadline (ref: borrower pull failures feeding
                # ObjectRecoveryManager).
                try:
                    rr = self._run(self.pool.get(owner.addr).call(
                        "recover_object", oid=oid, dead_locations=locs,
                        timeout=10.0), timeout=15.0)
                except (ConnectionLost, RemoteError, OSError,
                        TimeoutError) as err:
                    raise ObjectLostError(
                        f"owner of {oid.hex()[:12]} unreachable during "
                        f"recovery: {err}") from None
                if rr["status"] == "unrecoverable":
                    raise ObjectLostError(
                        f"object {oid.hex()[:12]} lost and not "
                        "reconstructable")
                continue  # owner is reconstructing (or has other copies)
            return val

    def _fetch_from_locations(self, oid: ObjectID, locations: List[Address],
                              owner: Optional[RuntimeAddress] = None):
        if self.store.contains(oid):
            v = self._read_local(oid)
            if v is not _MISSING:
                return v
        # Local first (may only need a spill restore); REMOTE sources are
        # shuffled so a fan-in of pullers spreads across every node that
        # already holds a copy instead of hammering the producer — with
        # copy registration below, a broadcast forms an emergent
        # distribution tree (ref: object manager location updates let
        # pulled copies serve later pulls). Sources that just answered
        # "busy" (serve cap, nodelet rpc_pull_object) sort last, so a
        # retry reaches fresh holders FIRST — that is what lets the tree
        # form within a single concurrent fan-in instead of only across
        # sequential waves.
        local = [a for a in locations if tuple(a) == self.nodelet_addr]
        remote = [a for a in locations if tuple(a) != self.nodelet_addr]
        random.shuffle(remote)
        now = time.time()
        remote.sort(key=lambda a: self._busy_sources.get(tuple(a), 0.0) > now)
        busy_seen = False
        for loc in local + remote:
            t0 = time.perf_counter()
            try:
                r = self._run(self.pool.get(self.nodelet_addr).call(
                    "pull_object", oid=oid, source=tuple(loc), timeout=120.0))
            except (ConnectionLost, RemoteError, OSError) as e:
                logger.warning("pull of %s failed: %s", oid.hex()[:12], e)
                continue
            pull_s = time.perf_counter() - t0
            if r.get("ok"):
                v = self._read_local(oid)
                if v is not _MISSING:
                    if tuple(loc) != self.nodelet_addr:
                        self._register_copy(oid, owner)
                        self._pull_sources[oid] = tuple(loc)
                        while len(self._pull_sources) > 1024:
                            self._pull_sources.popitem(last=False)
                        if r.get("nbytes"):
                            # an actual cross-node transfer happened (the
                            # nodelet omits nbytes on already-local hits)
                            self._record_pull_edge(loc, r["nbytes"], pull_s)
                    return v
            elif r.get("busy"):
                busy_seen = True
                self._busy_sources[tuple(loc)] = now + 3.0
            elif tuple(loc) != self.nodelet_addr \
                    and "not at source" in str(r.get("error", "")):
                # definitively evicted there (NOT a transient source
                # error or local store pressure) — have the owner drop
                # the stale location (primaries are pinned and never
                # pruned this way)
                self._notify_drop_location(oid, tuple(loc), owner)
        # one more local attempt (producer may be co-located)
        v = self._read_local(oid)
        if v is _MISSING and busy_seen:
            # every reachable copy is at its serve cap: signal "retry
            # with refreshed locations", NOT "lost" — a busy source must
            # never trigger recovery/reconstruction
            return _BUSY
        return v

    def _fire_and_forget(self, to_addr: Address, op: str, **kw):
        async def _send():
            try:
                await self.pool.get(tuple(to_addr)).call(op, timeout=10.0,
                                                         **kw)
            except Exception:
                pass
        self._spawn(_send())

    def _register_copy(self, oid: ObjectID, owner: Optional[RuntimeAddress]):
        """Tell the owner this node now holds a copy, so later pullers
        can fetch from here (fire-and-forget)."""
        if owner is None or owner.addr == self.address.addr:
            self._add_location_locked(oid, tuple(self.nodelet_addr))
            return
        self._fire_and_forget(owner.addr, "add_location", oid=oid,
                              addr=self.nodelet_addr)

    def _add_location_locked(self, oid: ObjectID, addr: Address):
        """Register only onto a live, ready entry (a freed/reset entry
        must not be resurrected), under the directory lock — other
        threads iterate e.locations (e.g. _locality_target)."""
        with self._dir_lock:
            e = self.directory.get(oid)
            if e is not None and e.state == "ready":
                e.locations.add(tuple(addr))

    def _notify_drop_location(self, oid: ObjectID, addr: Address,
                              owner: Optional[RuntimeAddress]):
        if owner is None or owner.addr == self.address.addr:
            self._drop_location_locked(oid, addr)
            return
        self._fire_and_forget(owner.addr, "drop_location", oid=oid,
                              addr=addr)

    def _drop_location_locked(self, oid: ObjectID, addr: Address):
        with self._dir_lock:
            e = self.directory.get(oid)
            if e is not None and tuple(addr) not in e.primaries:
                e.locations.discard(tuple(addr))

    def _reset_and_resubmit(self, spec: TaskSpec) -> bool:
        """Atomically flip the producing task's returns to pending and
        resubmit — shared by owner-side and borrower-triggered recovery.
        Returns False when another thread already has a reconstruction in
        flight (check-then-submit must be one critical section or the two
        paths double-execute and double-decrement arg refcounts)."""
        with self._recon_lock:
            rids = spec.return_ids()
            if spec.is_streaming:
                # re-execution re-yields every item; only LOST entries are
                # reset (live copies elsewhere must not be clobbered —
                # rpc_stream_item skips complete entries)
                if spec.task_id in self._inflight:
                    return False    # a re-execution is already running
                st = self._streams.get(spec.task_id)
                if st is None:
                    # generator handle was GC'd and its state dropped:
                    # revive an abandoned state so re-reported items are
                    # accepted (and nothing backpressures — no consumer)
                    st = self._streams[spec.task_id] = _StreamState()
                    st.abandoned = True
                hi = max(st.produced, st.total or 0)
                if hi == 0:
                    # state was revived: recover the watermark from the
                    # directory (item entries outlive the stream state)
                    with self._dir_lock:
                        while ObjectID.for_return(
                                spec.task_id, hi + 1) in self.directory:
                            hi += 1
                    st.produced = hi
                rids = [ObjectID.for_return(spec.task_id, i + 1)
                        for i in range(hi)]
                rids = [r for r in rids if self._entry(r).state == "lost"]
            entries = [self._entry(rid) for rid in rids]
            if not spec.is_streaming \
                    and any(en.state == "pending" for en in entries):
                return False
            for rid, re_ in zip(rids, entries):
                re_.state = "pending"
                re_.inline = None
                re_.locations = set()
                re_.primaries = set()
                re_.event.clear()
                self.refs.register_owned(rid)
        self._submit_spec(spec, retries_left=spec.max_retries)
        return True

    def _try_reconstruct(self, ref: ObjectRef, deadline: Optional[float], _depth: int) -> Any:
        """Lineage reconstruction (ref: object_recovery_manager.h — re-execute
        the producing task)."""
        oid = ref.id
        e = self._entry(oid)
        if e.spec is None or _depth > 10:
            raise ObjectLostError(
                f"object {oid.hex()[:12]} lost and not reconstructable")
        logger.warning("reconstructing %s via lineage", oid.hex()[:12])
        self._reset_and_resubmit(e.spec)
        return self._get_one(ref, deadline, _depth + 1)

    # --- wait ---------------------------------------------------------------
    # Event-driven (ref: src/ray/raylet/wait_manager.h): completions
    # notify registered waiters; nothing polls. Owned refs subscribe to
    # their directory entry in-process (zero RPCs); borrowed refs get one
    # long-lived wait_object watcher coroutine at the owner instead of a
    # locate RPC per 5 ms tick.

    def _entry_subscribe(self, e: _ObjectEntry, cb) -> bool:
        """Register a persistent completion callback. Returns True when
        the entry is already complete (callers must then check state
        themselves — the callback is NOT invoked retroactively)."""
        with self._dir_lock:
            e.waiters.append(cb)
        return e.event.is_set()

    def _entry_unsubscribe(self, e: _ObjectEntry, cb):
        with self._dir_lock:
            try:
                e.waiters.remove(cb)
            except ValueError:
                pass

    def _complete_entry(self, e: _ObjectEntry):
        """Single completion choke point: set the threading event for
        blocking getters, then fire waiter callbacks (outside the lock —
        callbacks may re-enter runtime methods)."""
        e.event.set()
        with self._dir_lock:
            waiters = list(e._waiters or ())
        for cb in waiters:
            try:
                cb()
            except Exception:
                pass

    async def _await_entry(self, e: _ObjectEntry,
                           timeout: Optional[float] = None) -> bool:
        """Await entry completion on the runtime loop without burning an
        executor thread. Returns completion status at exit."""
        if e.event.is_set():
            return True
        fut = self.loop.create_future()

        def _cb():
            try:
                self.loop.call_soon_threadsafe(
                    lambda: fut.done() or fut.set_result(None))
            except RuntimeError:
                pass  # loop shut down

        already = self._entry_subscribe(e, _cb)
        try:
            if already:
                return True
            try:
                await asyncio.wait_for(fut, timeout)
            except asyncio.TimeoutError:
                pass
            return e.event.is_set()
        finally:
            self._entry_unsubscribe(e, _cb)

    def wait(self, refs: Sequence[ObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        """ref: worker.py:2582 / CoreWorker::Wait + raylet wait_manager.h."""
        # Blocking on kick.wait from the loop thread would freeze the very
        # loop that delivers completions — fail loudly, like _run does.
        try:
            on_loop = asyncio.get_running_loop() is self.loop
        except RuntimeError:
            on_loop = False
        if on_loop:
            raise RuntimeError(
                "Runtime.wait called from the event-loop thread; this "
                "would deadlock — move the call to an executor thread")
        deadline = None if timeout is None else time.time() + timeout
        kick = threading.Event()
        completed: Set[bytes] = set()   # oid.binary set by notifications
        stop = [False]                  # watcher stop flag (closure cell)
        subs: List[Tuple[_ObjectEntry, Any]] = []
        watchers: List[Any] = []
        ready: List[ObjectRef] = []
        pending: List[ObjectRef] = []

        def _owned_ready(e: _ObjectEntry) -> bool:
            # "lost" completes the event but is not claimable by wait()
            return e.event.is_set() and e.state in ("ready", "error")

        async def _watch_borrowed(ref: ObjectRef):
            """One watcher per borrowed pending ref: park in the owner's
            wait_object (server blocks up to wait_timeout per call) until
            a claimable status arrives."""
            oid = ref.id
            while not stop[0] and not self._shutdown:
                try:
                    r = await self.pool.get(ref.owner.addr).call(
                        "wait_object", oid=oid, wait_timeout=5.0,
                        timeout=15.0)
                except Exception:
                    await asyncio.sleep(1.0)   # owner unreachable: retry
                    continue
                if r["status"] in ("ready", "error"):
                    completed.add(oid.binary())
                    kick.set()
                    return
                if r["status"] == "lost":
                    # may be revived by lineage reconstruction — keep
                    # watching, but don't hot-loop on a terminal state
                    await asyncio.sleep(1.0)

        for r in refs:
            oid = r.id
            if self.memory_store.contains(oid) or self.store.contains(oid):
                ready.append(r)
                continue
            if self.refs.is_owned(oid):
                e = self._entry(oid)
                cb = (lambda b=oid.binary(): (completed.add(b), kick.set()))
                self._entry_subscribe(e, cb)
                subs.append((e, cb))
                if _owned_ready(e):
                    ready.append(r)
                    continue
            else:
                watchers.append(asyncio.run_coroutine_threadsafe(
                    _watch_borrowed(r), self.loop))
            pending.append(r)

        try:
            while len(ready) < num_returns and pending:
                kick.clear()
                if completed:
                    done_now = set(completed)
                    still = []
                    for r in pending:
                        if r.id.binary() in done_now:
                            claim = (_owned_ready(self._entry(r.id))
                                     if self.refs.is_owned(r.id) else True)
                            if claim:
                                ready.append(r)
                                continue
                            completed.discard(r.id.binary())
                        still.append(r)
                    pending = still
                if len(ready) >= num_returns or not pending:
                    break
                rem = None if deadline is None else deadline - time.time()
                if rem is not None and rem <= 0:
                    break
                kick.wait(rem)
        finally:
            stop[0] = True
            for e, cb in subs:
                self._entry_unsubscribe(e, cb)
            for w in watchers:
                w.cancel()
        return ready, pending

    # ------------------------------------------------------ function shipping

    def export_function(self, fn: Any) -> bytes:
        """ref: function_manager.py:61 — pickled code via GCS KV, lazy
        import. The identity fast path skips re-pickling on every .remote()
        of the same function object (pickling dominated submission cost);
        a re-DEFINED function is a different object and re-exports."""
        try:
            fid = self._fid_by_obj.get(fn)
        except TypeError:
            fid = None   # unhashable / non-weakrefable callable
        if fid is not None:
            return fid
        blob = _dumps_function(fn)
        fid = hashlib.sha1(blob).digest()
        if fid not in self._exported:
            self.kv_put("fn", fid, blob, overwrite=False)
            self._exported.add(fid)
            self._fn_cache[fid] = fn
        try:
            self._fid_by_obj[fn] = fid
        except TypeError:
            pass   # unhashable callable: no fast path
        return fid

    def load_function(self, fid: bytes) -> Any:
        fn = self._fn_cache.get(fid)
        if fn is None:
            blob = self.kv_get("fn", fid)
            if blob is None:
                raise RuntimeError(f"function {fid.hex()[:12]} not found in GCS")
            fn = cloudpickle.loads(blob)
            self._fn_cache[fid] = fn
        return fn

    # ------------------------------------------------------- task submission

    def resolve_runtime_env(self, env: Optional[dict]) -> Optional[dict]:
        """Validate + upload local dirs → package URIs, memoized by spec
        (ref: runtime_env packaging at task submission)."""
        from ray_tpu import runtime_env as renv

        base = getattr(self._exec_ctx, "runtime_env", None) \
            or self.default_runtime_env
        if env is not None and base:
            # Task-level overrides job-level per field; env_vars deep-merge
            # with task keys winning (ref: runtime_env merge semantics).
            merged = {**base, **env}
            for field in ("env_vars", "process_env_vars"):
                if field in base or field in env:
                    merged[field] = {**base.get(field, {}),
                                     **env.get(field, {})}
        else:
            merged = env if env is not None else base
        if not merged:
            return None
        key = renv.to_json(merged)
        cached = self._renv_cache.get(key)
        if cached is None:
            cached = self._renv_cache[key] = renv.resolve_uris(self, merged)
        return cached

    def submit_task(self, fn: Callable, args: tuple, kwargs: dict, *,
                    name: str = "", num_returns: int = 1,
                    resources: Optional[ResourceSet] = None,
                    max_retries: Optional[int] = None,
                    retry_exceptions: bool = False,
                    scheduling: Optional[SchedulingStrategy] = None,
                    runtime_env: Optional[dict] = None,
                    generator_backpressure: Optional[int] = None,
                    generator_backpressure_bytes: Optional[int] = None
                    ) -> List[ObjectRef]:
        """ref: CoreWorker::SubmitTask core_worker.cc:1855."""
        fid = self.export_function(fn)
        task_id = TaskID(os_urandom4() + b"\x00" * 8 + self.job_id.binary())
        spec_args, arg_ids = self._pack_args(args, kwargs)
        mr = self.cfg.task_max_retries_default if max_retries is None else max_retries
        spec = TaskSpec(
            task_id=task_id, name=name or getattr(fn, "__name__", "task"),
            func_id=fid, args=spec_args, num_returns=num_returns,
            resources=resources or self._default_resources,
            owner=self.address, job_id=self.job_id, max_retries=mr,
            retry_exceptions=retry_exceptions,
            scheduling=scheduling or self._default_scheduling,
            runtime_env=self.resolve_runtime_env(runtime_env),
            trace_ctx=self._trace_ctx(),
            generator_backpressure=generator_backpressure,
            generator_backpressure_bytes=generator_backpressure_bytes)
        refs = self._register_returns(spec, arg_ids)
        self._submit_spec(spec, retries_left=mr)
        if spec.is_streaming:
            return ObjectRefGenerator(spec.task_id, self.address)
        return refs

    @staticmethod
    def _trace_ctx() -> Optional[dict]:
        """Caller's span context, stamped on outgoing specs
        (ref: tracing_helper.py _function_hydrate_span_args). A live
        context propagates regardless of the local enable flag — workers
        are never "enabled" process-locally, yet tasks they submit must
        continue the caller's trace."""
        from ray_tpu.util import tracing

        return tracing.current_context()

    def _register_returns(self, spec: TaskSpec, arg_ids: List[ObjectID]) -> List[ObjectRef]:
        refs = []
        if spec.is_streaming:
            self._streams.setdefault(spec.task_id, _StreamState())
        for rid in spec.return_ids():
            e = self._entry(rid)
            e.spec = spec                      # lineage
            self.refs.register_owned(rid)
            refs.append(ObjectRef(rid, self.address))
        self.refs.on_task_submitted(arg_ids)
        self._inflight[spec.task_id] = _PendingTask(spec, spec.max_retries)
        self._record_event(spec, "PENDING")
        return refs

    def _pack_args(self, args: tuple, kwargs: dict):
        """Inline small values; pass ObjectRefs as deps
        (ref: dependency_resolver.h inlining)."""
        spec_args: List[Tuple[str, Any]] = []
        arg_ids: List[ObjectID] = []
        for a in args:
            if isinstance(a, ObjectRef):
                spec_args.append(("ref", (a.id, a.owner)))
                arg_ids.append(a.id)
            else:
                spec_args.append(("v", serialization.pack(a)))
        kw = {}
        for k, a in kwargs.items():
            if isinstance(a, ObjectRef):
                kw[k] = ("ref", (a.id, a.owner))
                arg_ids.append(a.id)
            else:
                kw[k] = ("v", serialization.pack(a))
        if kw:
            spec_args.append(("kw", kw))
        return spec_args, arg_ids

    def _owned_ref_args(self, spec: TaskSpec) -> List[ObjectID]:
        out = []
        for kind, payload in spec.args:
            items = [payload] if kind == "ref" else (
                [pv for (kk, pv) in payload.values() if kk == "ref"]
                if kind == "kw" else [])
            for oid, owner in items:
                if owner.addr == self.address.addr:
                    out.append(oid)
        return out

    def _submit_spec(self, spec: TaskSpec, retries_left: int):
        self._inflight.setdefault(spec.task_id, _PendingTask(spec, retries_left))
        pending = [oid for oid in self._owned_ref_args(spec)
                   if not self._entry(oid).event.is_set()]
        if pending:
            # Resolve dependencies before leasing (ref: transport/
            # dependency_resolver.h): the lease target then sees final
            # locations, so locality-aware leasing can follow the data.
            self._spawn(self._enqueue_when_ready(spec, pending))
        else:
            self._enqueue_now(spec)

    def _enqueue_now(self, spec: TaskSpec):
        # The queue key includes the locality target (deps are resolved by
        # now, so it's final): a lease acquired for one queue only ever
        # drains tasks that want that same placement, so pipelining can't
        # drag a task onto a node its own data isn't on.
        target = (self._locality_target(spec)
                  if spec.scheduling.kind == "DEFAULT" else None)
        cls = (spec.scheduling_class(), target)
        q = self._queues[cls]
        q.append(spec)
        # Bound PENDING LEASE REQUESTS, not live pumps (ref:
        # direct_task_transport.cc lease rate limiting): a pump per
        # submission would fire one lease RPC per queued task — 100k
        # queued tasks must not mean 100k in-flight lease requests. But
        # pumps already HOLDING leases must not suppress new ones: a pump
        # can be parked inside a long-running push (a streaming task
        # blocks its worker for the stream's whole lifetime), and gating
        # on total pump count deadlocks the still-queued siblings that
        # the consumer is waiting on.
        parked = self._class_parked[cls]
        if parked > 0:
            # leased worker(s) parked in the reuse-grace window: hand them
            # the work instead of firing fresh lease RPCs — but ONLY as
            # far as they can absorb it; a burst deeper than the parked
            # pool must still spawn pumps or a 100-task fan-out would
            # serialize onto one worker
            ev = self._class_work.get(cls)
            if ev is not None:
                self.loop.call_soon_threadsafe(ev.set)
            if len(q) <= parked:
                return
        if self._class_pending_lease[cls] < self._max_pumps:
            self._spawn(self._pump_class(cls))

    async def _enqueue_when_ready(self, spec: TaskSpec,
                                  pending: List[ObjectID]):
        for oid in pending:
            e = self._entry(oid)
            # event-driven: a completion callback wakes us; the 1 s cap
            # only bounds shutdown latency, there is no busy-poll
            while not e.event.is_set() and not self._shutdown:
                await self._await_entry(e, timeout=1.0)
        # Errored/lost deps still dispatch: the executing worker surfaces
        # the dependency failure as the task's error (same as the ref,
        # where the raylet cancels on dep failure and the owner raises).
        self._enqueue_now(spec)

    async def _pump_class(self, cls: Tuple):
        """One pump == one leased worker draining this class's queue. Each
        submission spawns a pump, so parallelism grows with queue depth (the
        nodelet's worker pool is the actual cap); a pump that wins no work
        returns its lease immediately. ref: direct_task_transport.cc:346
        RequestNewWorkerIfNeeded + pipelining onto leased workers :588."""
        q = self._queues[cls]
        if not q:
            return
        # Re-check the bound HERE, on the loop (atomically w.r.t. other
        # pumps): the spawn-time check runs on the submitting thread and
        # reads a stale counter during bursts — a 100k-submission loop
        # would otherwise spawn 100k pumps that all fire lease RPCs once
        # the loop catches up. Excess pumps exit; the drain + exit-respawn
        # path keeps liveness.
        if self._class_pending_lease[cls] >= self._max_pumps:
            return
        self._class_pending_lease[cls] += 1
        try:
            lw = await self._acquire_lease(q[0], preferred=cls[1])
        except Exception:
            logger.exception("lease acquisition failed")
            lw = None
        finally:
            self._class_pending_lease[cls] -= 1
        if lw is None:
            if q and not self._shutdown:
                await asyncio.sleep(0.2)
                if self._queues[cls]:
                    self._spawn(self._pump_class(cls))
            return
        self._class_leases[cls].append(lw)
        try:
            while True:
                try:
                    spec = q.popleft()
                except IndexError:
                    # queue drained: park the lease for the reuse-grace
                    # window — a submit landing in it rides this worker
                    # with zero lease RPCs (ref: idle leased-worker reuse)
                    grace = self.cfg.lease_reuse_grace_s
                    if grace <= 0 or self._shutdown:
                        break
                    ev = self._class_work.get(cls)
                    if ev is None:
                        ev = self._class_work[cls] = asyncio.Event()
                    ev.clear()
                    if q:        # landed between drain and clear
                        continue
                    self._class_parked[cls] += 1
                    try:
                        await asyncio.wait_for(ev.wait(), grace)
                    except asyncio.TimeoutError:
                        break
                    finally:
                        self._class_parked[cls] -= 1
                    continue
                if spec.task_id in self._cancel_requested:
                    # cancelled in the window between queue-pop and push
                    self._cancel_requested.discard(spec.task_id)
                    self._fail_task_returns(spec, TaskCancelledError(
                        f"task {spec.name} cancelled before execution"))
                    self._record_event(spec, "CANCELLED")
                    continue
                if not await self._push_and_handle(spec, lw, cls):
                    break     # worker died; retries repump on a fresh lease
        finally:
            self._class_leases[cls].remove(lw)
            await self._return_lease(lw)
            # a task enqueued while this pump was between its last queue
            # check and the lease return may have been gated out — liveness
            # requires the exiting pump to respawn when work remains
            if self._queues[cls] and not self._shutdown:
                self._spawn(self._pump_class(cls))

    def _locality_target(self, spec: TaskSpec) -> Optional[Address]:
        """Lease-target choice by data locality (ref: lease_policy.h
        LocalityAwareLeasePolicy): prefer the nodelet already holding the
        most argument bytes, so big args need no transfer. Only owned,
        store-resident args count — inlined values and borrowed refs
        (whose locations live at their owner) don't steer placement."""
        scores: Dict[Address, int] = {}
        for oid in self._owned_ref_args(spec):
            with self._dir_lock:
                e = self.directory.get(oid)
                if e is None or e.state != "ready" or e.inline is not None:
                    continue
                locs = list(e._locations or ())  # snapshot: mutated by add_location
                size = e.size
            for loc in locs:
                loc = tuple(loc)
                scores[loc] = scores.get(loc, 0) + max(size, 1)
        if not scores:
            return None
        return max(scores.items(), key=lambda kv: kv[1])[0]

    async def _pg_bundle_addr(self, pg_id, bundle_index: int,
                              resources: Optional[ResourceSet] = None,
                              refresh: bool = False) -> Optional[Address]:
        """Resolve the nodelet hosting a PG bundle (index -1 = first
        placed bundle whose declared capacity fits `resources`). PG-task
        leases MUST go to the reserving node — any other nodelet answers
        "bundle not here" forever (ref: PG tasks dispatch against the
        bundle's reserved resources on its raylet). Placement is static
        after CREATED, so resolutions are cached per (pg, bundle);
        refresh=True (after an infeasible reply) re-reads the GCS —
        bundle replacement after node death moves the address."""
        key = (pg_id, bundle_index)
        if not refresh:
            hit = self._pg_addr_cache.get(key)
            if hit is not None:
                return hit
        try:
            pg = await self.pool.get(self.gcs_addr).call(
                "get_placement_group", pg_id=pg_id)
            if not pg:
                return None
            cands = [b for b in pg["bundles"]
                     if b["node_id"] is not None
                     and (bundle_index < 0 or b["index"] == bundle_index)]
            if bundle_index < 0 and resources is not None:
                fitting = [b for b in cands
                           if resources.fits_in(
                               ResourceSet(dict(b["resources"])))]
                cands = fitting or cands
            if not cands:
                return None
            node_id = cands[0]["node_id"]
            nodes = await self.pool.get(self.gcs_addr).call("get_nodes")
            for n in nodes:
                if n.node_id == node_id and n.alive:
                    addr = tuple(n.nodelet_addr)
                    self._pg_addr_cache[key] = addr
                    return addr
        except (ConnectionLost, RemoteError, OSError):
            pass
        return None

    async def _acquire_lease(self, spec: TaskSpec,
                             preferred: Optional[Address] = None
                             ) -> Optional[_LeasedWorker]:
        target = preferred or self.nodelet_addr
        pg = None
        if spec.scheduling.kind == "PLACEMENT_GROUP":
            pg = (spec.scheduling.pg_id, spec.scheduling.bundle_index)
            t = await self._pg_bundle_addr(spec.scheduling.pg_id,
                                           spec.scheduling.bundle_index,
                                           resources=spec.resources)
            if t is not None:
                target = t
        affinity_addr = None
        if spec.scheduling.kind == "NODE_AFFINITY":
            nodes = await self.pool.get(self.gcs_addr).call("get_nodes")
            for n in nodes:
                if n.node_id == spec.scheduling.node_id:
                    affinity_addr = target = tuple(n.nodelet_addr)
                    break
        deadline = time.time() + self.cfg.worker_lease_timeout_s * 4
        while time.time() < deadline:
            # Fresh idempotency token per attempt: a duplicated frame of
            # THIS request dedupes at the nodelet (no double grant), while
            # a deliberate retry re-attempts with a new token.
            idem = os.urandom(12).hex()
            try:
                r = await self.pool.get(tuple(target)).call(
                    "request_lease", resources=spec.resources, pg=pg,
                    job_id=spec.job_id.binary(),
                    retriable=spec.max_retries != 0,
                    env_vars=_process_env(spec.runtime_env),
                    idem=idem,
                    timeout=self.cfg.worker_lease_timeout_s + 10.0)
            except (ConnectionLost, RemoteError, OSError) as e:
                logger.warning("lease request to %s failed: %s", target, e)
                if affinity_addr is not None and not spec.scheduling.soft:
                    # hard affinity: a transient RPC failure must not
                    # quietly re-target the driver's node
                    target = affinity_addr
                else:
                    target = self.nodelet_addr
                await asyncio.sleep(0.2)
                continue
            st = r["status"]
            if st == "granted":
                from ray_tpu.devtools.chaos import note_peer
                note_peer(tuple(r["worker_addr"]), "worker")
                return _LeasedWorker(r["lease_id"], r["worker_addr"], tuple(target),
                                     r["worker_id"])
            if st == "spillback":
                if affinity_addr is not None and not spec.scheduling.soft:
                    # hard affinity (ref: NodeAffinitySchedulingStrategy
                    # soft=False): the task runs on ITS node or not at
                    # all — never follow a spillback elsewhere
                    await asyncio.sleep(0.1)
                    continue
                target = tuple(r["addr"])
                from ray_tpu.devtools.chaos import note_peer
                note_peer(target, "nodelet")
                continue
            if st == "retry":
                await asyncio.sleep(0.05)
                continue
            if st == "infeasible":
                # Stay pending while the cluster may grow (the reference
                # parks infeasible tasks in a queue surfaced to the
                # autoscaler; our GCS records the unmet demand on every
                # pick_node miss). Fail only after the extended deadline.
                await asyncio.sleep(0.5)
                if pg is not None:
                    # the bundle may have (re)placed on another node
                    t = await self._pg_bundle_addr(
                        pg[0], pg[1], resources=spec.resources,
                        refresh=True)
                    target = t if t is not None else self.nodelet_addr
                elif affinity_addr is not None and not spec.scheduling.soft:
                    target = affinity_addr   # hard affinity: wait it out
                else:
                    target = self.nodelet_addr
                continue
        # Deadline expired with the task still unschedulable. Same scheduling
        # class == same resource demand, so the whole queue is infeasible
        # (ref: infeasible queue surfaced to the autoscaler; we surface the
        # error to callers after the grace window).
        err = RuntimeError(
            f"infeasible task: no node can satisfy "
            f"{spec.resources.quantities} within deadline")
        q = self._queues[(spec.scheduling_class(), preferred)]
        self._fail_task_returns(spec, err)
        while q:
            s = q.popleft()
            if s.task_id != spec.task_id:
                self._fail_task_returns(s, err)
        return None

    async def _return_lease(self, lw: _LeasedWorker):
        try:
            await self.pool.get(lw.nodelet_addr).call("return_lease",
                                                      lease_id=lw.lease_id, timeout=5.0)
        except Exception:
            pass

    async def _push_and_handle(self, spec: TaskSpec, lw: _LeasedWorker,
                               cls: Tuple) -> bool:
        """Push one task to a leased worker. Returns False when the worker
        is dead (the caller must abandon this lease; retries are re-enqueued
        and repumped onto a fresh lease)."""
        self._record_event(spec, "RUNNING", worker=lw.worker_id.hex()[:12])
        self._task_worker[spec.task_id] = lw.worker_addr
        try:
            # timeout=None (reviewed): a task legitimately runs for hours;
            # worker death surfaces as ConnectionLost via the keepalive,
            # so this await is bounded by liveness, not a deadline.
            result: TaskResult = await self.pool.get(lw.worker_addr).call(
                "push_task", spec=spec, timeout=None)  # raylint: disable=unbounded-rpc-call
        except (ConnectionLost, RemoteError, OSError) as e:
            pt = self._inflight.get(spec.task_id)
            if spec.task_id in self._cancel_requested:
                # force-cancel killed the worker under this push: that's
                # cancellation, not a crash — never retried
                self._fail_task_returns(spec, TaskCancelledError(
                    f"task {spec.name} cancelled (force)"))
            elif pt is not None and pt.retries_left > 0:
                pt.retries_left -= 1
                logger.warning("task %s worker died (%s); retrying (%d left)",
                               spec.name, e, pt.retries_left)
                self._record_event(spec, "FAILED_RETRYING")
                self._queues[cls].append(spec)
                self._spawn(self._pump_class(cls))
            else:
                asyncio.get_running_loop().run_in_executor(
                    None, self.flight.dump, f"worker_crashed:{spec.name}",
                    {"task_id": spec.task_id.hex(), "error": str(e)})
                self._fail_task_returns(spec, WorkerCrashedError(
                    f"worker died running {spec.name}: {e}"))
            return False
        finally:
            self._task_worker.pop(spec.task_id, None)
            self._cancel_requested.discard(spec.task_id)
        self._complete_task(spec, result, cls,
                            worker=lw.worker_id.hex()[:12])
        return True

    def _complete_task(self, spec: TaskSpec, result: TaskResult,
                       cls: Optional[Tuple], worker: Optional[str] = None):
        self._cancel_requested.discard(spec.task_id)   # no leak on any path
        app_error = None
        for kind, payload in result.returns:
            if kind == "err":
                app_error = payload
                break
        if app_error is not None and spec.retry_exceptions:
            pt = self._inflight.get(spec.task_id)
            if pt is not None and pt.retries_left > 0:
                pt.retries_left -= 1
                self._record_event(spec, "FAILED_RETRYING")
                self._queues[cls].append(spec)
                self._spawn(self._pump_class(cls))
                return
        if spec.is_streaming:
            self._finalize_stream_on_result(spec, error=app_error)
        for (kind, payload), rid in zip(result.returns, spec.return_ids()):
            e = self._entry(rid)
            if kind == "inline":
                e.inline = payload
                try:
                    self.memory_store.put(rid, serialization.unpack(payload))
                except Exception:
                    pass
            elif kind == "store":
                if isinstance(payload, dict):
                    e.locations.add(tuple(payload["addr"]))
                    e.primaries.add(tuple(payload["addr"]))
                    e.size = payload.get("size", 0)
                else:
                    e.locations.add(tuple(payload))
                    e.primaries.add(tuple(payload))
            elif kind == "err":
                e.error = payload
                e.state = "error"
                self.memory_store.put(rid, payload)
            if e.state != "error":
                e.state = "ready"
            self._complete_entry(e)
        self._record_event(spec, "FAILED" if app_error else "FINISHED",
                           worker=worker)
        self._inflight.pop(spec.task_id, None)
        arg_ids = [p[0] for (k, p) in spec.args if k == "ref"]
        self.refs.on_task_done(arg_ids)
        if (app_error is None and not spec.is_streaming and result.returns
                and all(k == "inline" for k, _ in result.returns)):
            # Every return landed INLINE, owner-side: the values live in
            # this process and can never be lost, so the spec serves no
            # lineage purpose — drop it. Deep queues retain ~KB of spec
            # per completed task otherwise (1M-task run: multi-GB driver
            # RSS; ref: reference_count.h:59 pins lineage only while an
            # object could need reconstruction).
            with self._dir_lock:
                for rid in spec.return_ids():
                    ent = self.directory.get(rid)
                    if ent is not None:
                        ent.spec = None

    def _fail_task_returns(self, spec: TaskSpec, exc: BaseException):
        # System errors re-raise as themselves at the caller, not TaskError.
        ser = serialization.SerializedException(exc, "".join(
            traceback.format_exception(type(exc), exc, exc.__traceback__)),
            wrap=False)
        if spec.is_streaming:
            self._finalize_stream_on_result(spec, error=ser)
        for rid in spec.return_ids():
            e = self._entry(rid)
            e.error = ser
            e.state = "error"
            self._complete_entry(e)
            self.memory_store.put(rid, ser)
        self._record_event(spec, "FAILED")
        self._inflight.pop(spec.task_id, None)

    # ----------------------------------------------------------------- actors

    def create_actor(self, cls: type, args: tuple, kwargs: dict, *,
                     name: Optional[str] = None, namespace: str = "default",
                     resources: Optional[ResourceSet] = None,
                     max_restarts: int = 0, max_concurrency: int = 1,
                     scheduling: Optional[SchedulingStrategy] = None,
                     lifetime: Optional[str] = None,
                     runtime_env: Optional[dict] = None) -> ActorID:
        """ref: CoreWorker::CreateActor core_worker.cc:1922 → GCS RegisterActor."""
        fid = self.export_function(cls)
        actor_id = ActorID.of(self.job_id)
        task_id = TaskID.of(actor_id)
        spec_args, arg_ids = self._pack_args(args, kwargs)
        spec = TaskSpec(
            task_id=task_id, name=getattr(cls, "__name__", "Actor"),
            func_id=fid, args=spec_args, num_returns=0,
            resources=resources or ResourceSet({"CPU": 1.0}),
            owner=self.address, job_id=self.job_id,
            scheduling=scheduling or SchedulingStrategy(),
            is_actor_creation=True, actor_id=actor_id,
            max_restarts=max_restarts, max_concurrency=max_concurrency,
            actor_name=name, namespace=namespace,
            runtime_env=self.resolve_runtime_env(runtime_env),
            trace_ctx=self._trace_ctx())
        self.refs.on_task_submitted(arg_ids)
        r = self.gcs_call("register_actor", spec=spec)
        if not r.get("ok"):
            raise ValueError(r.get("error", "actor registration failed"))
        self._actor_addr[actor_id] = None
        self._subscribe_actor(actor_id)
        return actor_id

    def _subscribe_channel(self, channel: str):
        """Register a pubsub channel; remembered for resubscription after
        a GCS failover (ref: GcsClient resubscribe on reconnect,
        _raylet.pyx:2111 _auto_reconnect)."""
        self._gcs_subs.add(channel)

        async def _sub():
            try:
                await self.pool.get(self.gcs_addr).call(
                    "subscribe", channel=channel,
                    addr=self.address.addr, timeout=5.0)
            except Exception:
                pass
        self._spawn(_sub())

    def _resubscribe_all(self):
        """After the GCS came back: re-register every channel (a
        memory-storage GCS or one that died between snapshots lost its
        subscriber table)."""
        for ch in list(self._gcs_subs):
            async def _sub(ch=ch):
                try:
                    await self.pool.get(self.gcs_addr).call(
                        "subscribe", channel=ch, addr=self.address.addr,
                        timeout=5.0)
                except Exception:
                    pass
            self._spawn(_sub())

    def _subscribe_actor(self, actor_id: ActorID):
        self._subscribe_channel(f"actor:{actor_id.hex()}")

    async def rpc_pubsub_message(self, channel: str, message: Any):
        if channel.startswith("actor:"):
            aid = ActorID.from_hex(channel.split(":", 1)[1])
            self._actor_state[aid] = message
            self._actor_addr[aid] = tuple(message["address"]) if message.get("address") else None
            if self._actor_addr[aid] is not None:
                from ray_tpu.devtools.chaos import note_peer
                note_peer(self._actor_addr[aid], "worker")
            ev = self._actor_events.get(aid)
            if ev:
                ev.set()
            if message.get("state") == "DEAD":
                # terminal: prune the channel so _gcs_subs stays bounded
                # and failover resubscription doesn't replay dead actors
                self._gcs_subs.discard(channel)
                try:
                    await self.pool.get(self.gcs_addr).call(
                        "unsubscribe", channel=channel,
                        addr=self.address.addr, timeout=5.0)
                except Exception:
                    pass
        elif channel == "log":
            self._on_log(message)

    # ------------------------------------------------- compiled-DAG sinks

    def register_channel_sink(self, sink_id: str, sink: Any) -> None:
        """Accept channel_result frames for one CompiledDAG's output."""
        self._channel_sinks[sink_id] = sink

    def unregister_channel_sink(self, sink_id: str) -> None:
        self._channel_sinks.pop(sink_id, None)

    def deliver_channel_result(self, sink_id: str, seq: int, slot: int,
                               kind: str, payload: bytes) -> bool:
        """Local fast path for a leaf channel hosted in this process;
        returns False when the sink is gone (torn down)."""
        sink = self._channel_sinks.get(sink_id)
        if sink is None:
            return False
        sink.deliver(seq, slot, kind, payload)
        return True

    def rpc_channel_result(self, sink_id: str, seq: int, slot: int,
                           kind: str, payload: bytes) -> dict:
        # synchronous up to the enqueue (frames keep wire order) and
        # inline-eligible: ONEWAY results skip the dispatch-task round
        if not self.deliver_channel_result(sink_id, seq, slot, kind,
                                           payload):
            return {"ok": False, "error": "no such sink"}
        return {"ok": True}

    rpc_channel_result._rpc_inline = True

    def _on_log(self, message: dict):
        """Driver-side worker log fan-in (ref: worker.py:1758
        print_to_stdstream)."""
        if self.mode != "driver" or not self.cfg.log_to_driver:
            return
        import sys

        for entry in message.get("lines", []):
            stream = sys.stderr if entry.get("stream") == "err" else sys.stdout
            print(f"({entry.get('source', '?')}) {entry.get('line', '')}",
                  file=stream)

    def subscribe_logs(self):
        self._subscribe_channel("log")

    async def _resolve_actor(self, actor_id: ActorID,
                             timeout: Optional[float] = None) -> Address:
        """Wait for the actor to be ALIVE. No arbitrary deadline: like the
        reference's actor submit queue, calls buffer while the actor is
        still starting/restarting (a 200-actor fleet on a slow node takes
        minutes to spawn) and fail only when the GCS declares it DEAD —
        or the optional caller deadline passes. Runs as a coroutine on the
        runtime loop so a fleet of pending actors parks zero threads."""
        addr = self._actor_addr.get(actor_id)
        if addr is not None:
            return addr
        st = self._actor_state.get(actor_id)
        if st is not None and st.get("state") == "DEAD":
            raise ActorDiedError(f"actor {actor_id.hex()[:12]} is dead: "
                                 f"{st.get('death_cause')}",
                                 actor_id=actor_id.hex())
        deadline = None if timeout is None else time.time() + timeout
        view = None
        while not self._shutdown:
            step = 30.0
            if deadline is not None:
                step = min(step, max(0.1, deadline - time.time()))
            r = await self.pool.get(self.gcs_addr).call(
                "wait_actor_alive", actor_id=actor_id, wait_timeout=step,
                timeout=step + 10.0)
            view = r.get("view")
            if view is not None:
                self._actor_state[actor_id] = view
            if r.get("ok"):
                self._actor_addr[actor_id] = tuple(view["address"])
                from ray_tpu.devtools.chaos import note_peer
                note_peer(self._actor_addr[actor_id], "worker")
                return self._actor_addr[actor_id]
            if view is None or view.get("state") == "DEAD":
                break
            if deadline is not None and time.time() >= deadline:
                break
        cause = (view or {}).get("death_cause", "not alive in time")
        raise ActorDiedError(f"actor {actor_id.hex()[:12]}: {cause}",
                             actor_id=actor_id.hex())

    def submit_actor_call(self, actor_id: ActorID, method_name: str,
                          args: tuple, kwargs: dict, *, num_returns: int = 1,
                          max_task_retries: int = 0) -> List[ObjectRef]:
        """ref: CoreWorker::SubmitActorTask core_worker.cc:2156 + ordered
        actor submit queues (transport/actor_submit_queue.h)."""
        task_id = TaskID.of(actor_id)
        spec_args, arg_ids = self._pack_args(args, kwargs)
        self._actor_seq[actor_id] += 1
        spec = TaskSpec(
            task_id=task_id, name=method_name, func_id=b"", args=spec_args,
            num_returns=num_returns, resources=ResourceSet({}),
            owner=self.address, job_id=self.job_id,
            is_actor_call=True, actor_id=actor_id, method_name=method_name,
            seq_no=self._actor_seq[actor_id], max_retries=max_task_retries,
            trace_ctx=self._trace_ctx())
        refs = self._register_returns(spec, arg_ids)
        self._actor_queue(actor_id).append((spec, max_task_retries))
        self._spawn(self._actor_sender(actor_id))
        if spec.is_streaming:
            return ObjectRefGenerator(spec.task_id, self.address)
        return refs

    def _actor_queue(self, actor_id: ActorID) -> deque:
        q = self._actor_queues.get(actor_id)
        if q is None:
            q = self._actor_queues[actor_id] = deque()
        return q

    async def _actor_sender(self, actor_id: ActorID):
        """Single in-flight sender per actor: frames hit the wire in seq order
        (TCP FIFO) and the actor worker executes FIFO, giving the ordered
        semantics of the reference's sequence-numbered actor submit queue
        (transport/actor_submit_queue.h). Replies are handled concurrently
        (pipelining)."""
        if self._actor_sending.get(actor_id):
            return
        self._actor_sending[actor_id] = True
        try:
            q = self._actor_queue(actor_id)
            while q:
                spec, retries = q.popleft()
                try:
                    addr = await self._resolve_actor(actor_id)
                except (ActorDiedError, ActorUnavailableError) as e:
                    e.dispatched = False   # never left the submit queue
                    if isinstance(e, ActorDiedError):
                        # black box: the dead worker itself may never have
                        # dumped (SIGKILL / os._exit) — the caller's ring
                        # is the remaining evidence. Off-loop: file I/O.
                        asyncio.get_running_loop().run_in_executor(
                            None, self.flight.dump,
                            f"actor_died:{actor_id.hex()[:12]}",
                            {"cause": str(e)})
                    self._fail_task_returns(spec, e)
                    continue
                except (ConnectionLost, RemoteError, OSError):
                    # GCS blip (restart/failover): requeue and retry —
                    # gcs reconnect logic lives in gcs_call, which this
                    # loop-native wait path bypasses
                    q.appendleft((spec, retries))
                    await asyncio.sleep(1.0)
                    continue
                client = self.pool.get(tuple(addr))
                try:
                    await client.connect()
                except (ConnectionLost, OSError) as e:
                    # connect failed: the frame provably never left us
                    await self._on_actor_push_failure(spec, retries, addr, e,
                                                      dispatched=False)
                    continue
                try:
                    fut = await client.start_call("push_actor_task", spec=spec)
                except (ConnectionLost, OSError) as e:
                    # the frame was (at least partially) written before the
                    # failure — it MAY have reached the worker, so this is
                    # not provably unsent (drain() raises after write())
                    await self._on_actor_push_failure(spec, retries, addr, e)
                    continue
                self.loop.create_task(
                    self._handle_actor_reply(spec, retries, addr, fut))
        finally:
            self._actor_sending[actor_id] = False
            if self._actor_queue(actor_id):
                self._spawn(self._actor_sender(actor_id))

    async def _handle_actor_reply(self, spec: TaskSpec, retries: int,
                                  addr: Address, fut):
        try:
            result: TaskResult = await fut
        except (ConnectionLost, RemoteError, OSError) as e:
            await self._on_actor_push_failure(spec, retries, addr, e)
            return
        # actor path has no lease record: the worker's address is its
        # stable identity for the dashboard's per-worker lanes
        self._complete_task(spec, result, None,
                            worker=f"{addr[0]}:{addr[1]}")

    async def _on_actor_push_failure(self, spec: TaskSpec, retries: int,
                                     addr: Address, err: Exception, *,
                                     dispatched: bool = True):
        """Worker connection broke: the actor may be restarting
        (ref: direct_actor_task_submitter.h DisconnectActor/retry path).

        ``dispatched=False`` ⇒ the push frame provably never hit the wire;
        the surfaced error carries that so routing layers (serve proxy)
        can safely re-dispatch non-idempotent requests."""
        actor_id = spec.actor_id
        if self._actor_addr.get(actor_id) == tuple(addr):
            self._actor_addr[actor_id] = None
        self.pool.drop(tuple(addr))
        # "no actor hosted here" is a STALE ADDRESS, not an execution
        # error: the actor re-drove onto a different worker (GCS failover
        # mid-creation, or restart). The task provably never ran, so
        # re-resolving and resending is a delivery retry that must not
        # consume max_task_retries (ref: the direct actor submitter
        # resends undelivered tasks on reconnect without counting them).
        stale_addr = (isinstance(err, RemoteError)
                      and "no actor hosted here" in str(err))
        if isinstance(err, RemoteError) and not stale_addr:
            # Handler raised (not a transport failure): surface to caller.
            self._fail_task_returns(spec, err)
            return
        try:
            view = await self.pool.get(self.gcs_addr).call(
                "get_actor", actor_id=actor_id, timeout=10.0)
        except Exception:
            view = None
        state = (view or {}).get("state")
        if stale_addr and state != "DEAD":
            await asyncio.sleep(0.3)
            self._actor_queue(actor_id).append((spec, retries))
            self._spawn(self._actor_sender(actor_id))
        elif retries != 0 and state != "DEAD":
            await asyncio.sleep(0.3)
            self._actor_queue(actor_id).append(
                (spec, retries - 1 if retries > 0 else -1))
            self._spawn(self._actor_sender(actor_id))
        elif state in ("RESTARTING", "ALIVE", "PENDING_CREATION"):
            self._fail_task_returns(spec, ActorUnavailableError(
                f"actor {actor_id.hex()[:12]} unavailable: {err}",
                dispatched=dispatched))
        else:
            cause = (view or {}).get("death_cause", str(err))
            # driver-side black box: the dead actor's worker may have had
            # no chance to dump (SIGKILL), so the caller's recent task
            # events are the only post-mortem evidence. Off-loop: dump()
            # writes a file.
            loop = asyncio.get_running_loop()
            loop.run_in_executor(None, self.flight.dump,
                                 f"actor_died:{actor_id.hex()[:12]}",
                                 {"cause": str(cause)})
            self._fail_task_returns(spec, ActorDiedError(
                f"actor {actor_id.hex()[:12]} died: {cause}",
                actor_id=actor_id.hex(), dispatched=dispatched))

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        self.gcs_call("kill_actor", actor_id=actor_id, no_restart=no_restart)

    def cancel(self, ref, force: bool = False, recursive: bool = False):
        """ref: CoreWorker::CancelTask (core_worker.cc) — three cases:
        still QUEUED: drop from the submit queue, fail returns with
        TaskCancelledError. EXECUTING: inject KeyboardInterrupt into the
        worker's executor thread (force=True: tell the worker to exit —
        the interpreter dies, so even C-blocked tasks stop). Already
        FINISHED: no-op. Cancelled tasks are never retried. Actor-call
        refs route the interrupt to the actor's worker process (sync
        methods only — an async method coroutine has no thread to
        interrupt). A task grabbed by a pump but not yet pushed is
        caught at the pre-push _cancel_requested gate."""
        if recursive:
            raise NotImplementedError(
                "recursive cancellation is not implemented; cancel child "
                "task refs individually")
        oid = ref.id
        task_id = oid.task_id()
        pt = self._inflight.get(task_id)
        if pt is None:
            return   # finished (or not a task ref): nothing to cancel
        self._cancel_requested.add(task_id)
        # 1. queued, not yet leased: remove + fail (cheapest path)
        for cls, q in list(self._queues.items()):
            for spec in list(q):
                if spec.task_id == task_id:
                    try:
                        q.remove(spec)
                    except ValueError:
                        break    # a pump grabbed it; fall through to 2.
                    self._cancel_requested.discard(task_id)
                    self._fail_task_returns(spec, TaskCancelledError(
                        f"task {spec.name} cancelled before execution"))
                    self._record_event(spec, "CANCELLED")
                    return
        # 2. executing on a worker. Actor calls aren't in _task_worker —
        # resolve their worker through the actor address table.
        addr = self._task_worker.get(task_id)
        if addr is None and pt.spec is not None and pt.spec.is_actor_call:
            addr = self._actor_addr.get(task_id.actor_id())
        if addr is None:
            # not queued, not yet pushed: the pre-push gate in the pump
            # fires on _cancel_requested; or already completed (the
            # completion path clears the flag)
            return
        if force:
            # kill the worker process; _push_and_handle sees the broken
            # push + _cancel_requested and fails with TaskCancelledError
            try:
                self._run(self.pool.get(tuple(addr)).call(
                    "exit_worker", reason="cancelled (force)", timeout=5.0))
            except Exception:
                pass
        else:
            try:
                self._run(self.pool.get(tuple(addr)).call(
                    "cancel_task", task_id=task_id, timeout=10.0))
            except Exception:
                pass

    # -------------------------------------------- ownership protocol (server)

    async def rpc_wait_object(self, oid: ObjectID, wait_timeout: float = 30.0) -> dict:
        e = self._entry(oid)
        # asyncio waiter, not run_in_executor(event.wait): thousands of
        # concurrent borrower waits would exhaust the executor pool
        ok = await self._await_entry(e, timeout=wait_timeout)
        if not ok:
            return {"status": "pending"}
        if e.state == "error":
            return {"status": "error", "error": e.error}
        if e.state == "lost":
            return {"status": "lost"}
        if e.inline is not None:
            return {"status": "ready", "inline": e.inline}
        if self.device_store.contains(oid):
            # first remote need of a device-tier object: host-stage it
            # (D2H + shm write, off the loop) and answer with locations —
            # the data plane, not this control RPC, carries the bytes
            ok = await asyncio.get_running_loop().run_in_executor(
                None, self._stage_device_object, oid)
            if ok:
                with self._dir_lock:
                    locs = [list(a) for a in e.locations]
                return {"status": "ready", "inline": None,
                        "locations": locs}
        v = self.memory_store.get_if_exists(oid)
        if v is not _MISSING and not isinstance(v, serialization.SerializedException):
            return {"status": "ready", "inline": serialization.pack(v)}
        with self._dir_lock:
            locs = [list(a) for a in e.locations]
        return {"status": "ready", "inline": None, "locations": locs}

    # ------------------------------------------- streaming generators (owner)

    def stream_progress(self, task_id: TaskID) -> Tuple[int, Optional[int]]:
        st = self._streams.get(task_id)
        if st is None:
            return (0, None)
        return (st.produced, st.total)

    def next_stream_ref(self, task_id: TaskID, index: int,
                        timeout: Optional[float] = None) -> Optional[ObjectRef]:
        """Block until item `index` of the stream is ready; None on clean
        end-of-stream; raises the task's error once all yielded items were
        consumed (ref: generator semantics in task_manager.h:143-171)."""
        st = self._streams.get(task_id)
        if st is None:
            raise ValueError(f"no stream for task {task_id.hex()[:12]}")
        rid = ObjectID.for_return(task_id, index)
        e = self._entry(rid)
        deadline = None if timeout is None else time.time() + timeout
        while True:
            if e.event.is_set() and e.state in ("ready", "error"):
                self._advance_consumed(st, index)
                return ObjectRef(rid, self.address)
            if st.total is not None and index > st.total:
                if st.error is not None:
                    raise st.error.to_exception()
                return None
            if deadline is not None and time.time() >= deadline:
                raise GetTimeoutError(
                    f"stream item {index} of {task_id.hex()[:12]} not ready "
                    "in time")
            st.kick.clear()
            # re-check after clear: an update between the checks above and
            # the clear would otherwise be a lost wakeup (conditions must
            # mirror the loop's exits exactly or this spins)
            if (e.event.is_set() and e.state in ("ready", "error")) \
                    or (st.total is not None and index > st.total):
                continue
            st.kick.wait(1.0)

    def _advance_consumed(self, st: _StreamState, index: int):
        """Consumer progress: release backpressured item acks whose
        release condition now holds. Called from consumer threads; waiter
        futures complete on the loop. The check-then-append in
        rpc_stream_item and the advance-then-filter here must each be
        atomic or a waiter registered between them is never fired."""
        with self._stream_lock:
            if index <= st.consumed:
                return
            # byte sweep stops at produced: no sizes exist past it, and
            # drop_stream advances with a +1e9 sentinel that must not
            # become a billion-iteration loop on the event-loop thread
            for i in range(st.consumed + 1, min(index, st.produced) + 1):
                st.ahead_bytes -= st.item_bytes.pop(i, 0)
            if index > st.produced:
                st.item_bytes.clear()
                st.ahead_bytes = 0
            st.consumed = index
            fire = [f for cond, f in st.consumed_waiters if cond()]
            st.consumed_waiters = [(c, f) for c, f in st.consumed_waiters
                                   if not c()]
        for f in fire:
            try:
                self.loop.call_soon_threadsafe(
                    lambda f=f: f.done() or f.set_result(None))
            except RuntimeError:
                pass

    def drop_stream_soon(self, task_id: TaskID):
        """GC-safe drop: ObjectRefGenerator.__del__ can fire during ANY
        allocation — including inside a _stream_lock critical section on
        this very thread — so the finalizer must never take the lock
        itself. Defer to the loop thread."""
        try:
            self.loop.call_soon_threadsafe(self.drop_stream, task_id)
        except RuntimeError:
            pass   # loop already closed

    def drop_stream(self, task_id: TaskID):
        """Consumer discarded the generator: release any blocked executor
        (its next item report returns ok=False, stopping production),
        drop the state, and free produced-but-never-claimed items — no
        ObjectRef exists for those, so no decrement event would ever free
        them. Claimed items' entries persist under their refs' lifecycle;
        lineage reconstruction revives a fresh state via
        _reset_and_resubmit."""
        st = self._streams.pop(task_id, None)
        if st is None:
            return
        lo, hi = st.consumed, st.produced
        self._advance_consumed(st, st.produced + 10**9)
        st.kick.set()
        for i in range(lo + 1, hi + 1):
            self.refs.release_owned_if_unreferenced(
                ObjectID.for_return(task_id, i))

    async def rpc_stream_item(self, task_id: TaskID, index: int, kind: str,
                              payload: Any,
                              backpressure: Optional[int] = None,
                              backpressure_bytes: Optional[int] = None
                              ) -> dict:
        """Executor reports one yielded item (ref: ReportGeneratorItemReturns).
        Idempotent: a retried generator re-reports earlier indices onto
        already-complete entries, which are left untouched. With
        backpressure=N (items) and/or backpressure_bytes=B the ack is
        withheld until the consumer is within the bound — the executor's
        blocking report call IS the flow control
        (ref: _generator_backpressure_num_objects + the streaming
        executor's admission by object-store memory)."""
        st = self._streams.get(task_id)
        if st is None:
            return {"ok": False, "reason": "unknown-stream"}
        rid = ObjectID.for_return(task_id, index)
        e = self._entry(rid)
        if not e.event.is_set():
            self.refs.register_owned(rid)
            pt = self._inflight.get(task_id)
            e.spec = pt.spec if pt is not None else e.spec   # lineage
            if kind == "inline":
                e.inline = payload
                try:
                    self.memory_store.put(rid, serialization.unpack(payload))
                except Exception:
                    pass
            else:
                e.locations.add(tuple(payload["addr"]))
                e.primaries.add(tuple(payload["addr"]))
                e.size = payload.get("size", 0)
            e.state = "ready"
            self._complete_entry(e)
        size = (len(payload) if kind == "inline"
                else int(payload.get("size", 0)))
        st.produced = max(st.produced, index)
        st.kick.set()
        fut = None
        if (backpressure is not None or backpressure_bytes is not None) \
                and not st.abandoned:
            with self._stream_lock:
                # membership re-check: a concurrent drop_stream fires
                # existing waiters and pops the state — appending to an
                # orphaned state would wait forever
                if self._streams.get(task_id) is not st:
                    return {"ok": False, "reason": "dropped"}
                if index > st.consumed and index not in st.item_bytes:
                    st.item_bytes[index] = size
                    st.ahead_bytes += size

                def released(st=st, index=index):
                    if backpressure is not None \
                            and index - st.consumed > backpressure:
                        return False
                    if backpressure_bytes is not None \
                            and st.ahead_bytes > backpressure_bytes \
                            and index - st.consumed > 1:
                        # bytes over budget: wait — unless THIS item is
                        # the only unconsumed one (a single over-budget
                        # block must not deadlock the stream)
                        return False
                    return True

                if not released():
                    fut = self.loop.create_future()
                    st.consumed_waiters.append((released, fut))
        if fut is not None:
            await fut
            if self._streams.get(task_id) is not st:
                return {"ok": False, "reason": "dropped"}
        return {"ok": True}

    async def rpc_stream_done(self, task_id: TaskID, total: int,
                              error: Any = None) -> dict:
        st = self._streams.get(task_id)
        if st is None:
            return {"ok": False, "reason": "unknown-stream"}
        if st.total is None:   # first finalization wins (retries re-report)
            st.total = total
            st.error = error
        st.kick.set()
        return {"ok": True}

    def _finalize_stream_on_result(self, spec: TaskSpec, error=None):
        """Owner-side safety net: freeze the stream when the task result
        arrives, in case the executor died between its last item and the
        stream_done call."""
        st = self._streams.get(spec.task_id)
        if st is None:
            return
        if st.total is None:
            st.total = st.produced
            st.error = error
        st.kick.set()

    async def rpc_wait_objects(self, oids: List[ObjectID],
                               wait_timeout: float = 30.0) -> dict:
        """Bulk wait_object: one round-trip resolves many borrowed refs
        (ref: batched GetObjects on the store providers)."""
        results = await asyncio.gather(
            *(self.rpc_wait_object(oid, wait_timeout) for oid in oids))
        return {"results": list(results)}

    async def rpc_recover_object(self, oid: ObjectID,
                                 dead_locations=None) -> dict:
        """A borrower failed to fetch from every advertised location:
        prune locations whose NODES are confirmed dead (the borrower's
        claim alone may be a transient network error — pruning a live
        holder would leak its pinned primary and re-execute needlessly),
        then re-execute lineage if no copy remains (the borrower-
        initiated half of ObjectRecoveryManager)."""
        e = self._entry(oid)
        reported = {tuple(a) for a in (dead_locations or [])}
        if reported:
            try:
                nodes = await self.pool.get(self.gcs_addr).call(
                    "get_nodes", timeout=10.0)
                alive_addrs = {tuple(n.nodelet_addr) for n in nodes
                               if n.alive}
            except Exception:
                alive_addrs = None  # GCS unreachable: don't prune
            if alive_addrs is not None:
                with self._dir_lock:
                    for a in reported:
                        if a not in alive_addrs:
                            e.locations.discard(a)
                            e.primaries.discard(a)
        if e._locations or e.inline is not None \
                or self.memory_store.get_if_exists(oid) is not _MISSING:
            return {"status": "has_copies"}
        if e.spec is None:
            e.state = "lost"
            self._complete_entry(e)
            return {"status": "unrecoverable"}
        if e.state != "pending":
            logger.warning("reconstructing %s via lineage "
                           "(borrower-reported loss)", oid.hex()[:12])
            self._reset_and_resubmit(e.spec)
        return {"status": "reconstructing"}

    async def rpc_add_location(self, oid: ObjectID, addr: Address) -> dict:
        """A puller registered a secondary copy (emergent broadcast
        tree); only meaningful while the object is live and ready."""
        self._add_location_locked(oid, tuple(addr))
        return {"ok": True}

    async def rpc_drop_location(self, oid: ObjectID, addr: Address) -> dict:
        """A puller found a registered secondary copy missing (LRU
        eviction); primaries are pinned and never pruned this way."""
        self._drop_location_locked(oid, tuple(addr))
        return {"ok": True}

    async def rpc_locate(self, oid: ObjectID) -> dict:
        with self._dir_lock:
            e = self.directory.get(oid)
            if e is None:
                return {"status": "unknown"}
            # snapshot under the lock: puller registrations mutate the set
            # concurrently from executor threads
            return {"status": e.state,
                    "locations": [list(a) for a in e.locations]}

    async def rpc_add_borrow(self, oid: ObjectID, borrower_id: bytes) -> dict:
        self.refs.add_borrower(oid, borrower_id)
        return {"ok": True}

    async def rpc_remove_borrow(self, oid: ObjectID, borrower_id: bytes) -> dict:
        self.refs.remove_borrower(oid, borrower_id)
        return {"ok": True}

    async def rpc_ping(self) -> dict:
        return {"ok": True, "worker_id": self.worker_id}

    # -------------------------------------------------------------- telemetry

    def _record_event(self, spec: TaskSpec, state: str,
                      worker: Optional[str] = None):
        """ref: task_event_buffer.h:199 — buffered in the TelemetryAgent,
        shipped in batched reports (bounded, drops counted)."""
        self.telemetry.record_event({
            "task_id": spec.task_id.hex(), "name": spec.name,
            "state": state, "job_id": self.job_id, "ts": time.time(),
            "actor_id": spec.actor_id.hex() if spec.actor_id else None,
            # the EXECUTING worker (None on owner-side PENDING events)
            # — the dashboard's per-worker timeline lanes
            "worker": worker})

    def record_span(self, span: dict):
        """Tracing spans ride the task-event channel to the GCS — one
        store serves task states and spans (ref: profile events share the
        TaskEventBuffer, task_event_buffer.h). Stamped with the recording
        worker so the timeline lanes spans next to the tasks that
        emitted them."""
        span.setdefault("worker", self.worker_id.hex()[:12]
                        if self.mode == "worker" else None)
        self.telemetry.record_event(span)

    def _record_pull_edge(self, src_addr, nbytes, seconds):
        """Remote object-pull observation -> per-edge EWMA model."""
        try:
            src = self.telemetry.node_of_addr(tuple(src_addr))
            if src and self.node_id:
                self.telemetry.record_edge(src, self.node_id, nbytes,
                                           seconds, kind="object_pull")
        except Exception:
            pass

    def flush_task_events(self, wait: bool = False):
        """Ship buffered telemetry; `wait=True` blocks until the GCS
        acked (readers like `ray_tpu.timeline()` need read-your-writes)
        and must come from an executor/user thread. `wait=False` is safe
        from the event-loop thread — buffered items ship within one
        report interval."""
        self.telemetry.flush(wait=wait)

    # ------------------------------------------------------------------ misc

    def as_future(self, ref: ObjectRef) -> SyncFuture:
        fut: SyncFuture = SyncFuture()

        def _bg():
            try:
                fut.set_result(self._get_one(ref, None))
            except BaseException as e:
                fut.set_exception(e)

        threading.Thread(target=_bg, daemon=True).start()
        return fut


def os_urandom4() -> bytes:
    import os as _os

    return _os.urandom(4)


def _module_is_installed(mod) -> bool:
    """True if workers can `import mod` (stdlib / site-packages / ray_tpu)."""
    import sys

    import os

    f = getattr(mod, "__file__", None)
    if f is None:
        return True  # builtin/frozen
    top = mod.__name__.split(".")[0]
    if top in ("ray_tpu", "__main__"):
        return top == "ray_tpu"
    f = os.path.abspath(f)
    roots = [getattr(sys, "prefix", ""), getattr(sys, "base_prefix", "")]
    import site

    try:
        roots.extend(site.getsitepackages())
    except Exception:
        pass
    return any(r and f.startswith(r) for r in roots)


def _dumps_function(fn) -> bytes:
    """Pickle by reference for installed modules, by value otherwise — so
    functions defined in user scripts/tests ship to workers that cannot
    import their defining module (the reference gets this via
    cloudpickle-by-value of driver code, function_manager.py)."""
    import inspect

    mod = inspect.getmodule(fn)
    if mod is not None and mod.__name__ != "__main__" \
            and not _module_is_installed(mod):
        try:
            cloudpickle.register_pickle_by_value(mod)
            try:
                return cloudpickle.dumps(fn)
            finally:
                cloudpickle.unregister_pickle_by_value(mod)
        except Exception:
            pass
    return cloudpickle.dumps(fn)
