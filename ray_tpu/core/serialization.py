"""Object serialization: cloudpickle + pickle5 out-of-band buffers.

Reference: python/ray/_private/serialization.py:108 (SerializationContext) —
cloudpickle metadata with pickle-protocol-5 out-of-band buffers enabling
zero-copy numpy/Arrow reads straight from the plasma segment. We reproduce
that layout and add jax.Array awareness: device arrays are pulled to host
(numpy) on serialize — the HBM tier keeps device buffers per-process, the
shared store holds only host bytes.

Wire layout of a stored object:
    [u32 n_buffers][u64 meta_len][meta (cloudpickle, with PickleBuffer
    placeholders)] then for each buffer: pad-to-64 [u64 len][payload]
Deserialization maps each payload as a zero-copy memoryview into the shm
segment, so numpy arrays returned by `get` alias store memory (read-only).
"""

from __future__ import annotations

import pickle
import struct
from typing import Any, List, Tuple

import cloudpickle
import numpy as np

_ALIGN = 64
_HDR = struct.Struct("<IQ")
_LEN = struct.Struct("<Q")


def _is_jax_array(x) -> bool:
    t = type(x)
    mod = t.__module__
    return mod.startswith("jax") and t.__name__ in ("ArrayImpl", "Array")


class _JaxArrayReducer:
    """Moves jax.Arrays device->host at serialize time.

    They deserialize as numpy; the consumer re-places them onto its own mesh
    (device placement is never implicit across process boundaries — on TPU,
    sharding is a property of the consuming program, not the bytes).
    """


def _pre_dump(obj: Any) -> Any:
    return obj


def serialize(obj: Any) -> Tuple[bytes, List[memoryview]]:
    """Returns (meta, out_of_band_buffers)."""
    buffers: List[pickle.PickleBuffer] = []

    def _reduce_jax(arr):
        return np.asarray(arr)  # device -> host, then numpy takes the oob path

    def buffer_cb(buf: pickle.PickleBuffer) -> bool:
        buffers.append(buf)
        return False  # serialize out-of-band

    import copyreg

    # cloudpickle honours dispatch via the Pickler subclass; simplest robust
    # route: map jax arrays to numpy before pickling via a custom pickler.
    class _P(cloudpickle.Pickler):
        def persistent_id(self, o):
            return None

        def reducer_override(self, o):
            if _is_jax_array(o):
                arr = np.asarray(o)
                return (np.asarray, (arr,))
            import types

            if isinstance(o, (types.FunctionType, type)):
                from ray_tpu.core.runtime import (_dumps_function,
                                                  _module_is_installed)
                import inspect

                mod = inspect.getmodule(o)
                if (mod is not None and mod.__name__ != "__main__"
                        and not _module_is_installed(mod)):
                    # functions/classes from user scripts the executing
                    # worker cannot import: embed by value
                    return (cloudpickle.loads, (_dumps_function(o),))
            # chain to cloudpickle's own reducer_override (it handles
            # __main__ functions/classes by value) — returning
            # NotImplemented here would bypass it entirely
            return super().reducer_override(o)

    import io

    f = io.BytesIO()
    p = _P(f, protocol=5, buffer_callback=buffer_cb)
    p.dump(obj)
    meta = f.getvalue()
    return meta, [b.raw() for b in buffers]


def serialized_size(meta: bytes, buffers: List[memoryview]) -> int:
    n = _HDR.size + len(meta)
    for b in buffers:
        n = _aligned(n) + _LEN.size + b.nbytes
    return n


def _aligned(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


def write_to(view: memoryview, meta: bytes, buffers: List[memoryview]) -> int:
    """Writes the wire layout into `view`; returns bytes written."""
    _HDR.pack_into(view, 0, len(buffers), len(meta))
    off = _HDR.size
    view[off:off + len(meta)] = meta
    off += len(meta)
    for b in buffers:
        off = _aligned(off)
        _LEN.pack_into(view, off, b.nbytes)
        off += _LEN.size
        view[off:off + b.nbytes] = b.cast("B")
        off += b.nbytes
    return off


def pack(obj: Any) -> bytes:
    meta, bufs = serialize(obj)
    out = bytearray(serialized_size(meta, bufs))
    write_to(memoryview(out), meta, bufs)
    return bytes(out)


def read_from(view: memoryview) -> Any:
    """Zero-copy deserialize from a stored object's memory."""
    n_buffers, meta_len = _HDR.unpack_from(view, 0)
    off = _HDR.size
    meta = view[off:off + meta_len]
    off += meta_len
    bufs = []
    for _ in range(n_buffers):
        off = _aligned(off)
        (blen,) = _LEN.unpack_from(view, off)
        off += _LEN.size
        bufs.append(view[off:off + blen])
        off += blen
    return pickle.loads(meta, buffers=bufs)


def unpack(data: bytes) -> Any:
    return read_from(memoryview(data))


# --- exception transport ----------------------------------------------------


class SerializedException:
    """Wrapper so exceptions raised in workers re-raise at the caller.

    Reference: python/ray/exceptions.py RayTaskError — the remote traceback
    string travels with the exception and is appended to the local one.
    """

    def __init__(self, exc: BaseException, tb_str: str, wrap: bool = True):
        """wrap=True: user-code exception, re-raised wrapped in TaskError with
        the remote traceback. wrap=False: framework/system exception
        (ActorDiedError, WorkerCrashedError, ...) re-raised as itself."""
        try:
            self.payload = pack(exc)
            self.unpicklable = False
        except Exception:
            self.payload = pack(RuntimeError(f"{type(exc).__name__}: {exc}"))
            self.unpicklable = True
        self.tb_str = tb_str
        self.wrap = wrap

    def to_exception(self) -> BaseException:
        from ray_tpu.core.status import TaskError

        try:
            cause = unpack(self.payload)
        except Exception as e:  # cause class not importable at caller
            cause = RuntimeError(f"(undeserializable task error: {e})")
        if not self.wrap:
            return cause
        return TaskError(cause, self.tb_str)
