"""Actor API: @ray_tpu.remote on classes, handles, method calls.

Reference: python/ray/actor.py — ActorClass._remote:665 (create), method
proxies ActorMethod._remote:167, restart options actor.py:332-351
(max_restarts / max_task_retries). Handles are serializable; a deserialized
handle resolves the actor's current address through the GCS, so handles keep
working across actor restarts.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ray_tpu.core.common import ResourceSet, SchedulingStrategy
from ray_tpu.core.ids import ActorID
from ray_tpu.core import runtime as rt


_ACTOR_OPTIONS = {
    "num_cpus", "num_tpus", "memory", "resources", "name", "namespace",
    "max_restarts", "max_task_retries", "max_concurrency",
    "scheduling_strategy", "lifetime", "runtime_env",
}


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str, num_returns: int = 1):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns

    def options(self, num_returns: Optional[int] = None) -> "ActorMethod":
        return ActorMethod(self._handle, self._name,
                           num_returns if num_returns is not None else self._num_returns)

    def remote(self, *args, **kwargs):
        from ray_tpu.core.common import STREAMING

        nr = self._num_returns
        if nr in ("streaming", "dynamic"):
            nr = STREAMING
        runtime = rt.get_runtime()
        refs = runtime.submit_actor_call(
            self._handle._actor_id, self._name, args, kwargs,
            num_returns=nr,
            max_task_retries=self._handle._max_task_retries)
        if nr == STREAMING:
            return refs   # an ObjectRefGenerator
        if nr == 1:
            return refs[0]
        return refs

    def __call__(self, *args, **kwargs):
        raise TypeError(f"Actor method '{self._name}' must be called with .remote().")


class ActorHandle:
    def __init__(self, actor_id: ActorID, method_meta: Dict[str, int],
                 max_task_retries: int = 0):
        self._actor_id = actor_id
        self._method_meta = method_meta
        self._max_task_retries = max_task_retries

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        meta = self._method_meta
        if meta and name not in meta:
            raise AttributeError(f"actor has no method {name!r}")
        return ActorMethod(self, name, meta.get(name, 1) if meta else 1)

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._method_meta,
                              self._max_task_retries))

    def __repr__(self):
        return f"ActorHandle({self._actor_id.hex()[:12]})"


class ActorClass:
    def __init__(self, cls: type, options: Optional[Dict[str, Any]] = None):
        self._cls = cls
        self._options = dict(options or {})

    def options(self, **opts) -> "ActorClass":
        bad = set(opts) - _ACTOR_OPTIONS
        if bad:
            raise ValueError(f"invalid actor options: {sorted(bad)}")
        merged = dict(self._options)
        merged.update(opts)
        return ActorClass(self._cls, merged)

    def remote(self, *args, **kwargs) -> ActorHandle:
        o = self._options
        runtime = rt.get_runtime()
        resources = ResourceSet.from_options(
            o.get("num_cpus"), o.get("num_tpus"), o.get("memory"),
            o.get("resources"))
        actor_id = runtime.create_actor(
            self._cls, args, kwargs,
            name=o.get("name"), namespace=o.get("namespace", "default"),
            resources=resources,
            max_restarts=o.get("max_restarts",
                               runtime.cfg.actor_max_restarts_default),
            max_concurrency=o.get("max_concurrency", 1),
            scheduling=o.get("scheduling_strategy") or SchedulingStrategy(),
            lifetime=o.get("lifetime"),
            runtime_env=o.get("runtime_env"))
        return ActorHandle(actor_id, _method_meta(self._cls),
                           o.get("max_task_retries", 0))

    def bind(self, *args, **kwargs):
        """Build a lazy actor DAG node (ref: ray.dag ClassNode)."""
        from ray_tpu.dag import ClassNode

        return ClassNode(self, args, kwargs)

    def __call__(self, *a, **k):
        raise TypeError(
            f"Actor class '{self._cls.__name__}' cannot be instantiated "
            "directly; use .remote().")


def _method_meta(cls: type) -> Dict[str, int]:
    meta = {}
    for name in dir(cls):
        if name.startswith("__"):
            continue
        m = getattr(cls, name, None)
        if callable(m):
            meta[name] = getattr(m, "_ray_tpu_num_returns", 1)
    return meta


def method(num_returns: int = 1):
    """@ray_tpu.method(num_returns=N) on actor methods (ref: @ray.method)."""
    def deco(fn):
        fn._ray_tpu_num_returns = num_returns
        return fn
    return deco


def get_actor(name: str, namespace: str = "default") -> ActorHandle:
    """ref: ray.get_actor — named actor lookup via GCS."""
    runtime = rt.get_runtime()
    r = runtime.gcs_call("get_named_actor", name=name, namespace=namespace)
    if r is None:
        raise ValueError(f"no actor named {name!r} in namespace {namespace!r}")
    spec = r["spec"]
    cls = runtime.load_function(spec.func_id)
    return ActorHandle(spec.actor_id, _method_meta(cls), 0)
