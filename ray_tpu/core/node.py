"""Node/session bootstrap: spawn gcs + nodelet daemons.

Reference: python/ray/_private/node.py (start_head_processes:1148) and
services.py (start_gcs_server:1280, start_raylet:1353). Daemons are separate
OS processes started with a ready-pipe handshake; the session directory holds
logs and liveness metadata.
"""

from __future__ import annotations

import atexit
import json
import os
import subprocess
import sys
import time
from typing import Any, Dict, Optional, Tuple

from ray_tpu.core.config import Config

Address = Tuple[str, int]


def _spawn_with_ready(cmd, session_dir: str, log_name: str,
                      timeout: float = 30.0) -> Tuple[subprocess.Popen, str]:
    """Start a daemon that writes "host:port[:...]\n" to --ready-fd."""
    rfd, wfd = os.pipe()
    os.set_inheritable(wfd, True)
    logdir = os.path.join(session_dir, "logs")
    os.makedirs(logdir, exist_ok=True)
    out = open(os.path.join(logdir, log_name + ".out"), "ab")
    err = open(os.path.join(logdir, log_name + ".err"), "ab")
    # pass_fds (implies close_fds=True): only the ready-fd crosses into the
    # daemon — inheriting everything leaks the parent's stdout/stderr pipes
    # into long-lived daemons, which keeps `pytest | tail`-style consumers
    # blocked on EOF forever after the parent exits.
    proc = subprocess.Popen(cmd + ["--ready-fd", str(wfd)],
                            stdout=out, stderr=err, pass_fds=(wfd,),
                            start_new_session=True)
    out.close(); err.close()
    os.close(wfd)
    line = b""
    deadline = time.time() + timeout
    with os.fdopen(rfd, "rb") as f:
        while time.time() < deadline:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"{log_name} died at startup; see {logdir}/{log_name}.err")
            chunk = f.readline()
            if chunk:
                line = chunk
                break
    if not line:
        proc.terminate()
        raise RuntimeError(f"{log_name} did not become ready in {timeout}s")
    return proc, line.decode().strip()


def start_gcs(session_dir: str, cfg: Config, host: str = "127.0.0.1",
              port: int = 0) -> Tuple[subprocess.Popen, Address]:
    proc, ready = _spawn_with_ready(
        [sys.executable, "-m", "ray_tpu.core.gcs", "--host", host,
         "--port", str(port), "--config", cfg.to_json()],
        session_dir, "gcs")
    h, p = ready.rsplit(":", 1)
    return proc, (h, int(p))


def start_nodelet(session_dir: str, cfg: Config, gcs_addr: Address,
                  resources: Optional[Dict[str, float]] = None,
                  labels: Optional[Dict[str, Any]] = None,
                  host: str = "127.0.0.1", port: int = 0,
                  log_name: str = "nodelet"):
    proc, ready = _spawn_with_ready(
        [sys.executable, "-m", "ray_tpu.core.nodelet", "--host", host,
         "--port", str(port), "--gcs", f"{gcs_addr[0]}:{gcs_addr[1]}",
         "--session-dir", session_dir,
         "--resources", json.dumps(resources or {}),
         "--labels", json.dumps(labels or {}),
         "--config", cfg.to_json()],
        session_dir, log_name)
    h, p, node_id_hex, store_name = ready.split(":", 3)
    return proc, (h, int(p)), node_id_hex, store_name


def new_session_dir() -> str:
    base = os.environ.get("RAY_TPU_TMPDIR", "/tmp/ray_tpu")
    path = os.path.join(base, f"session_{int(time.time() * 1000)}_{os.getpid()}")
    os.makedirs(os.path.join(path, "logs"), exist_ok=True)
    # convenience symlink like the reference's session_latest
    latest = os.path.join(base, "session_latest")
    try:
        if os.path.islink(latest) or os.path.exists(latest):
            os.remove(latest)
        os.symlink(path, latest)
    except OSError:
        pass
    return path


def detect_tpu_chips() -> int:
    """Best-effort local chip count WITHOUT importing jax (daemons must not
    grab the TPU). Honors explicit override first."""
    env = os.environ.get("RAY_TPU_CHIPS")
    if env is not None:
        return int(env)
    # TPU VM metadata conventions (ref for GPU analog: autodetect in node.py)
    env = os.environ.get("TPU_CHIPS_PER_HOST_BOUNDS")
    if env:
        try:
            dims = [int(x) for x in env.split(",")]
            n = 1
            for d in dims:
                n *= d
            return n
        except ValueError:
            pass
    if os.environ.get("JAX_PLATFORMS", "").startswith(("tpu", "axon")):
        return 1
    return 0
