"""Error and exception model.

Reference: src/ray/common/status.h (C++ Status codes) and
python/ray/exceptions.py (user-facing exception taxonomy). One module here:
the Python layer is the only consumer in ray_tpu, the native store reports
errors via return codes.
"""

from __future__ import annotations


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A task raised; carries the remote traceback (ref: RayTaskError)."""

    def __init__(self, cause: BaseException, remote_tb: str):
        self.cause = cause
        self.remote_tb = remote_tb
        super().__init__(f"{type(cause).__name__}: {cause}\n--- remote traceback ---\n{remote_tb}")


class WorkerCrashedError(RayTpuError):
    """The worker executing a task died (ref: WorkerCrashedError)."""


class ActorDiedError(RayTpuError):
    """Actor is dead and (re)start budget is exhausted (ref: RayActorError).

    Carries the dead actor's id (hex) so routing layers can evict the
    exact replica locally instead of waiting for a control-plane probe
    (ref: RayActorError.actor_id), and whether the failed call was ever
    dispatched to the actor's worker: ``dispatched=False`` means the task
    frame provably never reached the worker, so re-running it cannot
    duplicate side effects — routing layers may retry it regardless of
    idempotency (ref: router.py re-dispatches queued-but-unsent requests
    on replica death)."""

    def __init__(self, msg: str = "", actor_id: str = None,
                 dispatched: bool = True):
        super().__init__(msg)
        self.actor_id = actor_id
        self.dispatched = dispatched

    def __reduce__(self):   # keep actor_id/dispatched across pickling
        return (type(self), (self.args[0] if self.args else "",
                             self.actor_id, self.dispatched))


class ActorUnavailableError(RayTpuError):
    """Actor is restarting; call may be retried (ref: ActorUnavailableError).

    ``dispatched`` mirrors ActorDiedError: False ⇒ the call never reached
    the worker, so a retry is side-effect-safe for any method."""

    def __init__(self, msg: str = "", dispatched: bool = True):
        super().__init__(msg)
        self.dispatched = dispatched

    def __reduce__(self):
        return (type(self), (self.args[0] if self.args else "",
                             self.dispatched))


class TaskCancelledError(RayTpuError):
    """Task was cancelled via ray_tpu.cancel (ref: TaskCancelledError)."""


class ObjectLostError(RayTpuError):
    """Object's value was lost and could not be reconstructed
    (ref: ObjectLostError / ObjectReconstructionFailedError)."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """ray_tpu.get(..., timeout=) expired (ref: GetTimeoutError)."""


class ObjectStoreFullError(RayTpuError):
    """Host shm tier full and nothing evictable (ref: ObjectStoreFullError)."""


class RuntimeEnvSetupError(RayTpuError):
    """Worker environment failed to materialize (ref: RuntimeEnvSetupError)."""


class PlacementGroupUnavailableError(RayTpuError):
    """Gang reservation infeasible with current cluster shape."""


class NodeDiedError(RayTpuError):
    """Node lost (health-check failure) while hosting the referenced entity."""
