"""Command-line interface: `python -m ray_tpu.cli <cmd>`.

Reference: python/ray/scripts/scripts.py — start/stop (:540,:1004), status,
timeline (:1835), memory (:1900), and the state CLI (`ray list ...`,
util/state/state_cli.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _connect(address: str):
    import ray_tpu

    ray_tpu.init(address=address)
    return ray_tpu


def cmd_start(args):
    """Start a head (gcs + nodelet) that outlives this command."""
    from ray_tpu.core.config import Config
    from ray_tpu.core.node import new_session_dir, start_gcs, start_nodelet

    cfg = Config.load(json.loads(args.system_config))
    session_dir = new_session_dir()
    gcs_proc, gcs_addr = start_gcs(session_dir, cfg, host=args.host,
                                   port=args.port)
    resources = json.loads(args.resources)
    nodelet_proc, nodelet_addr, node_id, store = start_nodelet(
        session_dir, cfg, gcs_addr, resources=resources, host=args.host)
    info = {"address": f"{gcs_addr[0]}:{gcs_addr[1]}",
            "session_dir": session_dir,
            "gcs_pid": gcs_proc.pid, "nodelet_pid": nodelet_proc.pid}
    with open(os.path.join(session_dir, "head.json"), "w") as f:
        json.dump(info, f)
    print(json.dumps(info, indent=2))
    print(f"\nConnect with: ray_tpu.init(address='{info['address']}')")


def cmd_stop(args):
    """Stop daemons of the latest session (ref: ray stop)."""
    base = os.environ.get("RAY_TPU_TMPDIR", "/tmp/ray_tpu")
    latest = os.path.join(base, "session_latest", "head.json")
    if not os.path.exists(latest):
        print("no running head found")
        return
    with open(latest) as f:
        info = json.load(f)
    import signal

    for key in ("nodelet_pid", "gcs_pid"):
        try:
            os.kill(info[key], signal.SIGTERM)
            print(f"stopped {key} {info[key]}")
        except ProcessLookupError:
            pass


def cmd_status(args):
    ray_tpu = _connect(args.address)
    from ray_tpu.util import state

    print(json.dumps(state.cluster_summary(), indent=2, default=str))


def cmd_list(args):
    ray_tpu = _connect(args.address)
    from ray_tpu.util import state

    fn = {"nodes": state.list_nodes, "actors": state.list_actors,
          "tasks": state.list_tasks, "jobs": state.list_jobs,
          "edges": state.edge_stats, "objects": state.list_objects,
          "pgs": state.list_placement_groups}[args.what]
    print(json.dumps(fn(), indent=2, default=str))


def cmd_summary(args):
    ray_tpu = _connect(args.address)
    from ray_tpu.util import state

    print(json.dumps(state.summarize_tasks(), indent=2, default=str))


def cmd_stack(args):
    """Stack dumps of every worker on every node (ref: ray stack)."""
    ray_tpu = _connect(args.address)
    for node_id, dump in ray_tpu.stack().items():
        print(f"===== node {node_id[:12]} =====")
        if "error" in dump:
            print(f"ERROR: {dump['error']}")
            continue
        for wid, w in dump.get("workers", {}).items():
            print(f"--- worker {wid} pid={w.get('pid')} "
                  f"state={w.get('state')} ---")
            print(w.get("stacks", w.get("error", "")))


def cmd_istats(args):
    """Per-daemon handler stats + event-loop lag (ref: event_stats)."""
    ray_tpu = _connect(args.address)
    print(json.dumps(ray_tpu.internal_stats(), indent=2, default=str))


def cmd_debug(args):
    """List active remote-pdb breakpoints and attach (ref: ray debug)."""
    ray_tpu = _connect(args.address)
    from ray_tpu.util import rpdb

    sessions = rpdb.list_breakpoints()
    if not sessions:
        print("no active breakpoints")
        return
    for i, s in enumerate(sessions):
        print(f"[{i}] pid={s.get('pid')} {s.get('host')}:{s.get('port')}")
    if args.list:
        return
    if not 0 <= args.index < len(sessions):
        print(f"no breakpoint session [{args.index}] "
              f"({len(sessions)} active)")
        return
    s = sessions[args.index]
    print(f"attaching to {s['host']}:{s['port']} — 'c' to continue, "
          "'q' to quit")
    rpdb.attach(s["host"], s["port"], token=s.get("token", ""))


def cmd_microbenchmark(args):
    from ray_tpu._perf import main as perf_main

    argv = []
    if args.address:
        argv += ["--address", args.address]
    for f in args.filter or []:
        argv += ["--filter", f]
    argv += ["--min-seconds", str(args.min_seconds)]
    perf_main(argv)


def cmd_gateway(args):
    """Serve remote drivers (ref: ray client server / proxier)."""
    import asyncio

    from ray_tpu.client_gateway import serve

    asyncio.run(serve(args.address, args.host, args.port))


def cmd_timeline(args):
    """Chrome-trace export of the unified timeline — task states, user
    spans, collective rounds, data-op spans — with per-worker lanes
    (ref: ray timeline; observability/timeline.py)."""
    ray_tpu = _connect(args.address)
    from ray_tpu.observability import chrome_trace

    events = ray_tpu.timeline(limit=args.limit)
    trace = chrome_trace(events)
    out = args.output or "timeline.json"
    with open(out, "w") as f:
        json.dump(trace, f)
    n = sum(1 for e in trace if e.get("ph") != "M")
    print(f"wrote {n} slices to {out}")


def cmd_memory(args):
    ray_tpu = _connect(args.address)
    from ray_tpu.util import state

    print(json.dumps(state.memory_summary(), indent=2, default=str))


def _mib(n) -> str:
    return f"{(n or 0) / (1 << 20):.2f}MiB"


def _pin_str(rec: dict) -> str:
    pins = rec.get("pins") or {}
    if not pins:
        return "-"
    parts = []
    for reason, p in pins.items():
        extra = ",".join(f"{k}={v}" for k, v in p.items()
                         if k != "count" and v is not None)
        parts.append(f"{reason}x{p.get('count', 1)}"
                     + (f"({extra})" if extra else ""))
    return " ".join(parts)


def cmd_top(args):
    """`top mem`: cluster memory attribution (observability/memory.py)
    — per-subsystem bytes, the biggest holders with owner / pin reasons /
    temperature, spill candidates, leak suspects."""
    ray_tpu = _connect(args.address)
    from ray_tpu.util import state

    rep = state.memory_report(top_n=args.limit)
    if args.json:
        print(json.dumps(rep, indent=2, default=str))
        return
    print(f"attributed records: {rep.get('records', 0)}"
          f" (+{rep.get('records_overflow', 0)} summarized)")
    sub = rep.get("subsystem_bytes", {})
    store = rep.get("subsystem_store_bytes", {})
    hwm = rep.get("subsystem_hwm_bytes", {})
    print("subsystem        resident      in-store         high-water")
    for name in sorted(set(sub) | set(hwm)):
        print(f"  {name:<12} {_mib(sub.get(name)):>12} "
              f"{_mib(store.get(name)):>12} {_mib(hwm.get(name)):>12}")
    for node, st in (rep.get("nodes") or {}).items():
        print(f"node {node[:12]}: store {_mib(st.get('store_bytes'))} of "
              f"{_mib(st.get('store_capacity'))}, attribution coverage "
              f"{100.0 * (st.get('coverage') or 0):.1f}%")
    print(f"top holders (of {rep.get('records', 0)}):")
    for r in rep.get("top_holders", []):
        print(f"  {r.get('key', '?')[:20]:<20} {r.get('subsystem'):<10} "
              f"{_mib(r.get('nbytes')):>12}  idle={r.get('idle_s')}s "
              f"acc={r.get('access_count')} pins={_pin_str(r)}")
    print(f"spill candidates (unpinned, idle>={rep.get('cold_after_s')}s): "
          f"{len(rep.get('spill_candidates', []))} object(s), "
          f"{_mib(rep.get('spill_candidate_bytes'))}")
    leaks = rep.get("leak_suspects", [])
    if leaks:
        print(f"LEAK SUSPECTS (pinned, owner dead "
              f">={rep.get('leak_suspect_s')}s):")
        for r in leaks:
            print(f"  {r.get('key', '?')[:20]:<20} "
                  f"{_mib(r.get('nbytes')):>12} orphan={r.get('orphan_s')}s "
                  f"pins={_pin_str(r)}")
    else:
        print("leak suspects: none")


def cmd_metrics(args):
    ray_tpu = _connect(args.address)
    from ray_tpu.util.metrics import prometheus_text

    print(prometheus_text())


def cmd_doctor(args):
    """One-shot cluster health triage: nodes alive, progress beacons
    fresh (no active stall), telemetry drop counters zero. Exits
    non-zero when any check fails (observability/health.py)."""
    ray_tpu = _connect(args.address)
    from ray_tpu.util import state

    summary = state.cluster_summary()
    report = state.health_report()
    checks = []

    dead = summary.get("nodes_dead", 0)
    checks.append(("nodes alive",
                   summary.get("nodes_alive", 0) > 0 and dead == 0,
                   f"{summary.get('nodes_alive', 0)} alive, {dead} dead"))

    beacons = report.get("beacons", [])
    stalled = [b for b in beacons if b.get("stalled")]
    checks.append(("beacons fresh", not stalled,
                   f"{len(beacons)} registered, "
                   + (", ".join(b.get("component", "?") for b in stalled)
                      + " stalled" if stalled else "none stalled")))

    drops = {k: summary.get(k, 0.0)
             for k in ("task_events_dropped", "telemetry_reports_dropped")}
    checks.append(("drop counters zero",
                   all(v == 0 for v in drops.values()),
                   ", ".join(f"{k}={int(v)}" for k, v in drops.items())))

    events = report.get("events", [])
    # remediation events are the health plane ACTING (elastic training
    # quarantine/refill/grow) — context below, not a failed check
    recent = [e for e in events if e.get("kind") in ("stall", "straggler")]
    remediations = [e for e in events if e.get("kind") == "remediation"]
    checks.append(("no recent stall/straggler events", not recent,
                   f"{len(recent)} event(s)"
                   + ("" if not recent else ": " + "; ".join(
                       f"{e.get('kind')}:{e.get('component', '?')}"
                       for e in recent[-3:]))))

    # memory plane (observability/memory.py): leak suspects fail the
    # triage; top holders + spill-candidate bytes print as context
    mem = {}
    try:
        mem = state.memory_report(top_n=50)
    except Exception as e:
        checks.append(("memory report reachable", False, str(e)))
    leaks = mem.get("leak_suspects", [])
    checks.append(("no memory leak suspects", not leaks,
                   f"{len(leaks)} pinned object(s) with a dead owner"
                   + ("" if not leaks else ": " + ", ".join(
                       f"{r.get('key', '?')[:16]}({_pin_str(r)})"
                       for r in leaks[:3]))))

    failed = 0
    for name, ok, detail in checks:
        print(f"[{'ok' if ok else 'FAIL'}] {name}: {detail}")
        failed += 0 if ok else 1

    if remediations:
        print(f"remediations: {len(remediations)} self-healing action(s)")
        for e in remediations[-3:]:
            ctx = e.get("context") or {}
            print(f"  {e.get('component', '?')}: {ctx.get('action', '?')} "
                  f"world {ctx.get('world_before', '?')}->"
                  f"{ctx.get('world_after', '?')} "
                  f"suspects={ctx.get('suspects') or {}}")

    if mem:
        total = sum((mem.get("subsystem_bytes") or {}).values())
        print(f"memory: {_mib(total)} attributed "
              f"across {mem.get('records', 0)} record(s); spill-candidate "
              f"{_mib(mem.get('spill_candidate_bytes'))} "
              f"({len(mem.get('spill_candidates', []))} object(s), "
              f"idle>={mem.get('cold_after_s')}s)")
        tier = mem.get("spill_tier") or {}
        if any(tier.values()):
            print(f"  spill tier: {tier.get('spilled_objects', 0)} object(s) "
                  f"on disk ({_mib(tier.get('spilled_bytes'))}), "
                  f"{tier.get('spilled_then_dropped', 0)} spilled-then-"
                  f"dropped from shm; lifetime spill "
                  f"{_mib(tier.get('spill_bytes_total'))} / restore "
                  f"{_mib(tier.get('restore_bytes_total'))} "
                  f"({tier.get('restored_objects', 0)} restore(s))")
        by_node = {}
        for r in mem.get("top_holders", []):
            by_node.setdefault(r.get("node"), []).append(r)
        for node, recs in by_node.items():
            tops = ", ".join(
                f"{r.get('key', '?')[:12]}[{r.get('subsystem')}]"
                f"={_mib(r.get('nbytes'))}" for r in recs[:5])
            print(f"  node {(node or '?')[:12]} top holders: {tops}")
    if args.verbose:
        print(json.dumps(report, indent=2, default=str))
        print(json.dumps(mem, indent=2, default=str))
    if failed:
        raise SystemExit(f"doctor: {failed} check(s) failed")
    print("doctor: all checks passed")


def cmd_blackbox(args):
    """Flight-recorder post-mortems: list the dumps a crashed/stalled
    process left behind, render one, or merge into a chrome trace
    (observability/flight.py)."""
    from ray_tpu.observability import flight

    dumps = flight.list_dumps(args.dir)
    if not dumps:
        print(f"no flight dumps under {args.dir or '(session dir)'}")
        return
    if args.list or (args.index is None and not args.chrome):
        for i, path in enumerate(dumps):
            try:
                doc = flight.load_dump(path)
                print(f"[{i}] {path}  reason={doc.get('reason')} "
                      f"worker={doc.get('worker')} "
                      f"events={len(doc.get('events', []))}")
            except Exception as e:
                print(f"[{i}] {path}  (unreadable: {e})")
        return
    idx = args.index if args.index is not None else len(dumps) - 1
    if not 0 <= idx < len(dumps):
        raise SystemExit(f"no dump [{idx}] ({len(dumps)} found)")
    doc = flight.load_dump(dumps[idx])
    if args.chrome:
        with open(args.chrome, "w") as f:
            json.dump(flight.to_chrome(doc), f)
        print(f"wrote chrome trace to {args.chrome}")
        return
    print(flight.render_summary(doc, tail=args.tail))


def cmd_serve(args):
    """serve deploy/status/shutdown (ref: serve/scripts.py CLI)."""
    ray_tpu = _connect(args.address)
    from ray_tpu import serve

    if args.serve_cmd == "deploy":
        from ray_tpu.serve.schema import ServeDeploySchema, apply_config

        if not args.config:
            raise SystemExit("serve deploy requires --config <file>")
        schema = ServeDeploySchema.from_file(args.config)
        info = apply_config(schema)
        print(json.dumps(info, indent=2))
    elif args.serve_cmd == "status":
        print(json.dumps(serve.status(), indent=2, default=str))
    elif args.serve_cmd == "shutdown":
        serve.shutdown()
        print("serve shut down")


def cmd_lint(args):
    """raylint over the tree (no cluster needed). All flags pass through
    to the lint CLI: `ray_tpu lint --changed-only --fail-on error ...`"""
    from ray_tpu.devtools.lint.cli import main as lint_main

    rest = args.lint_args
    if rest[:1] == ["--"]:   # `ray_tpu lint -- --flags` form
        rest = rest[1:]
    sys.exit(lint_main(rest))


def cmd_chaos(args):
    """Seeded fault-injection scenario: spin up an ephemeral cluster,
    run the canonical task+actor workload under a FaultPlan, and check
    the invariants (typed-within-deadline, exactly-once side effects,
    clean pin/resource accounting). Same seed ⟹ same injected faults."""
    from ray_tpu.devtools import chaos

    if args.plan:
        with open(args.plan) as f:
            plan = chaos.FaultPlan.from_json(f.read())
        if args.seed is not None:
            plan.seed = args.seed
    else:
        plan = chaos.canonical_plan(args.seed or 0)
    report = chaos.run_scenario(plan, num_nodes=args.nodes,
                                tasks=args.tasks, actors=args.actors,
                                calls=args.calls)
    if args.json:
        print(json.dumps(report, indent=2, default=str))
    else:
        print(f"seed={report['seed']} rules={report['rules']} "
              f"injected={report['injected_driver_side']} "
              f"elapsed={report['elapsed_s']}s")
        for v in report["violations"]:
            print(f"  VIOLATION: {v}")
        print("OK" if report["ok"] else "FAILED")
    sys.exit(0 if report["ok"] else 1)


def cmd_dashboard(args):
    """Serve the HTTP dashboard against a running cluster
    (ref: dashboard/head.py)."""
    import asyncio

    from ray_tpu.dashboard import DashboardHead

    h, p = args.address.rsplit(":", 1)

    async def _serve():
        head = DashboardHead((h, int(p)), session_dir=args.session_dir,
                             host=args.http_host, port=args.http_port)
        addr = await head.start()
        print(json.dumps({"dashboard_url": f"http://{addr[0]}:{addr[1]}"}),
              flush=True)
        while True:
            await asyncio.sleep(3600)

    asyncio.run(_serve())


def main():
    # `lint` routes before argparse: REMAINDER refuses leading optionals
    # (bpo-17050), and every lint arg is a passthrough anyway.
    if sys.argv[1:2] == ["lint"]:

        class _A:
            lint_args = sys.argv[2:]

        cmd_lint(_A())
        return

    p = argparse.ArgumentParser(prog="ray_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("start", help="start head daemons")
    s.add_argument("--host", default="127.0.0.1")
    s.add_argument("--port", type=int, default=0)
    s.add_argument("--resources", default="{}")
    s.add_argument("--system-config", default="{}")
    s.set_defaults(fn=cmd_start)

    s = sub.add_parser("stop", help="stop head daemons")
    s.set_defaults(fn=cmd_stop)

    s = sub.add_parser("microbenchmark",
                       help="core task/actor/object throughput suite "
                            "(ref: ray microbenchmark)")
    s.add_argument("--address", default=None)
    s.add_argument("--filter", action="append", default=None)
    s.add_argument("--min-seconds", type=float, default=2.0)
    s.set_defaults(fn=cmd_microbenchmark)

    for name, fn in [("status", cmd_status), ("summary", cmd_summary),
                     ("memory", cmd_memory), ("metrics", cmd_metrics),
                     ("stack", cmd_stack), ("internal-stats", cmd_istats)]:
        s = sub.add_parser(name)
        s.add_argument("--address", required=True)
        s.set_defaults(fn=fn)

    s = sub.add_parser("list")
    s.add_argument("what", choices=["nodes", "actors", "tasks", "jobs",
                                    "edges", "objects", "pgs"])
    s.add_argument("--address", required=True)
    s.set_defaults(fn=cmd_list)

    s = sub.add_parser("top", help="cluster resource hogs; `top mem` = "
                       "attributed memory by subsystem/holder "
                       "(observability/memory.py)")
    s.add_argument("what", choices=["mem"])
    s.add_argument("--address", required=True)
    s.add_argument("--limit", type=int, default=20)
    s.add_argument("--json", action="store_true",
                   help="raw memory_report() JSON")
    s.set_defaults(fn=cmd_top)

    s = sub.add_parser("doctor", help="cluster health triage: nodes, "
                       "beacons, drop counters (non-zero exit on failure)")
    s.add_argument("--address", required=True)
    s.add_argument("--verbose", action="store_true",
                   help="also print the full health report")
    s.set_defaults(fn=cmd_doctor)

    s = sub.add_parser("blackbox",
                       help="list/render flight-recorder post-mortems")
    s.add_argument("--dir", default=None,
                   help="dump directory (default: the flight default dir)")
    s.add_argument("--list", action="store_true")
    s.add_argument("--index", type=int, default=None,
                   help="which dump to render (default: newest)")
    s.add_argument("--chrome", default=None,
                   help="write the dump as a chrome trace to this path")
    s.add_argument("--tail", type=int, default=20)
    s.set_defaults(fn=cmd_blackbox)

    s = sub.add_parser("timeline")
    s.add_argument("--address", required=True)
    s.add_argument("--limit", type=int, default=10000)
    s.add_argument("--output", default=None)
    s.set_defaults(fn=cmd_timeline)

    s = sub.add_parser("debug", help="attach to a remote-pdb breakpoint")
    s.add_argument("--address", required=True)
    s.add_argument("--index", type=int, default=0)
    s.add_argument("--list", action="store_true")
    s.set_defaults(fn=cmd_debug)

    s = sub.add_parser("gateway", help="run a client gateway "
                       "(remote drivers: python thin client + C++ API)")
    s.add_argument("--address", required=True, help="GCS host:port")
    s.add_argument("--host", default="0.0.0.0")
    s.add_argument("--port", type=int, default=10001)
    s.set_defaults(fn=cmd_gateway)

    s = sub.add_parser("dashboard", help="run the HTTP dashboard")
    s.add_argument("--address", required=True, help="GCS host:port")
    s.add_argument("--session-dir", default="")
    s.add_argument("--http-host", default="127.0.0.1")
    s.add_argument("--http-port", type=int, default=8265)
    s.set_defaults(fn=cmd_dashboard)

    s = sub.add_parser("serve", help="serve deploy/status/shutdown")
    s.add_argument("serve_cmd", choices=["deploy", "status", "shutdown"])
    s.add_argument("--address", required=True)
    s.add_argument("--config", default=None, help="config file for deploy")
    s.set_defaults(fn=cmd_serve)

    s = sub.add_parser("lint", help="raylint static analysis "
                       "(`ray_tpu lint -- --help` for its flags)")
    s.add_argument("lint_args", nargs=argparse.REMAINDER,
                   help="passed through to python -m ray_tpu.devtools.lint "
                        "(paths, --changed-only, --fail-on, --json, ...)")
    s.set_defaults(fn=cmd_lint)

    s = sub.add_parser("chaos", help="run the seeded fault-injection "
                       "scenario (devtools.chaos) on an ephemeral cluster")
    s.add_argument("--seed", type=int, default=None,
                   help="FaultPlan seed (same seed ⟹ same fault sequence)")
    s.add_argument("--plan", default=None,
                   help="FaultPlan JSON file (default: the canonical "
                        "drop/reorder/duplicate/black-hole mix)")
    s.add_argument("--nodes", type=int, default=1)
    s.add_argument("--tasks", type=int, default=8)
    s.add_argument("--actors", type=int, default=2)
    s.add_argument("--calls", type=int, default=4)
    s.add_argument("--json", action="store_true",
                   help="print the full report (incl. the injected-fault "
                        "sequence) as JSON")
    s.set_defaults(fn=cmd_chaos)

    # cluster launcher (ref: scripts.py:1238,1314,1398,1696 up/down/
    # attach/exec over the NodeProvider API)
    s = sub.add_parser("up", help="bring a cluster up from a YAML config")
    s.add_argument("cluster_yaml")
    s.add_argument("--restart", action="store_true")
    s.set_defaults(fn=lambda a: _launcher().up(a.cluster_yaml,
                                               restart=a.restart))

    s = sub.add_parser("down", help="tear a cluster down")
    s.add_argument("cluster_yaml")
    s.set_defaults(fn=lambda a: _launcher().down(a.cluster_yaml))

    s = sub.add_parser("exec", help="run a shell command on the cluster")
    s.add_argument("cluster_yaml")
    s.add_argument("command")
    s.set_defaults(fn=lambda a: sys.exit(
        _launcher().exec_cmd(a.cluster_yaml, a.command)))

    s = sub.add_parser("submit", help="run a python script on the cluster")
    s.add_argument("cluster_yaml")
    s.add_argument("script")
    s.add_argument("script_args", nargs="*")
    s.set_defaults(fn=lambda a: sys.exit(
        _launcher().submit(a.cluster_yaml, a.script, *a.script_args)))

    s = sub.add_parser("attach",
                       help="shell with the cluster address exported")
    s.add_argument("cluster_yaml")
    s.set_defaults(fn=lambda a: sys.exit(_launcher().attach(a.cluster_yaml)))

    s = sub.add_parser("cluster-status", help="launcher-level status")
    s.add_argument("cluster_yaml")
    s.set_defaults(fn=lambda a: _launcher().status(a.cluster_yaml))

    args = p.parse_args()
    args.fn(args)


def _launcher():
    from ray_tpu.autoscaler import launcher

    return launcher


if __name__ == "__main__":
    main()
