"""Multi-node-on-one-machine test cluster.

Reference: python/ray/cluster_utils.py:99 — spawn one GCS plus N nodelets as
separate processes with faked resources to exercise distributed scheduling,
spillback, object transfer, and failure handling without real hosts.
"""

from __future__ import annotations

import os
import signal
import time
from typing import Any, Dict, List, Optional

from ray_tpu.core.config import Config
from ray_tpu.core.node import new_session_dir, start_gcs, start_nodelet


class ClusterNode:
    def __init__(self, proc, addr, node_id_hex, store_name):
        self.proc = proc
        self.addr = addr
        self.node_id_hex = node_id_hex
        self.store_name = store_name

    def kill(self):
        """Hard-kill the nodelet (and its workers) — chaos testing
        (ref: NodeKillerActor _private/test_utils.py:1400)."""
        try:
            os.killpg(os.getpgid(self.proc.pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            try:
                self.proc.kill()
            except Exception:
                pass
        self.proc.wait(timeout=5)


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_resources: Optional[Dict[str, float]] = None,
                 system_config: Optional[Dict[str, Any]] = None):
        self.cfg = Config.load(system_config)
        self.session_dir = new_session_dir()
        self.gcs_proc, self.gcs_addr = start_gcs(self.session_dir, self.cfg)
        self.nodes: List[ClusterNode] = []
        if initialize_head:
            self.add_node(resources=head_resources or {"CPU": 2.0})

    @property
    def address(self) -> str:
        return f"{self.gcs_addr[0]}:{self.gcs_addr[1]}"

    def add_node(self, resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, Any]] = None) -> ClusterNode:
        res = dict(resources or {"CPU": 2.0})
        res.setdefault("CPU", 2.0)
        proc, addr, node_id_hex, store_name = start_nodelet(
            self.session_dir, self.cfg, self.gcs_addr, resources=res,
            labels=labels, log_name=f"nodelet-{len(self.nodes)}")
        node = ClusterNode(proc, addr, node_id_hex, store_name)
        self.nodes.append(node)
        return node

    def remove_node(self, node: ClusterNode):
        node.kill()
        self.nodes.remove(node)

    def kill_gcs(self):
        """Hard-kill the control plane (ref: GCS fault-tolerance tests,
        test_gcs_fault_tolerance.py)."""
        try:
            self.gcs_proc.kill()
            self.gcs_proc.wait(timeout=5)
        except Exception:
            pass

    def restart_gcs(self):
        """Restart GCS on the SAME address so nodelets/drivers reconnect.
        Requires cfg.gcs_storage='file' for state to survive."""
        self.kill_gcs()
        self.gcs_proc, self.gcs_addr = start_gcs(
            self.session_dir, self.cfg, host=self.gcs_addr[0],
            port=self.gcs_addr[1])

    def connect(self, **kwargs):
        import ray_tpu

        return ray_tpu.init(address=self.address, **kwargs)

    def shutdown(self):
        import ray_tpu

        if ray_tpu.is_initialized():
            ray_tpu.shutdown()
        for n in self.nodes:
            try:
                n.kill()
            except Exception:
                pass
        try:
            self.gcs_proc.terminate()
            self.gcs_proc.wait(timeout=3)
        except Exception:
            try:
                self.gcs_proc.kill()
            except Exception:
                pass
