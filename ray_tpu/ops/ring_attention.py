"""Ring attention: sequence-parallel causal attention over the 'sp' mesh axis.

The long-context strategy SURVEY.md §5.7 requires (absent in the reference,
which delegates long sequences to wrapped frameworks). Each chip holds a
contiguous sequence chunk of Q, K, V; K/V blocks rotate around the ICI ring
via jax.lax.ppermute while every chip accumulates its chunk's attention with
the online-softmax recurrence. After sp steps every Q has attended to every
K/V at O(S/sp) activation memory per chip, with the transfers overlapping
compute (XLA schedules the ppermute DMA concurrently with the local block
matmul — the Pallas-level fused variant is a later-round optimization).

Call inside shard_map with q/k/v sharded on the seq axis:
    jax.shard_map(lambda q, k, v: ring_attention(q, k, v, axis_name="sp"),
                  mesh=mesh, in_specs=P(None, "sp", None, None), ...)

Causality across chunks: chunk i attends fully to chunks j < i, causally to
its own chunk, not at all to j > i — masking is done per rotation step from
the global chunk offsets, so the math exactly matches full causal attention.

Differentiable: the whole recurrence is jnp + ppermute, which have transpose
rules; jax.grad threads the ring backward automatically.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ray_tpu.parallel import _compat  # noqa: F401 — installs jax.shard_map

NEG_INF = -1e30


def _block_attn(q, k, v, mask):
    """One (q-chunk x kv-chunk) block. q [B,S,KV,G,D]; k/v [B,T,KV,D].
    Returns unnormalized o plus (m, l) for the online-softmax merge."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bskgd,btkd->bskgt", q, k,
                   preferred_element_type=jnp.float32) * scale
    s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1)
    # all-masked rows: keep m finite so exp() underflows to 0 cleanly
    m = jnp.maximum(m, -1e29)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bskgt,btkd->bskgd", p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)
    return o, m, l


def ring_attention(q, k, v, axis_name: str = "sp", causal: bool = True):
    """q [B,Sc,H,D], k/v [B,Sc,KV,D] — Sc is this chip's chunk.
    Must be called inside shard_map/pjit with `axis_name` bound."""
    B, Sc, H, D = q.shape
    KV = k.shape[2]
    G = H // KV
    sp = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)

    q5 = q.reshape(B, Sc, KV, G, D).astype(jnp.float32)
    pos_q = jnp.arange(Sc)
    pos_k = jnp.arange(Sc)

    def mask_for(kv_chunk_idx):
        if not causal:
            return jnp.ones((1, Sc, 1, 1, Sc), bool)
        # global positions: q at my*Sc + i, k at kv_chunk_idx*Sc + j
        qg = my * Sc + pos_q
        kg = kv_chunk_idx * Sc + pos_k
        return (qg[:, None] >= kg[None, :])[None, :, None, None, :]

    def step(carry, _):
        o, m, l, kk, vv, src = carry
        bo, bm, bl = _block_attn(q5, kk.astype(q.dtype), vv, mask_for(src))
        m_new = jnp.maximum(m, bm)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(bm - m_new)
        l_new = l * alpha + bl * beta
        o_new = o * alpha[..., None] + bo * beta[..., None]
        # rotate kv to the next chip on the ring (ICI neighbor exchange)
        perm = [(i, (i + 1) % sp) for i in range(sp)]
        kk = jax.lax.ppermute(kk, axis_name, perm)
        vv = jax.lax.ppermute(vv, axis_name, perm)
        src = jax.lax.ppermute(src, axis_name, perm)
        return (o_new, m_new, l_new, kk, vv, src), None

    o0 = jnp.zeros((B, Sc, KV, G, D), jnp.float32)
    m0 = jnp.full((B, Sc, KV, G), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, Sc, KV, G), jnp.float32)
    # JAX >= 0.8 tracks "varying manual axes" through shard_map: literals
    # created inside the body are unvarying while the rotated kv is varying;
    # promote the accumulators so the scan carry types line up.
    if hasattr(jax.lax, "pcast"):
        o0, m0, l0 = (jax.lax.pcast(x, (axis_name,), to="varying")
                      for x in (o0, m0, l0))
    carry = (o0, m0, l0, k, v, my)
    (o, m, l, _, _, _), _ = jax.lax.scan(step, carry, None, length=sp)
    l = jnp.maximum(l, 1e-30)
    out = (o / l[..., None]).reshape(B, Sc, H, D)
    return out.astype(q.dtype)
