"""ray_tpu.ops: TPU kernels (Pallas) and collective attention algorithms.

- flash_attention: fused causal attention forward (Pallas, VMEM-blocked
  online softmax) with a memory-bounded chunked backward.
- ring_attention: sequence-parallel attention over the 'sp' mesh axis —
  KV blocks rotate around the ICI ring via ppermute while each chip keeps
  its queries resident (SURVEY.md §5.7: absent in the reference; first-class
  here).

Kernels run under `interpret=True` automatically on CPU (tests); compiled
Mosaic on TPU.
"""

from ray_tpu.ops.flash_attention import flash_attention
from ray_tpu.ops.ring_attention import ring_attention

__all__ = ["flash_attention", "ring_attention"]
