"""Paged decode attention for the continuous-batching LLM engine.

SURVEY.md §7.9 hard parts ("paged attention kernels (Pallas)") /
VERDICT r2 item 5. KV lives in a global pool of fixed-size pages,
[num_pages, page_size, KV, HD]; each decode slot owns a list of page
indices (its page table) instead of a contiguous [max_seq] stripe, so
HBM scales with TOKENS IN USE, not worst-case-per-slot (the vLLM
memory model, re-designed for XLA's static shapes).

TPU kernel design: one grid instance per (slot, kv_head, page). The
page table and per-slot lengths ride in as SCALAR-PREFETCH arguments
(pltpu.PrefetchScalarGridSpec) so the k/v BlockSpec index_maps can
point each grid step's DMA at that slot's next physical page — Mosaic
fetches exactly the pages the slot owns, never materializing the
gathered [slots, max_pages*page_size] view the way an XLA gather
would. Out-of-range steps clamp their index (repeat DMA, elided) and
skip compute via pl.when; online-softmax state (acc/m/l) lives in VMEM
scratch across the page steps of one (slot, kv_head), exactly like
ops/flash_attention.py's streaming kernel.

Shapes: q [S, H, HD] (one new token per slot), pools [KV, NP, ps, HD]
(kv-head major so the kernel's page block keeps (ps, HD) as its last two
dims — a Mosaic tiling requirement), page_table [S, maxP] int32,
lengths [S] int32 (tokens INCLUDING the current one). Output [S, H, HD].
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def paged_attention_reference(q, k_pool, v_pool, page_table, lengths):
    """Pure-XLA reference: gather the pages, mask, attend. Materializes
    the [S, maxP*ps] view — fine for CPU tests and as the interpret-mode
    fallback; the kernel exists to avoid exactly this materialization."""
    S, H, HD = q.shape
    KV, NP, ps, _ = k_pool.shape
    maxP = page_table.shape[1]
    groups = H // KV
    k = k_pool[:, page_table].reshape(KV, S, maxP * ps, HD)  # [KV, S, T, HD]
    v = v_pool[:, page_table].reshape(KV, S, maxP * ps, HD)
    qf = q.reshape(S, KV, groups, HD).astype(jnp.float32)
    kf = k.astype(jnp.float32)
    s = jnp.einsum("skgd,kstd->skgt", qf, kf) * (HD ** -0.5)
    pos = jnp.arange(maxP * ps)[None, :]                   # [1, T]
    mask = pos < lengths[:, None]                          # [S, T]
    s = jnp.where(mask[:, None, None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    # fully-masked rows (inactive slots) produce uniform p; output unused
    out = jnp.einsum("skgt,kstd->skgd", p, v.astype(jnp.float32))
    return out.reshape(S, H, HD).astype(q.dtype)


def _kernel(pt_ref, len_ref, q_ref, k_ref, v_ref, o_ref, acc, m_scr, l_scr,
            *, page_size: int, max_pages: int, scale: float):
    """Grid (S, KV, maxP). pt_ref/len_ref are scalar-prefetched."""
    s = pl.program_id(0)
    p = pl.program_id(2)

    @pl.when(p == 0)
    def _init():
        acc[...] = jnp.zeros_like(acc)
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)

    length = len_ref[s]
    # number of pages this slot actually uses (0 for inactive slots)
    n_pages = jax.lax.div(length + page_size - 1, page_size)

    @pl.when(p < n_pages)
    def _step():
        q = q_ref[0, 0]                                # [G, HD]
        k = k_ref[0, 0]                                # [ps, HD]
        v = v_ref[0, 0]
        st = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32) * scale
        tok = p * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)
        st = jnp.where(tok < length, st, NEG_INF)      # [G, ps]
        m = m_scr[...][:, 0:1]
        l = l_scr[...][:, 0:1]
        m_new = jnp.maximum(m, jnp.max(st, axis=1, keepdims=True))
        pr = jnp.exp(st - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(pr, axis=1, keepdims=True)
        acc[...] = acc[...] * alpha + jax.lax.dot(
            pr.astype(v.dtype), v, preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(p == max_pages - 1)
    def _finish():
        l = jnp.maximum(l_scr[...][:, 0:1], 1e-30)
        o_ref[0, 0] = (acc[...] / l).astype(o_ref.dtype)


def paged_decode_attention_inplace_reference(q, k_new, v_new, k_pool,
                                             v_pool, page_table, lengths):
    """Pure-XLA reference for the fused write+attend decode kernel:
    scatter the new token's k/v into each active slot's tip page, then
    attend. Inactive slots (length 0) write nothing."""
    S = q.shape[0]
    ps = k_pool.shape[2]
    pos = jnp.maximum(lengths - 1, 0)
    page = jnp.take_along_axis(page_table, (pos // ps)[:, None],
                               axis=1)[:, 0]
    # inactive rows write back what is already there (no trash page)
    off = pos % ps
    old_k = k_pool[:, page, off, :]                    # [KV, S, HD]
    old_v = v_pool[:, page, off, :]
    kn = k_new.transpose(1, 0, 2).astype(k_pool.dtype)  # [KV, S, HD]
    vn = v_new.transpose(1, 0, 2).astype(v_pool.dtype)
    live = (lengths > 0)[None, :, None]
    k_pool = k_pool.at[:, page, off, :].set(jnp.where(live, kn, old_k))
    v_pool = v_pool.at[:, page, off, :].set(jnp.where(live, vn, old_v))
    o = paged_attention_reference(q, k_pool, v_pool, page_table, lengths)
    return o, k_pool, v_pool


def _kernel_inplace(pt_ref, len_ref, q_ref, kn_ref, vn_ref, k_ref, v_ref,
                    o_ref, ko_ref, vo_ref, acc, m_scr, l_scr, *,
                    page_size: int, max_pages: int, scale: float):
    """Fused write+attend, grid (S, KV, maxP). The current token's k/v is
    patched into its (s, kv) tip-page block in registers, used for the
    online-softmax step, and stored back ONCE through the pool-aliased
    output — the pools never pass through an XLA scatter, whose
    KV-minor layout preference forced two full-pool layout copies
    (+6 GB transient at 2.7B) around the decode loop."""
    s = pl.program_id(0)
    p = pl.program_id(2)

    @pl.when(p == 0)
    def _init():
        acc[...] = jnp.zeros_like(acc)
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)

    length = len_ref[s]
    n_pages = jax.lax.div(length + page_size - 1, page_size)
    pos = jax.lax.max(length - 1, 0)
    wp = jax.lax.div(pos, page_size)        # tip-page ORDINAL for slot s
    off = jax.lax.rem(pos, page_size)
    is_wp = jnp.logical_and(p == wp, length > 0)

    @pl.when(p < n_pages)
    def _step():
        q = q_ref[0, 0]                                # [G, HD]
        k = k_ref[0, 0]                                # [ps, HD]
        v = v_ref[0, 0]
        # patch the new token into the tip page (registers, not HBM)
        row = jax.lax.broadcasted_iota(jnp.int32, (page_size, 1), 0)
        sel = jnp.logical_and(row == off, is_wp)       # [ps, 1]
        k = jnp.where(sel, kn_ref[0, 0].astype(k.dtype), k)   # kn [1, HD]
        v = jnp.where(sel, vn_ref[0, 0].astype(v.dtype), v)
        st = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32) * scale
        tok = p * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)
        st = jnp.where(tok < length, st, NEG_INF)      # [G, ps]
        m = m_scr[...][:, 0:1]
        l = l_scr[...][:, 0:1]
        m_new = jnp.maximum(m, jnp.max(st, axis=1, keepdims=True))
        pr = jnp.exp(st - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(pr, axis=1, keepdims=True)
        acc[...] = acc[...] * alpha + jax.lax.dot(
            pr.astype(v.dtype), v, preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)
        # store the patched tip page back through the aliased output —
        # only this one block per (s, kv) is ever written
        @pl.when(is_wp)
        def _write():
            ko_ref[0, 0] = k.astype(ko_ref.dtype)
            vo_ref[0, 0] = v.astype(vo_ref.dtype)

    @pl.when(p == max_pages - 1)
    def _finish():
        l = jnp.maximum(l_scr[...][:, 0:1], 1e-30)
        o_ref[0, 0] = (acc[...] / l).astype(o_ref.dtype)


def paged_decode_attention_inplace(q, k_new, v_new, k_pool, v_pool,
                                   page_table, lengths):
    """Fused decode step: write each active slot's new k/v [S, KV, HD]
    into its tip page AND attend, in one kernel. Pools are input/output
    ALIASED (callers must donate them); returns (o [S, H, HD], k_pool,
    v_pool). lengths INCLUDE the current token; length-0 slots skip both
    the write and the compute (callers mask their output)."""
    S, H, HD = q.shape
    KV, NP, ps, _ = k_pool.shape
    maxP = page_table.shape[1]
    G = H // KV
    if jax.default_backend() != "tpu":
        return paged_decode_attention_inplace_reference(
            q, k_new, v_new, k_pool, v_pool, page_table, lengths)

    qt = q.reshape(S, KV, G, HD)
    kn4 = k_new.reshape(S, KV, 1, HD)
    vn4 = v_new.reshape(S, KV, 1, HD)

    def q_idx(s, kv, p, pt, ln):
        return (s, kv, 0, 0)

    def kv_idx(s, kv, p, pt, ln):
        length = ln[s]
        n_pages = jax.lax.div(length + ps - 1, ps)
        j = jax.lax.min(p, jax.lax.max(n_pages - 1, 0))
        return (kv, pt[s, j], 0, 0)

    def write_idx(s, kv, p, pt, ln):
        # constant across p: the tip page for live slots; THE trash page
        # (0, reserved by PagePool) for length-0 rows. Pallas flushes
        # each (s, kv) output window even when the pl.when store never
        # fired, so a length-0 slot's flush must land on the
        # garbage-tolerant trash page — NOT pt[s, 0], which for an
        # occupied-but-decode-masked slot (mid-chunked-prefill) is a
        # real, possibly prefix-SHARED page.
        pos = jax.lax.max(ln[s] - 1, 0)
        pg = pt[s, jax.lax.div(pos, ps)]
        return (kv, jax.lax.select(ln[s] > 0, pg, 0), 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S, KV, maxP),
        in_specs=[
            pl.BlockSpec((1, 1, G, HD), q_idx),
            pl.BlockSpec((1, 1, 1, HD), q_idx),
            pl.BlockSpec((1, 1, 1, HD), q_idx),
            pl.BlockSpec((1, 1, ps, HD), kv_idx),
            pl.BlockSpec((1, 1, ps, HD), kv_idx),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, G, HD), q_idx),
            pl.BlockSpec((1, 1, ps, HD), write_idx),
            pl.BlockSpec((1, 1, ps, HD), write_idx),
        ],
        scratch_shapes=[
            pltpu.VMEM((G, HD), jnp.float32),
            pltpu.VMEM((G, 128), jnp.float32),
            pltpu.VMEM((G, 128), jnp.float32),
        ],
    )
    o, k_pool, v_pool = pl.pallas_call(
        functools.partial(_kernel_inplace, page_size=ps, max_pages=maxP,
                          scale=HD ** -0.5),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((S, KV, G, HD), q.dtype),
            jax.ShapeDtypeStruct(k_pool.shape, k_pool.dtype),
            jax.ShapeDtypeStruct(v_pool.shape, v_pool.dtype),
        ],
        input_output_aliases={5: 1, 6: 2},
    )(page_table, lengths, qt, kn4, vn4, k_pool, v_pool)
    return o.reshape(S, H, HD), k_pool, v_pool


def paged_attention(q, k_pool, v_pool, page_table, lengths):
    """q [S, H, HD] -> [S, H, HD]. lengths must INCLUDE the current
    token (its k/v already written to the pool). Inactive slots pass
    length 0 and read back garbage that callers mask."""
    S, H, HD = q.shape
    KV, NP, ps, _ = k_pool.shape
    maxP = page_table.shape[1]
    G = H // KV
    if jax.default_backend() != "tpu":
        return paged_attention_reference(q, k_pool, v_pool, page_table,
                                         lengths)

    # [S, KV, G, HD] so one grid instance owns one (slot, kv head)
    qt = q.reshape(S, KV, G, HD)

    def q_idx(s, kv, p, pt, ln):
        return (s, kv, 0, 0)

    def kv_idx(s, kv, p, pt, ln):
        # clamp into this slot's live pages: out-of-range steps repeat
        # the previous index so Mosaic elides their DMA
        length = ln[s]
        n_pages = jax.lax.div(length + ps - 1, ps)
        j = jax.lax.min(p, jax.lax.max(n_pages - 1, 0))
        return (kv, pt[s, j], 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S, KV, maxP),
        in_specs=[
            pl.BlockSpec((1, 1, G, HD), q_idx),
            pl.BlockSpec((1, 1, ps, HD), kv_idx),
            pl.BlockSpec((1, 1, ps, HD), kv_idx),
        ],
        out_specs=pl.BlockSpec((1, 1, G, HD), q_idx),
        scratch_shapes=[
            pltpu.VMEM((G, HD), jnp.float32),
            pltpu.VMEM((G, 128), jnp.float32),
            pltpu.VMEM((G, 128), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_kernel, page_size=ps, max_pages=maxP,
                          scale=HD ** -0.5),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, KV, G, HD), q.dtype),
    )(page_table, lengths, qt, k_pool, v_pool)
    return out.reshape(S, H, HD)
