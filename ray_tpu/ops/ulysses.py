"""Ulysses sequence parallelism: head-sharded all-to-all attention.

The second long-context strategy SURVEY.md §5.7 calls for (DeepSpeed
Ulysses, Jacobs et al. 2023 — absent in the reference, which delegates
long sequences to wrapped frameworks). Where ring attention keeps the
sequence sharded and rotates KV around the ICI ring (sp communication
steps), Ulysses does ONE all-to-all each way: scatter heads / gather
sequence, run full-sequence attention on H/sp local heads, then invert.
Communication volume is O(S·D·H/sp) per chip independent of sp, so it
beats the ring when the head count comfortably divides over the axis and
the full-S attention fits memory; the ring wins at extreme S. Both are
mesh-axis presets over the same 'sp' axis — pick per workload.

Call inside shard_map with q/k/v sharded on the seq axis:
    jax.shard_map(lambda q, k, v: ulysses_attention(q, k, v),
                  mesh=mesh, in_specs=P(None, "sp", None, None), ...)

Constraints: n_heads % sp == 0 and n_kv_heads % sp == 0 (contiguous head
blocks keep GQA groups chip-local; the group ratio G = H/KV is preserved
because H/sp = G·(KV/sp)).

Differentiable: all_to_all has a transpose rule (its inverse), so
jax.grad threads the exchange backward automatically.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ray_tpu.parallel import _compat  # noqa: F401 — installs jax.shard_map


def _full_attention(q, k, v, causal: bool):
    """Reference einsum attention with GQA broadcast (the per-chip compute
    after the exchange; mirrors models/llama.py _attention_xla, duplicated
    here so ops does not import models)."""
    B, S, H, D = q.shape
    T, KV = k.shape[1], k.shape[2]
    groups = H // KV
    q = q.reshape(B, S, KV, groups, D)
    scores = jnp.einsum("bskgd,btkd->bkgst", q, k,
                        preferred_element_type=jnp.float32) / (D ** 0.5)
    if causal:
        mask = jnp.arange(S)[:, None] >= jnp.arange(T)[None, :]
        scores = jnp.where(mask[None, None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bkgst,btkd->bskgd", probs, v)
    return out.reshape(B, S, H, D)


def ulysses_attention(q, k, v, axis_name: str = "sp",
                      causal: bool = True):
    """q [B, Sc, H, D], k/v [B, Sc, KV, D] — Sc is this chip's sequence
    chunk. Must be called inside shard_map/pjit with `axis_name` bound.
    Positions (RoPE) must already be applied with global offsets, exactly
    as the ring path does."""
    sp = jax.lax.axis_size(axis_name)
    H, KV = q.shape[2], k.shape[2]
    if H % sp or KV % sp:
        raise ValueError(
            f"ulysses needs heads divisible by the sp axis: "
            f"H={H}, KV={KV}, sp={sp} (use ring attention instead)")

    def scatter_heads(x):
        # [B, Sc, N, D] -> [B, Sc*sp, N/sp, D]: each chip receives every
        # chip's chunk for its head block (one ICI all-to-all)
        return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                  concat_axis=1, tiled=True)

    qg = scatter_heads(q)
    kg = scatter_heads(k)
    vg = scatter_heads(v)
    out = _full_attention(qg, kg, vg, causal)
    # inverse exchange: split seq back out, gather this chip's heads
    return jax.lax.all_to_all(out, axis_name, split_axis=1,
                              concat_axis=2, tiled=True)
