"""Fused causal attention (flash-style) for TPU in Pallas.

Forward: one kernel instance per (batch, head, q-block); the q-block stays in
VMEM while K/V stream through in chunks with the online-softmax recurrence —
O(S) memory instead of O(S^2), and the QK^T / PV matmuls hit the MXU at
[block_q x head_dim] x [head_dim x block_k] granularity.

Backward: full Pallas two-kernel backward (FlashAttention-2 style): a dQ
pass gridded over q-blocks and a dK/dV pass gridded over k-blocks, both
recomputing probabilities from the saved log-sum-exp so nothing O(S^2) is
ever materialized. A chunked-recompute JAX fallback remains selectable via
BACKWARD_IMPL for debugging.

GQA is handled in the kernel via the k/v index maps (kv_head = head // group)
— no KV broadcast materialization.

Shapes: q [B, S, H, D], k/v [B, T, KV, D], output [B, S, H, D].
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _fwd_kernel_loop(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int,
                     scale: float, causal: bool):
    """Full-K/V-resident variant: one grid instance per q-block streams
    k-blocks in a fori_loop. Fewer grid steps than the ki-minor kernel —
    faster at short/medium S where per-step overhead dominates; the
    ki-minor streaming kernel wins for windowed long-S (it never fetches
    out-of-band K/V)."""
    # q_ref: [1, 1, block_q, D]; k_ref/v_ref: [1, 1, T, D]
    block_q, D = q_ref.shape[2], q_ref.shape[3]
    T = k_ref.shape[2]
    qi = pl.program_id(2)
    # operands keep the input dtype (bf16 MXU rate); f32 accumulation
    q = q_ref[0, 0]

    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)

    def body(ki, carry):
        o, m, l = carry
        k = k_ref[0, 0, pl.ds(ki * block_k, block_k), :]
        v = v_ref[0, 0, pl.ds(ki * block_k, block_k), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1, keepdims=True)
        o_new = o * alpha + jax.lax.dot(p.astype(v.dtype), v,
                                        preferred_element_type=jnp.float32)
        return o_new, m_new, l_new

    o0 = jnp.zeros((block_q, D), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    if causal:
        # only k-blocks at or before this q-block contribute
        num_k = jax.lax.div((qi + 1) * block_q + block_k - 1, block_k)
    else:
        num_k = T // block_k
    o, m, l = jax.lax.fori_loop(0, num_k, body, (o0, m0, l0))
    l = jnp.maximum(l, 1e-30)
    o_ref[0, 0] = (o / l).astype(o_ref.dtype)
    # Lane-broadcast (Mosaic wants last-dim 128 blocks; official TPU flash
    # kernel stores l/m the same way).
    lse_ref[0, 0] = jnp.broadcast_to(m + jnp.log(l), (block_q, 128))


def _flash_fwd_loop(q, k, v, *, causal: bool, block_q: int, block_k: int):
    B, S, H, D = q.shape
    T, KV = k.shape[1], k.shape[2]
    groups = H // KV
    scale = D ** -0.5
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    block_q = min(block_q, S)
    block_k = min(block_k, T)
    grid = (B, H, S // block_q)

    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel_loop, block_k=block_k, scale=scale,
                          causal=causal),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, T, D),
                         lambda b, h, i, g=groups: (b, h // g, 0, 0)),
            pl.BlockSpec((1, 1, T, D),
                         lambda b, h, i, g=groups: (b, h // g, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, 128), lambda b, h, i: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, S, 128), jnp.float32),
        ],
        interpret=_use_interpret(),
    )(qt, kt, vt)
    return out.transpose(0, 2, 1, 3), lse


def _fwd_kernel_stream(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_scr,
                       l_scr, *, block_q: int, block_k: int, scale: float,
                       causal: bool, window: int, num_k: int):
    """ki-minor streaming variant: grid (B, H, q-blocks, k-blocks).
    K/V arrive one block per step through a CLAMPED index_map, so blocks
    outside the causal/window band are never fetched (Mosaic elides the
    DMA when the block index repeats) — O(S*W) HBM traffic for sliding
    windows instead of O(S*T). acc/m/l live in VMEM scratch across the
    ki steps of one q-block (same structure as the official TPU flash
    kernel); the last ki step normalizes and writes o/lse."""
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        acc[...] = jnp.zeros_like(acc)
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)

    run = True
    if causal:
        run = qi * block_q + block_q > ki * block_k
        if window > 0:
            run = run & (qi * block_q < (ki + 1) * block_k + window)

    @pl.when(run)
    def _step():
        # operands stay in the input dtype (bf16 on TPU: 8x the f32 MXU
        # rate); the MXU accumulates in f32 via preferred_element_type —
        # an f32 cast here made the whole kernel f32-matmul-bound
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, 1), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1)
            keep = q_pos >= k_pos
            if window > 0:
                keep = keep & (q_pos - k_pos < window)
            s = jnp.where(keep, s, NEG_INF)
        m = m_scr[...][:, 0:1]
        l = l_scr[...][:, 0:1]
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1, keepdims=True)
        # p joins v's dtype for the second MXU pass (f32 accumulation);
        # standard flash practice, same as the official TPU kernel
        acc[...] = acc[...] * alpha + jax.lax.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == num_k - 1)
    def _finish():
        l = jnp.maximum(l_scr[...][:, 0:1], 1e-30)
        o_ref[0, 0] = (acc[...] / l).astype(o_ref.dtype)
        lse_ref[0, 0] = m_scr[...] + jnp.log(l)


def _flash_fwd(q, k, v, *, causal: bool, block_q: int, block_k: int,
               window: int = 0):
    if window <= 0:
        # plain causal/full: the q-block loop kernel has 1/num_k the
        # grid steps — faster where per-step overhead dominates
        return _flash_fwd_loop(q, k, v, causal=causal, block_q=block_q,
                               block_k=block_k)
    B, S, H, D = q.shape
    T, KV = k.shape[1], k.shape[2]
    groups = H // KV
    scale = D ** -0.5
    # layout: [B, H, S, D] per-instance slices
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    block_q = min(block_q, S)
    block_k = min(block_k, T)
    num_k = T // block_k
    grid = (B, H, S // block_q, num_k)

    def kv_idx(b, h, qi, ki, g=groups):
        # clamp into the band: out-of-band steps repeat a neighboring
        # index, so Mosaic elides their K/V DMA entirely
        j = ki
        if causal:
            hi = jax.lax.div(qi * block_q + block_q - 1, block_k)
            j = jax.lax.min(j, hi)
            if window > 0:
                lo = jax.lax.max(
                    0, jax.lax.div(qi * block_q - window + 1, block_k))
                j = jax.lax.max(j, lo)
        return (b, h // g, j, 0)

    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel_stream, block_q=block_q,
                          block_k=block_k, scale=scale, causal=causal,
                          window=window, num_k=num_k),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_k, D), kv_idx),
            pl.BlockSpec((1, 1, block_k, D), kv_idx),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, D),
                         lambda b, h, qi, ki: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 128),
                         lambda b, h, qi, ki: (b, h, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, S, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),     # acc
            pltpu.VMEM((block_q, 128), jnp.float32),   # running max
            pltpu.VMEM((block_q, 128), jnp.float32),   # running sum
        ],
        interpret=_use_interpret(),
    )(qt, kt, vt)
    return out.transpose(0, 2, 1, 3), lse


def _bwd_dq_kernel(q_ref, k_ref, v_ref, g_ref, o_ref, lse_ref, dq_ref, *,
                   block_k: int, scale: float, causal: bool, window: int):
    """One instance per (b, h, q-block): stream K/V, accumulate dQ
    (FlashAttention-2 backward, dQ pass). delta = rowsum(o * dO) is
    computed in-kernel from the resident blocks."""
    block_q, D = q_ref.shape[2], q_ref.shape[3]
    T = k_ref.shape[2]
    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32)
    g = g_ref[0, 0].astype(jnp.float32)
    o = o_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0][:, 0:1]
    delta = jnp.sum(o * g, axis=-1, keepdims=True)
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)

    def body(ki, dq):
        k = k_ref[0, 0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1)
            keep = q_pos >= k_pos
            if window > 0:
                keep = keep & (q_pos - k_pos < window)
            s = jnp.where(keep, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(g, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        return dq + jax.lax.dot(ds, k, preferred_element_type=jnp.float32)

    start_k = 0
    if causal:
        num_k = jax.lax.div((qi + 1) * block_q + block_k - 1, block_k)
        if window > 0:
            start_k = jax.lax.max(
                0, jax.lax.div(qi * block_q - window + 1, block_k))
    else:
        num_k = T // block_k
    dq = jax.lax.fori_loop(start_k, num_k,
                           body, jnp.zeros((block_q, D), jnp.float32))
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _bwd_dkdv_kernel(q_ref, k_ref, v_ref, g_ref, o_ref, lse_ref,
                     dk_ref, dv_ref, *, block_q: int, scale: float,
                     causal: bool, window: int):
    """Grid (b, h, k-block, q-block): the dk/dv output block is constant in
    the (minor) q axis, so Mosaic keeps it resident and this accumulates
    across sequential q steps — O(block) VMEM at any sequence length
    (FlashAttention-2 backward, dK/dV pass). dK/dV land per-query-head;
    the wrapper sums over GQA groups."""
    block_k, D = k_ref.shape[2], k_ref.shape[3]
    ki = pl.program_id(2)
    qi = pl.program_id(3)

    @pl.when(qi == 0)
    def _zero():
        dk_ref[0, 0] = jnp.zeros_like(dk_ref[0, 0])
        dv_ref[0, 0] = jnp.zeros_like(dv_ref[0, 0])

    # Causal: a q-block strictly above the diagonal contributes nothing.
    run = True
    if causal:
        run = (qi + 1) * block_q > ki * block_k
        if window > 0:
            # windowed: q-blocks wholly past the window skip this k-block
            run = run & (qi * block_q < (ki + 1) * block_k + window)

    @pl.when(run)
    def _accumulate():
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        q = q_ref[0, 0].astype(jnp.float32)
        g = g_ref[0, 0].astype(jnp.float32)
        o = o_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, 0:1]
        delta = jnp.sum(o * g, axis=-1, keepdims=True)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, 1), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1)
            keep = q_pos >= k_pos
            if window > 0:
                keep = keep & (q_pos - k_pos < window)
            s = jnp.where(keep, s, NEG_INF)
        p = jnp.exp(s - lse)                                   # [bq, bk]
        dv_ref[0, 0] += jax.lax.dot_general(
            p, g, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                # p^T @ g
        dp = jax.lax.dot_general(g, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_ref[0, 0] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                # ds^T @ q


def _flash_pallas_bwd(res, g, *, causal: bool, block_q: int, block_k: int,
                      window: int = 0):
    """Full Pallas backward: two kernels (dQ; dK/dV), GQA group-sum on the
    dK/dV results (FlashAttention-2, Dao 2023)."""
    q, k, v, out, lse = res
    B, S, H, D = q.shape
    T, KV = k.shape[1], k.shape[2]
    groups = H // KV
    scale = D ** -0.5
    block_q = min(block_q, S)
    block_k = min(block_k, T)

    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    gt = g.transpose(0, 2, 1, 3)
    ot = out.transpose(0, 2, 1, 3)

    q_blk = pl.BlockSpec((1, 1, block_q, D), lambda b, h, i: (b, h, i, 0))
    kv_spec = pl.BlockSpec((1, 1, T, D),
                           lambda b, h, i, g_=groups: (b, h // g_, 0, 0))
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, block_k=block_k, scale=scale,
                          causal=causal, window=window),
        grid=(B, H, S // block_q),
        in_specs=[
            q_blk,
            kv_spec,
            kv_spec,
            q_blk,
            q_blk,
            pl.BlockSpec((1, 1, block_q, 128), lambda b, h, i: (b, h, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, D),
                               lambda b, h, i: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
        interpret=_use_interpret(),
    )(qt, kt, vt, gt, ot, lse)

    q_stream = pl.BlockSpec((1, 1, block_q, D),
                            lambda b, h, i, j: (b, h, j, 0))
    kv_blk = pl.BlockSpec((1, 1, block_k, D),
                          lambda b, h, i, j, g_=groups: (b, h // g_, i, 0))
    dkv_spec = pl.BlockSpec((1, 1, block_k, D),
                            lambda b, h, i, j: (b, h, i, 0))
    dk_h, dv_h = pl.pallas_call(
        functools.partial(_bwd_dkdv_kernel, block_q=block_q, scale=scale,
                          causal=causal, window=window),
        grid=(B, H, T // block_k, S // block_q),
        in_specs=[
            q_stream,
            kv_blk,
            kv_blk,
            q_stream,
            q_stream,
            pl.BlockSpec((1, 1, block_q, 128),
                         lambda b, h, i, j: (b, h, j, 0)),
        ],
        out_specs=[dkv_spec, dkv_spec],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, T, D), jnp.float32),
            jax.ShapeDtypeStruct((B, H, T, D), jnp.float32),
        ],
        interpret=_use_interpret(),
    )(qt, kt, vt, gt, ot, lse)

    # GQA: sum per-query-head contributions into each kv head.
    dk = dk_h.reshape(B, KV, groups, T, D).sum(2).transpose(0, 2, 1, 3)
    dv = dv_h.reshape(B, KV, groups, T, D).sum(2).transpose(0, 2, 1, 3)
    return dq.transpose(0, 2, 1, 3), dk.astype(k.dtype), dv.astype(v.dtype)


def _reference_chunked_bwd(res, g, *, causal: bool, chunk: int,
                           window: int = 0):
    """Recompute-based backward, chunked over the key axis to stay O(S*chunk)
    in memory. Uses the forward's lse so probabilities are exact."""
    q, k, v, out, lse = res
    lse = lse[..., 0]                                  # drop lane broadcast
    B, S, H, D = q.shape
    T, KV = k.shape[1], k.shape[2]
    groups = H // KV
    scale = D ** -0.5

    qf = q.astype(jnp.float32)
    of = out.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    delta = jnp.sum(of * gf, axis=-1)                  # [B, S, H]

    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    kg = kf[:, :, :, None, :]                           # [B,T,KV,1,D]
    vg = vf[:, :, :, None, :]
    q5 = qf.reshape(B, S, KV, groups, D)
    g5 = gf.reshape(B, S, KV, groups, D)
    lse5 = lse.transpose(0, 2, 1).reshape(B, S, KV, groups)
    delta5 = delta.reshape(B, S, KV, groups)
    q_pos = jnp.arange(S)

    nchunks = max(1, T // chunk)
    csize = T // nchunks

    def body(carry, ci):
        dq_acc = carry
        ks = jax.lax.dynamic_slice_in_dim(kg, ci * csize, csize, axis=1)
        vs = jax.lax.dynamic_slice_in_dim(vg, ci * csize, csize, axis=1)
        s = jnp.einsum("bskgd,btkud->bskgt", q5, ks) * scale  # u==1 squeezed
        if causal:
            k_pos = ci * csize + jnp.arange(csize)
            mask = q_pos[:, None] >= k_pos[None, :]
            if window > 0:
                mask = mask & (q_pos[:, None] - k_pos[None, :] < window)
            s = jnp.where(mask[None, :, None, None, :], s, NEG_INF)
        p = jnp.exp(s - lse5[..., None])                     # [B,S,KV,G,c]
        dv_c = jnp.einsum("bskgt,bskgd->btkd", p, g5)
        dp = jnp.einsum("bskgd,btkud->bskgt", g5, vs)
        ds = p * (dp - delta5[..., None]) * scale
        dq_c = jnp.einsum("bskgt,btkud->bskgd", ds, ks)
        dk_c = jnp.einsum("bskgt,bskgd->btkd", ds, q5)
        return dq_acc + dq_c, (dk_c, dv_c)

    dq0 = jnp.zeros_like(q5)
    dq, (dk_chunks, dv_chunks) = jax.lax.scan(body, dq0, jnp.arange(nchunks))
    dk = jnp.moveaxis(dk_chunks, 0, 1).reshape(B, T, KV, D)
    dv = jnp.moveaxis(dv_chunks, 0, 1).reshape(B, T, KV, D)
    return (dq.reshape(B, S, H, D).astype(q.dtype), dk.astype(k.dtype),
            dv.astype(v.dtype))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, block_q, block_k, window):
    out, _ = _flash_fwd(q, k, v, causal=causal, block_q=block_q,
                        block_k=block_k, window=window)
    return out


def _flash_vjp_fwd(q, k, v, causal, block_q, block_k, window):
    out, lse = _flash_fwd(q, k, v, causal=causal, block_q=block_q,
                          block_k=block_k, window=window)
    return out, (q, k, v, out, lse)


BACKWARD_IMPL = "pallas"   # "pallas" | "chunked" (recompute fallback)


def _flash_vjp_bwd(causal, block_q, block_k, window, res, g):
    if BACKWARD_IMPL == "pallas":
        return _flash_pallas_bwd(res, g, causal=causal, block_q=block_q,
                                 block_k=block_k, window=window)
    return _reference_chunked_bwd(res, g, causal=causal, chunk=block_k * 4,
                                  window=window)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, *, causal: bool = True, block_q: int = 512,
                    block_k: int = 512, window: Optional[int] = None):
    # 512x512 blocks measured +14% end-to-end over 256x256 on v5e at
    # S=1024 (llama-125m train step 110.5ms -> 95.5ms); scores block is
    # 1 MiB f32, comfortably inside VMEM alongside q/k/v tiles.
    """q [B,S,H,D], k/v [B,T,KV,D] -> [B,S,H,D]. S, T must divide blocks
    (pad upstream); returns in q.dtype. window=W (causal only) restricts
    each query to the last W keys — Mistral-style sliding-window
    attention; blocks wholly outside the band are skipped, so compute is
    O(S*W) instead of O(S^2)."""
    if window is not None and not causal:
        raise ValueError("window= requires causal=True")
    B, S, H, D = q.shape
    block_q = min(block_q, S)
    block_k = min(block_k, k.shape[1])
    while S % block_q:
        block_q //= 2
    while k.shape[1] % block_k:
        block_k //= 2
    return _flash(q, k, v, causal, max(block_q, 1), max(block_k, 1),
                  int(window or 0))
