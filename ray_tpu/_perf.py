"""Core-runtime microbenchmark harness.

Reference: python/ray/_private/ray_perf.py:120-318 (`ray microbenchmark`,
scripts.py:1821) — the canonical task/actor/object-plane throughput and
latency suite. Same dimensions, same methodology (timed loops against a
live cluster, ops/sec reported); run via `python -m ray_tpu.cli
microbenchmark` or programmatically with run_microbenchmarks().
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

import numpy as np

import ray_tpu


def _timeit(name: str, fn: Callable[[], int], results: List[dict],
            min_seconds: float = 2.0):
    """Run fn (returns #ops) until min_seconds elapsed; record ops/s."""
    fn()  # warmup
    ops = 0
    t0 = time.time()
    while time.time() - t0 < min_seconds:
        ops += fn()
    dt = time.time() - t0
    results.append({"name": name, "ops_per_s": round(ops / dt, 1),
                    "ops": ops, "seconds": round(dt, 2)})


@ray_tpu.remote
def _noop():
    return None


@ray_tpu.remote
def _noop_arg(x):
    return None


@ray_tpu.remote
class _Actor:
    def noop(self):
        return None

    def echo(self, x):
        return x


def run_microbenchmarks(which: Optional[List[str]] = None,
                        min_seconds: float = 2.0) -> List[dict]:
    """Runs against the current cluster (ray_tpu.init first).
    `which` filters by substring (like ray microbenchmark --filter)."""
    results: List[dict] = []

    def want(name: str) -> bool:
        return not which or any(w in name for w in which)

    # Pool warmup before any measurement (ref: ray_perf.py benchmarks
    # run against a warm cluster; ray prestarts workers at init): a
    # fractional-CPU fan-out forces the worker pool to steady state so
    # the first benchmarks don't measure worker spawn + jax import.
    # Skipped when only object-plane benches run — they need no workers.
    if any(want(n) for n in ("task_single", "task_batch", "task_args",
                             "actor")):
        ray_tpu.get([_noop.options(num_cpus=0.1).remote()
                     for _ in range(16)])

    # --- object plane (ref: ray_perf.py put/get benchmarks)
    if want("put_small"):
        def put_small():
            for _ in range(100):
                ray_tpu.put(b"x" * 100)
            return 100
        _timeit("put_small_100B", put_small, results, min_seconds)

    if want("put_get_1MiB"):
        buf = np.zeros(1 << 20, np.uint8)

        def put_get_large():
            for _ in range(10):
                ray_tpu.get(ray_tpu.put(buf))
            return 10
        _timeit("put_get_1MiB", put_get_large, results, min_seconds)

    if want("get_batch"):
        refs = [ray_tpu.put(i) for i in range(1000)]

        def get_batch():
            ray_tpu.get(refs)
            return 1000
        _timeit("get_batch_1k", get_batch, results, min_seconds)

    # --- task plane (ref: single/batch task invocation benchmarks)
    if want("task_single"):
        def task_single():
            ray_tpu.get(_noop.remote())
            return 1
        _timeit("task_roundtrip", task_single, results, min_seconds)

    if want("task_batch"):
        def task_batch():
            ray_tpu.get([_noop.remote() for _ in range(100)])
            return 100
        _timeit("task_batch_100", task_batch, results, min_seconds)

    if want("task_args"):
        ref = ray_tpu.put(np.zeros(1 << 16, np.uint8))

        def task_args():
            ray_tpu.get([_noop_arg.remote(ref) for _ in range(50)])
            return 50
        _timeit("task_obj_arg_64KiB", task_args, results, min_seconds)

    # --- actor plane (ref: actor call benchmarks)
    if want("actor"):
        a = _Actor.options(num_cpus=0.1).remote()
        ray_tpu.get(a.noop.remote())

        def actor_sync():
            ray_tpu.get(a.noop.remote())
            return 1
        _timeit("actor_call_roundtrip", actor_sync, results, min_seconds)

        def actor_pipelined():
            ray_tpu.get([a.noop.remote() for _ in range(100)])
            return 100
        _timeit("actor_calls_pipelined_100", actor_pipelined, results,
                min_seconds)
        ray_tpu.kill(a)

    return results


def main(argv=None):
    import argparse
    import json

    p = argparse.ArgumentParser()
    p.add_argument("--address", default=None)
    p.add_argument("--filter", action="append", default=None,
                   help="substring filter, repeatable")
    p.add_argument("--min-seconds", type=float, default=2.0)
    args = p.parse_args(argv)
    if args.address:
        ray_tpu.init(address=args.address)
    else:
        ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    try:
        for r in run_microbenchmarks(args.filter, args.min_seconds):
            print(json.dumps(r))
    finally:
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
