"""PG: vanilla policy gradient (REINFORCE).

Reference: rllib/algorithms/pg (pre-exile) — Monte-Carlo reward-to-go
returns, no critic, one gradient step per sampled batch. The simplest
on-policy baseline in the zoo; reuses PPO's discrete policy net and
rollout workers (the value head exists in the shared net but carries no
loss here, matching PG's critic-free objective).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict

import numpy as np

import ray_tpu
from ray_tpu.rl.core import (Algorithm, CPU_WORKER_ENV,
                             probe_env_spec,
                             reward_to_go, rollout_result)
from ray_tpu.rl.ppo import RolloutWorker, init_policy, policy_forward


@dataclass
class PGConfig:
    env: str = "CartPole-v1"
    env_config: Dict[str, Any] = field(default_factory=dict)
    num_rollout_workers: int = 2
    rollout_fragment_length: int = 100
    lr: float = 4e-3
    gamma: float = 0.99
    hidden: int = 64
    seed: int = 0


class PGTrainer(Algorithm):
    """ref: pg.py training_step — sample, compute returns, one policy
    gradient step on -logp * R."""

    def _setup(self, cfg: PGConfig):
        import jax
        import optax

        obs_dim, n_actions, _a, _h = probe_env_spec(cfg.env, cfg.env_config)
        assert n_actions is not None, "PG here supports discrete actions"
        self.params = init_policy(jax.random.PRNGKey(cfg.seed), obs_dim,
                                  n_actions, cfg.hidden)
        self.opt = optax.adam(cfg.lr)
        self.opt_state = self.opt.init(self.params)
        self.workers = [
            RolloutWorker.options(num_cpus=0.5, runtime_env=CPU_WORKER_ENV).remote(
                cfg.env, cfg.seed + i * 1000, cfg.env_config)
            for i in range(cfg.num_rollout_workers)]
        self.timesteps = 0
        self._update = jax.jit(self._make_update())

    def _make_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        def loss_fn(params, mb):
            logits, _values = policy_forward(params, mb["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, mb["actions"][:, None], axis=-1)[:, 0]
            pg_loss = -(logp * mb["returns"]).mean()
            entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
            return pg_loss, {"entropy": entropy}

        def update(params, opt_state, mb):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, mb)
            upd, opt_state = self.opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, upd)
            return params, opt_state, {"loss": loss, **aux}

        return update

    def training_step(self) -> Dict[str, Any]:
        import jax

        cfg = self.config
        params_host = jax.device_get(self.params)
        batches = ray_tpu.get([
            w.sample.remote(params_host, cfg.rollout_fragment_length)
            for w in self.workers])
        obs, actions, rets = [], [], []
        for b in batches:
            obs.append(b["obs"])
            actions.append(b["actions"])
            rets.append(reward_to_go(b, cfg.gamma))
        ret = np.concatenate(rets)
        ret = (ret - ret.mean()) / (ret.std() + 1e-8)
        mb = {"obs": np.concatenate(obs),
              "actions": np.concatenate(actions), "returns": ret}
        self.timesteps += len(ret)
        self.params, self.opt_state, aux = self._update(
            self.params, self.opt_state, mb)
        stats = ray_tpu.get([w.episode_stats.remote() for w in self.workers])
        return rollout_result(self.timesteps, stats, aux)

    def get_weights(self):
        return self.params

    def set_weights(self, weights):
        self.params = weights
