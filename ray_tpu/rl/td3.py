"""TD3: twin-delayed deep deterministic policy gradient.

Reference: rllib/algorithms/td3/ (twin critics, target policy smoothing,
delayed actor updates over the DDPG base ddpg/ddpg.py). Continuous
control; CPU rollout actors with Gaussian exploration noise, one jitted
learner update on the device.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

import numpy as np

import ray_tpu
from ray_tpu.rl.core import (CPU_WORKER_ENV, Algorithm, EnvSampler, ReplayBuffer, mlp_forward,
                             mlp_init, probe_env_spec)


def init_td3_nets(key, obs_dim: int, act_dim: int, hidden: int):
    import jax

    ks = jax.random.split(key, 3)
    actor = mlp_init(ks[0], [obs_dim, hidden, hidden, act_dim],
                     out_scale=0.01)
    q1 = mlp_init(ks[1], [obs_dim + act_dim, hidden, hidden, 1])
    q2 = mlp_init(ks[2], [obs_dim + act_dim, hidden, hidden, 1])
    return {"actor": actor, "q1": q1, "q2": q2}


def policy_action(actor, obs, act_high: float):
    import jax.numpy as jnp

    return jnp.tanh(mlp_forward(actor, obs)) * act_high


def q_value(q, obs, act):
    import jax.numpy as jnp

    return mlp_forward(q, jnp.concatenate([obs, act], -1))[..., 0]


@ray_tpu.remote
class _TD3Worker(EnvSampler):
    def __init__(self, env_name: str, seed: int,
                 env_config: Optional[dict] = None):
        super().__init__(env_name, seed, env_config)
        self.act_high = float(np.asarray(
            self.env.action_space.high).reshape(-1)[0])
        self.rng = np.random.default_rng(seed)

    def sample(self, actor, num_steps: int, random_actions: bool,
               expl_noise: float):
        import jax.numpy as jnp

        def select(obs):
            if random_actions:
                return self.env.action_space.sample()
            a = policy_action(actor, jnp.asarray(obs)[None], self.act_high)
            action = np.asarray(a)[0]
            return np.clip(
                action + self.rng.normal(
                    0, expl_noise * self.act_high, action.shape),
                -self.act_high, self.act_high).astype(np.float32)

        return self.sample_transitions(select, num_steps)


@dataclass
class TD3Config:
    env: str = "Pendulum-v1"
    env_config: Dict[str, Any] = field(default_factory=dict)
    num_rollout_workers: int = 1
    rollout_fragment_length: int = 100
    replay_capacity: int = 100_000
    learning_starts: int = 500
    train_batch_size: int = 128
    updates_per_iter: int = 32
    actor_lr: float = 3e-4
    critic_lr: float = 3e-4
    gamma: float = 0.99
    tau: float = 0.005
    policy_delay: int = 2            # delayed actor updates (TD3 trick #2)
    target_noise: float = 0.2        # target policy smoothing (trick #3)
    target_noise_clip: float = 0.5
    exploration_noise: float = 0.1
    hidden: int = 128
    seed: int = 0


class TD3Trainer(Algorithm):
    """ref: rllib/algorithms/td3/td3.py (DDPG base + TD3 tricks)."""

    def _setup(self, cfg: TD3Config):
        import jax
        import optax

        obs_dim, _n, act_dim, act_high = probe_env_spec(
            cfg.env, cfg.env_config)
        assert act_dim is not None, "TD3 needs a continuous action space"
        self.act_high = act_high or 1.0
        self.nets = init_td3_nets(jax.random.PRNGKey(cfg.seed), obs_dim,
                                  act_dim, cfg.hidden)
        self.target = jax.tree_util.tree_map(lambda x: x, self.nets)
        self.actor_opt = optax.adam(cfg.actor_lr)
        self.critic_opt = optax.adam(cfg.critic_lr)
        self.actor_os = self.actor_opt.init(self.nets["actor"])
        self.critic_os = self.critic_opt.init(
            {"q1": self.nets["q1"], "q2": self.nets["q2"]})
        self.buffer = ReplayBuffer(cfg.replay_capacity, cfg.seed)
        self.workers = [
            _TD3Worker.options(num_cpus=0.5, runtime_env=CPU_WORKER_ENV).remote(
                cfg.env, cfg.seed + i * 1000, cfg.env_config)
            for i in range(cfg.num_rollout_workers)]
        self.timesteps = 0
        self.num_updates = 0
        self._update = jax.jit(self._make_update(), static_argnames="do_actor")

    def _make_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.config
        act_high = self.act_high

        def update(nets, target, actor_os, critic_os, mb, key,
                   do_actor: bool):
            # --- twin critics with target policy smoothing
            def critic_loss(qs):
                noise = jnp.clip(
                    jax.random.normal(key, mb["actions"].shape)
                    * cfg.target_noise,
                    -cfg.target_noise_clip, cfg.target_noise_clip)
                a_next = jnp.clip(
                    policy_action(target["actor"], mb["next_obs"], act_high)
                    + noise * act_high, -act_high, act_high)
                tq = jnp.minimum(
                    q_value(target["q1"], mb["next_obs"], a_next),
                    q_value(target["q2"], mb["next_obs"], a_next))
                backup = jax.lax.stop_gradient(
                    mb["rewards"] + cfg.gamma * (1 - mb["dones"]) * tq)
                l1 = jnp.square(q_value(qs["q1"], mb["obs"], mb["actions"])
                                - backup).mean()
                l2 = jnp.square(q_value(qs["q2"], mb["obs"], mb["actions"])
                                - backup).mean()
                return l1 + l2

            qs = {"q1": nets["q1"], "q2": nets["q2"]}
            closs, cgrads = jax.value_and_grad(critic_loss)(qs)
            cupd, critic_os = self.critic_opt.update(cgrads, critic_os, qs)
            qs = optax.apply_updates(qs, cupd)
            nets = {**nets, "q1": qs["q1"], "q2": qs["q2"]}

            # --- delayed deterministic actor + polyak (only every
            #     policy_delay updates; staticly compiled both ways)
            def actor_loss(actor):
                a = policy_action(actor, mb["obs"], act_high)
                return -q_value(nets["q1"], mb["obs"], a).mean()

            if do_actor:
                aloss, agrads = jax.value_and_grad(actor_loss)(nets["actor"])
                aupd, actor_os = self.actor_opt.update(agrads, actor_os,
                                                       nets["actor"])
                nets = {**nets,
                        "actor": optax.apply_updates(nets["actor"], aupd)}
                target = jax.tree_util.tree_map(
                    lambda t, s: (1 - cfg.tau) * t + cfg.tau * s,
                    target, nets)
            else:
                aloss = jnp.zeros(())
            return nets, target, actor_os, critic_os, {
                "critic_loss": closs, "actor_loss": aloss}

        return update

    def training_step(self) -> Dict[str, Any]:
        import jax

        cfg = self.config
        actor_host = jax.device_get(self.nets["actor"])
        warmup = self.timesteps < cfg.learning_starts
        refs = [w.sample.remote(actor_host, cfg.rollout_fragment_length,
                                warmup, cfg.exploration_noise)
                for w in self.workers]
        for b in ray_tpu.get(refs):
            self.buffer.add_batch(b)
            self.timesteps += len(b["rewards"])

        aux = {}
        if len(self.buffer) >= cfg.learning_starts:
            for u in range(cfg.updates_per_iter):
                mb = self.buffer.sample(cfg.train_batch_size)
                key = jax.random.PRNGKey(self.iteration * 99991 + u)
                self.num_updates += 1
                (self.nets, self.target, self.actor_os, self.critic_os,
                 aux) = self._update(
                    self.nets, self.target, self.actor_os, self.critic_os,
                    mb, key,
                    do_actor=self.num_updates % cfg.policy_delay == 0)

        stats = ray_tpu.get([w.episode_stats.remote() for w in self.workers])
        eps_done = [s for s in stats if s["episodes"]]
        return {
            "timesteps_total": self.timesteps,
            "num_updates": self.num_updates,
            "episode_return_mean": float(np.mean(
                [s["mean_return"] for s in eps_done])) if eps_done else 0.0,
            "episodes_total": sum(s["episodes"] for s in stats),
            "buffer_size": len(self.buffer),
            **{k: float(v) for k, v in aux.items()},
        }

    def get_weights(self):
        return self.nets

    def set_weights(self, weights):
        import jax

        self.nets = weights
        self.target = jax.tree_util.tree_map(lambda x: x, self.nets)
