"""Contextual bandits: LinUCB and Linear Thompson Sampling.

Reference: rllib/algorithms/bandit/ (bandit.py BanditLinUCB/BanditLinTS;
exact incremental ridge-regression arms in bandit_torch_model.py
DiscreteLinearModel). Closed-form per-arm posteriors — no gradient
learner; the "training step" is env interaction + rank-1 updates, so this
runs driver-local like rllib's single-worker bandit configs.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

import numpy as np


class LinearDiscreteBanditEnv:
    """Test-friendly contextual bandit (ref: rllib
    examples/env/bandit_envs_discrete.py): reward = theta_a . x + noise,
    one-step episodes, gymnasium-shaped API."""

    def __init__(self, num_arms: int = 4, context_dim: int = 8,
                 noise: float = 0.01, seed: int = 0):
        self.rng = np.random.default_rng(seed)
        self.theta = self.rng.standard_normal((num_arms, context_dim))
        self.theta /= np.linalg.norm(self.theta, axis=1, keepdims=True)
        self.num_arms, self.context_dim, self.noise = (
            num_arms, context_dim, noise)
        self._ctx = None

    def reset(self, seed: Optional[int] = None):
        if seed is not None:
            self.rng = np.random.default_rng(seed)
        self._ctx = self.rng.standard_normal(self.context_dim).astype(
            np.float32)
        return self._ctx, {}

    def step(self, action: int):
        rew = float(self.theta[action] @ self._ctx
                    + self.rng.normal(0, self.noise))
        best = float(np.max(self.theta @ self._ctx))
        info = {"regret": best - float(self.theta[action] @ self._ctx)}
        return self._ctx, rew, True, False, info


class _LinearArm:
    """One arm's ridge posterior, Sherman–Morrison incremental inverse."""

    def __init__(self, dim: int, lam: float):
        self.A_inv = np.eye(dim, dtype=np.float64) / lam
        self.b = np.zeros(dim, np.float64)

    @property
    def theta(self) -> np.ndarray:
        return self.A_inv @ self.b

    def update(self, x: np.ndarray, r: float):
        Ax = self.A_inv @ x
        self.A_inv -= np.outer(Ax, Ax) / (1.0 + x @ Ax)
        self.b += r * x


@dataclass
class BanditConfig:
    env: Any = None                  # factory or instance; default test env
    env_config: Dict[str, Any] = field(default_factory=dict)
    num_arms: int = 4
    context_dim: int = 8
    steps_per_iter: int = 100
    alpha: float = 1.0               # LinUCB exploration width
    ts_scale: float = 1.0            # LinTS posterior scale v
    ridge_lambda: float = 1.0
    seed: int = 0


class _BanditBase:
    def __init__(self, config: BanditConfig):
        self.config = config
        env = config.env
        if env is None:
            kw = {"num_arms": config.num_arms,
                  "context_dim": config.context_dim, "seed": config.seed}
            kw.update(config.env_config)   # env_config wins, no dup kwarg
            env = LinearDiscreteBanditEnv(**kw)
        elif callable(env):
            env = env(config.env_config)
        self.env = env
        # size the arm set from the ENV when it says (a custom env's arm
        # count must win over the config default, else arms go unplayed)
        self.num_arms = int(getattr(env, "num_arms", config.num_arms))
        self.context_dim = int(getattr(env, "context_dim",
                                       config.context_dim))
        self.arms = [
            _LinearArm(self.context_dim, config.ridge_lambda)
            for _ in range(self.num_arms)]
        self.rng = np.random.default_rng(config.seed)
        self.iteration = 0
        self.timesteps = 0
        self.cum_regret = 0.0

    def _select(self, x: np.ndarray) -> int:
        raise NotImplementedError

    def train(self) -> Dict[str, Any]:
        cfg = self.config
        rewards = []
        for _ in range(cfg.steps_per_iter):
            x, _ = self.env.reset()
            x = np.asarray(x, np.float64)
            a = self._select(x)
            _, rew, _, _, info = self.env.step(a)
            self.arms[a].update(x, rew)
            rewards.append(rew)
            self.cum_regret += float(info.get("regret", 0.0))
            self.timesteps += 1
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "timesteps_total": self.timesteps,
            "episode_return_mean": float(np.mean(rewards)),
            "cumulative_regret": self.cum_regret,
        }

    def save(self):
        return {"arms": [(a.A_inv.copy(), a.b.copy()) for a in self.arms],
                "iteration": self.iteration}

    def restore(self, ckpt):
        for arm, (A_inv, b) in zip(self.arms, ckpt["arms"]):
            arm.A_inv, arm.b = A_inv, b
        self.iteration = ckpt.get("iteration", 0)

    def stop(self):
        pass


class LinUCBTrainer(_BanditBase):
    """UCB over per-arm ridge posteriors: argmax theta.x + alpha*sqrt(
    x^T A^-1 x) (ref: bandit_torch_model.py predict + partial_fit)."""

    def _select(self, x: np.ndarray) -> int:
        scores = [arm.theta @ x
                  + self.config.alpha * np.sqrt(x @ arm.A_inv @ x)
                  for arm in self.arms]
        return int(np.argmax(scores))


class LinTSTrainer(_BanditBase):
    """Thompson sampling: theta ~ N(A^-1 b, v^2 A^-1) per arm, play the
    argmax draw (ref: bandit.py BanditLinTS)."""

    def _select(self, x: np.ndarray) -> int:
        v2 = self.config.ts_scale ** 2
        scores = [
            self.rng.multivariate_normal(arm.theta, v2 * arm.A_inv) @ x
            for arm in self.arms]
        return int(np.argmax(scores))


# Config aliases so the registry has distinct (config, trainer) pairs.
BanditLinUCBConfig = BanditConfig
BanditLinTSConfig = BanditConfig
