"""DT: Decision Transformer — offline RL as sequence modeling.

Reference: rllib/algorithms/dt/ (dt.py, dt_torch_model.py — Chen et al.
2021: trajectories become (return-to-go, state, action) token streams; a
causal transformer is trained to predict the action at each state token;
at evaluation the desired return is fed as the first RTG token and
decremented by observed rewards). The transformer here is a compact
pure-JAX causal encoder — MXU-friendly fused QKV matmuls, static
context length K, the same interleaved 3-tokens-per-step layout as the
reference's GPT backbone.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List

import numpy as np

from ray_tpu.rl.core import Algorithm, dense_init, mlp_forward, mlp_init


# --- tiny causal transformer -------------------------------------------------


def init_dt_model(key, obs_dim: int, n_actions: int, d: int, n_layers: int,
                  max_steps: int):
    import jax

    ks = jax.random.split(key, 6 + 4 * n_layers)
    model = {
        "rtg_emb": dense_init(ks[0], 1, d),
        "obs_emb": dense_init(ks[1], obs_dim, d),
        "act_emb": dense_init(ks[2], n_actions, d),
        "pos_emb": jax.random.normal(ks[3], (max_steps, d)) * 0.02,
        "head": mlp_init(ks[4], [d, n_actions], out_scale=0.01),
        "blocks": [],
    }
    for i in range(n_layers):
        b = 6 + 4 * i
        model["blocks"].append({
            "qkv": dense_init(ks[b], d, 3 * d, scale=0.3),
            "proj": dense_init(ks[b + 1], d, d, scale=0.3),
            "mlp1": dense_init(ks[b + 2], d, 4 * d),
            "mlp2": dense_init(ks[b + 3], 4 * d, d, scale=0.3),
        })
    return model


def _layer_norm(x):
    import jax.numpy as jnp

    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + 1e-5)


def dt_forward(model, rtg, obs, acts_onehot):
    """rtg [B,K,1], obs [B,K,O], acts_onehot [B,K,A] -> action logits
    at each state token [B,K,A]. Token order per step: (R_t, s_t, a_t),
    single-head causal attention over the 3K stream."""
    import jax.numpy as jnp

    B, K = rtg.shape[:2]
    d = model["pos_emb"].shape[-1]
    pos = model["pos_emb"][:K][None, :, None, :]          # [1,K,1,d]
    tok = jnp.stack([
        rtg @ model["rtg_emb"]["w"] + model["rtg_emb"]["b"],
        obs @ model["obs_emb"]["w"] + model["obs_emb"]["b"],
        acts_onehot @ model["act_emb"]["w"] + model["act_emb"]["b"],
    ], axis=2) + pos                                      # [B,K,3,d]
    x = tok.reshape(B, 3 * K, d)
    T = 3 * K
    mask = jnp.tril(jnp.ones((T, T), bool))
    for blk in model["blocks"]:
        h = _layer_norm(x)
        qkv = h @ blk["qkv"]["w"] + blk["qkv"]["b"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        att = (q @ jnp.swapaxes(k, -1, -2)) / jnp.sqrt(d)
        att = jnp.where(mask[None], att, -1e9)
        att = jnp.exp(att - att.max(-1, keepdims=True))
        att = att / att.sum(-1, keepdims=True)
        x = x + (att @ v) @ blk["proj"]["w"] + blk["proj"]["b"]
        h = _layer_norm(x)
        h = jnp.maximum(h @ blk["mlp1"]["w"] + blk["mlp1"]["b"], 0.0)
        x = x + h @ blk["mlp2"]["w"] + blk["mlp2"]["b"]
    x = _layer_norm(x).reshape(B, K, 3, d)
    return mlp_forward(model["head"], x[:, :, 1])          # state tokens


# --- trainer -----------------------------------------------------------------


@dataclass
class DTConfig:
    # offline dataset: list of episodes, each {"obs" [T,O], "actions" [T],
    # "rewards" [T]} — or flat transition arrays with "dones" to split on
    dataset: Any = None
    n_actions: int = 0
    context_len: int = 8            # K steps of (R, s, a) context
    d_model: int = 64
    n_layers: int = 2
    lr: float = 1e-3
    train_batch_size: int = 64
    updates_per_iter: int = 32
    # evaluation-time return conditioning (ref: target_return config)
    target_return: float = 100.0
    seed: int = 0


def _episodes_from(dataset) -> List[Dict[str, np.ndarray]]:
    if isinstance(dataset, list):
        return [{k: np.asarray(v) for k, v in ep.items()}
                for ep in dataset]
    data = {k: np.asarray(v) for k, v in dataset.items()}
    ends = np.flatnonzero(data["dones"]) + 1
    bounds = [0, *ends.tolist()]
    if bounds[-1] != len(data["obs"]):
        bounds.append(len(data["obs"]))
    return [{k: data[k][a:b] for k in ("obs", "actions", "rewards")}
            for a, b in zip(bounds[:-1], bounds[1:]) if b > a]


class DTTrainer(Algorithm):
    """ref: rllib/algorithms/dt/dt.py training_step — sample K-step
    windows from offline episodes, supervised action prediction
    conditioned on returns-to-go."""

    def _setup(self, cfg: DTConfig):
        import jax
        import optax

        assert cfg.dataset is not None, "DT needs an offline dataset"
        self.episodes = _episodes_from(cfg.dataset)
        for ep in self.episodes:
            # returns-to-go per step, the conditioning signal
            ep["rtg"] = np.cumsum(ep["rewards"][::-1])[::-1].astype(
                np.float32).copy()
        obs_dim = int(self.episodes[0]["obs"].shape[-1])
        self.n_actions = cfg.n_actions or int(
            max(ep["actions"].max() for ep in self.episodes)) + 1
        self.model = init_dt_model(jax.random.PRNGKey(cfg.seed), obs_dim,
                                   self.n_actions, cfg.d_model,
                                   cfg.n_layers, cfg.context_len)
        self.opt = optax.adam(cfg.lr)
        self.opt_state = self.opt.init(self.model)
        self._rng = np.random.default_rng(cfg.seed)
        self.workers = []
        self._update = jax.jit(self._make_update())

    def _sample_windows(self, batch_size: int):
        """K-step windows, left-padded with zeros (mask marks real
        steps), matching the reference's SegmentationBuffer sampling."""
        cfg = self.config
        K = cfg.context_len
        obs_dim = self.episodes[0]["obs"].shape[-1]
        rtg = np.zeros((batch_size, K, 1), np.float32)
        obs = np.zeros((batch_size, K, obs_dim), np.float32)
        acts = np.zeros((batch_size, K), np.int32)
        mask = np.zeros((batch_size, K), np.float32)
        for b in range(batch_size):
            ep = self.episodes[self._rng.integers(len(self.episodes))]
            T = len(ep["actions"])
            end = self._rng.integers(1, T + 1)
            start = max(0, end - K)
            n = end - start
            rtg[b, K - n:, 0] = ep["rtg"][start:end]
            obs[b, K - n:] = ep["obs"][start:end]
            acts[b, K - n:] = ep["actions"][start:end]
            mask[b, K - n:] = 1.0
        return rtg, obs, acts, mask

    def _make_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        A = self.n_actions

        def loss_fn(model, rtg, obs, acts, mask):
            # true actions ride as tokens; a_t sits AFTER s_t in the
            # stream, so the causal mask keeps the prediction at s_t
            # from seeing it (no shift needed)
            logits = dt_forward(model, rtg, obs, jax.nn.one_hot(acts, A))
            logp = jax.nn.log_softmax(logits)
            nll = -jnp.take_along_axis(logp, acts[..., None], -1)[..., 0]
            loss = (nll * mask).sum() / jnp.maximum(mask.sum(), 1.0)
            acc = (((logits.argmax(-1) == acts) * mask).sum()
                   / jnp.maximum(mask.sum(), 1.0))
            return loss, acc

        def update(model, opt_state, rtg, obs, acts, mask):
            (loss, acc), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(model, rtg, obs, acts, mask)
            upd, opt_state = self.opt.update(grads, opt_state, model)
            return optax.apply_updates(model, upd), opt_state, loss, acc

        return update

    def training_step(self) -> Dict[str, Any]:
        import jax.numpy as jnp

        cfg = self.config
        loss = acc = float("nan")
        for _ in range(cfg.updates_per_iter):
            rtg, obs, acts, mask = self._sample_windows(
                cfg.train_batch_size)
            self.model, self.opt_state, loss, acc = self._update(
                self.model, self.opt_state, jnp.asarray(rtg),
                jnp.asarray(obs), jnp.asarray(acts), jnp.asarray(mask))
        return {"loss": float(loss), "action_accuracy": float(acc),
                "num_episodes": len(self.episodes)}

    def compute_action(self, history) -> int:
        """history: {"rtg": [t], "obs": [t, O], "actions": [t-1]} — the
        running episode so far; returns the next action (greedy)."""
        import jax.numpy as jnp

        K = self.config.context_len
        t = len(history["obs"])
        n = min(t, K)
        obs_dim = history["obs"][0].shape[-1] if t else 0
        rtg = np.zeros((1, K, 1), np.float32)
        obs = np.zeros((1, K, obs_dim), np.float32)
        acts = np.zeros((1, K), np.int32)
        rtg[0, K - n:, 0] = np.asarray(history["rtg"][-n:])
        obs[0, K - n:] = np.asarray(history["obs"][-n:])
        # past actions as tokens; the current (unknown) action slot is a
        # zero token the causal mask hides from the prediction anyway
        past = list(history["actions"])[-(n - 1):] if n > 1 else []
        acts[0, K - n:K - n + len(past)] = np.asarray(past, np.int32)
        import jax

        logits = dt_forward(self.model, jnp.asarray(rtg), jnp.asarray(obs),
                            jax.nn.one_hot(jnp.asarray(acts),
                                           self.n_actions))
        return int(np.asarray(logits)[0, -1].argmax())

    def get_weights(self):
        return self.model

    def set_weights(self, weights):
        self.model = weights
