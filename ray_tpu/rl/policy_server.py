"""Policy server/client: RL where the environment lives OUTSIDE the
cluster.

Reference: rllib's external-env stack — env/external_env.py,
env/policy_server_input.py (REST server the trainer reads experiences
from) and env/policy_client.py (external simulator asks for actions,
logs rewards). The classic example: a game server calls
start_episode/get_action/log_returns/end_episode against a learning
cluster (rllib/examples/serving/cartpole_server.py).

Shape here: the PolicyServer is a TCP JSON-frame service (same framing
as the client gateway) embedded in the trainer process; external
PolicyClients drive episodes; the trainer consumes completed episodes
per iteration and pushes fresh weights back into the server. Inference
stays CPU-side numpy (tiny policies), the learner update is the same
jitted PPO step as everywhere else.
"""

from __future__ import annotations

import json
import socket
import socketserver
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from dataclasses import dataclass

from ray_tpu.rl.core import Algorithm
from ray_tpu.rl.ppo import (categorical_sample, compute_gae, init_policy,
                            make_ppo_update, policy_forward, run_ppo_epochs)


class _Episode:
    def __init__(self, eid: int):
        self.eid = eid
        self.obs: List[np.ndarray] = []
        self.actions: List[int] = []
        self.logps: List[float] = []
        self.values: List[float] = []
        self.rewards: List[float] = []
        self.pending_reward = 0.0


class PolicyServer:
    """Serves get_action to external clients and accumulates completed
    episodes for the trainer (ref: PolicyServerInput)."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0):
        self.params = None                    # set by the trainer
        self.lock = threading.Lock()
        self._episodes: Dict[int, _Episode] = {}
        self._completed: List[_Episode] = []
        self._next_eid = 0
        self._rng = np.random.default_rng(0)

        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                session: set = set()   # episode ids opened on this conn
                try:
                    while True:
                        line = self.rfile.readline()
                        if not line:
                            return
                        try:
                            req = json.loads(line)
                            out = outer._dispatch(req, session)
                        except Exception as e:
                            out = {"ok": False,
                                   "error": f"{type(e).__name__}: {e}"}
                        self.wfile.write((json.dumps(out) + "\n").encode())
                        self.wfile.flush()
                finally:
                    # a disconnecting client abandons its open episodes;
                    # drop them or they leak forever
                    outer._abandon(session)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()

    # ------------------------------------------------------------- protocol

    def _dispatch(self, req: dict, session: Optional[set] = None) -> dict:
        m = req.get("method")
        if m == "start_episode":
            with self.lock:
                eid = self._next_eid
                self._next_eid += 1
                self._episodes[eid] = _Episode(eid)
            if session is not None:
                session.add(eid)
            return {"ok": True, "episode_id": eid}
        if m == "get_action":
            return self._get_action(int(req["episode_id"]),
                                    np.asarray(req["obs"], np.float32))
        if m == "log_returns":
            with self.lock:
                ep = self._episodes[int(req["episode_id"])]
                ep.pending_reward += float(req["reward"])
            return {"ok": True}
        if m == "end_episode":
            with self.lock:
                ep = self._episodes.pop(int(req["episode_id"]))
                if ep.actions:
                    ep.rewards.append(ep.pending_reward)
                    self._completed.append(ep)
            if session is not None:
                session.discard(int(req["episode_id"]))
            return {"ok": True}
        raise ValueError(f"unknown method {m!r}")

    def _abandon(self, eids: set):
        with self.lock:
            for eid in eids:
                self._episodes.pop(eid, None)

    def _get_action(self, eid: int, obs: np.ndarray) -> dict:
        # Forward + sample FIRST; episode state only mutates on success
        # (a failed call must not desync rewards from actions).
        with self.lock:
            params = self.params
        if params is None:
            raise RuntimeError("server has no policy weights yet")
        import jax.numpy as jnp

        logits, value = policy_forward(params, jnp.asarray(obs)[None])
        with self.lock:
            # the shared Generator must not race across handler threads
            a, logp = categorical_sample(np.asarray(logits)[0], self._rng)
            ep = self._episodes[eid]
            if ep.actions:
                # reward accumulated since the last action closes that step
                ep.rewards.append(ep.pending_reward)
            ep.pending_reward = 0.0
            ep.obs.append(obs)
            ep.actions.append(a)
            ep.logps.append(logp)
            ep.values.append(float(np.asarray(value)[0]))
        return {"ok": True, "action": a}

    # -------------------------------------------------------- trainer side

    def set_weights(self, params):
        with self.lock:
            self.params = params

    def drain_episodes(self, min_steps: int = 1,
                       timeout_s: float = 60.0) -> List[_Episode]:
        """Block until at least min_steps of completed experience exist,
        then take everything (ref: PolicyServerInput.next batching)."""
        import time

        deadline = time.time() + timeout_s
        while time.time() < deadline:
            with self.lock:
                steps = sum(len(e.actions) for e in self._completed)
                if steps >= min_steps:
                    out, self._completed = self._completed, []
                    return out
            time.sleep(0.02)
        with self.lock:
            out, self._completed = self._completed, []
        return out

    def shutdown(self):
        try:
            self._server.shutdown()
            self._server.server_close()
        except Exception:
            pass


class PolicyClient:
    """External-simulator side (ref: env/policy_client.py). Line-JSON
    over TCP; one connection, synchronous."""

    def __init__(self, address: Tuple[str, int] | str):
        if isinstance(address, str):
            h, _, p = address.rpartition(":")
            address = (h, int(p))
        self._sock = socket.create_connection(address)
        self._f = self._sock.makefile("rw", encoding="utf-8")

    def _call(self, method: str, **kw) -> dict:
        kw["method"] = method
        self._f.write(json.dumps(kw) + "\n")
        self._f.flush()
        resp = json.loads(self._f.readline())
        if not resp.get("ok"):
            raise RuntimeError(resp.get("error", "policy server error"))
        return resp

    def start_episode(self) -> int:
        return self._call("start_episode")["episode_id"]

    def get_action(self, episode_id: int, obs) -> int:
        return self._call("get_action", episode_id=episode_id,
                          obs=np.asarray(obs, np.float32).tolist())["action"]

    def log_returns(self, episode_id: int, reward: float):
        self._call("log_returns", episode_id=episode_id,
                   reward=float(reward))

    def end_episode(self, episode_id: int):
        self._call("end_episode", episode_id=episode_id)

    def close(self):
        try:
            self._sock.close()
        except Exception:
            pass


@dataclass
class ExternalPPOConfig:
    obs_dim: int = 0
    n_actions: int = 0
    train_batch_size: int = 256
    num_epochs: int = 4
    minibatch_size: int = 64
    lr: float = 3e-4
    gamma: float = 0.99
    lam: float = 0.95
    clip: float = 0.2
    vf_coeff: float = 0.5
    entropy_coeff: float = 0.01
    hidden: int = 64
    seed: int = 0
    host: str = "0.0.0.0"
    port: int = 0


class ExternalPPOTrainer(Algorithm):
    """PPO learning from external clients (ref: the server half of
    rllib's cartpole_server example — same jitted update as PPOTrainer,
    experiences arrive over the wire instead of from rollout actors)."""

    def _setup(self, cfg: ExternalPPOConfig):
        import jax
        import optax

        self.params = init_policy(jax.random.PRNGKey(cfg.seed), cfg.obs_dim,
                                  cfg.n_actions, cfg.hidden)
        self.opt = optax.adam(cfg.lr)
        self.opt_state = self.opt.init(self.params)
        self._update = jax.jit(make_ppo_update(cfg, self.opt))
        self.server = PolicyServer(cfg.host, cfg.port)
        self.server.set_weights(jax.device_get(self.params))
        self.workers = []
        self.timesteps = 0

    @property
    def address(self) -> Tuple[str, int]:
        return ("127.0.0.1" if self.config.host == "0.0.0.0"
                else self.config.host, self.server.port)

    def training_step(self) -> Dict[str, Any]:
        import jax

        cfg = self.config
        episodes = self.server.drain_episodes(cfg.train_batch_size)
        if not episodes:
            return {"timesteps_total": self.timesteps, "episodes_this_iter": 0}

        obs, acts, logps, advs, rets, ep_returns = [], [], [], [], [], []
        for ep in episodes:
            b = {"rewards": np.asarray(ep.rewards, np.float32),
                 "dones": np.zeros(len(ep.actions), np.bool_),
                 "values": np.asarray(ep.values, np.float32),
                 "last_value": 0.0}
            b["dones"][-1] = True        # episodes arrive complete
            adv, ret = compute_gae(b, cfg.gamma, cfg.lam)
            obs.append(np.stack(ep.obs))
            acts.append(np.asarray(ep.actions, np.int32))
            logps.append(np.asarray(ep.logps, np.float32))
            advs.append(adv)
            rets.append(ret)
            ep_returns.append(float(np.sum(ep.rewards)))
        obs = np.concatenate(obs)
        self.timesteps += len(obs)
        self.params, self.opt_state, aux = run_ppo_epochs(
            self._update, self.params, self.opt_state,
            obs=obs, actions=np.concatenate(acts),
            logp=np.concatenate(logps), adv=np.concatenate(advs),
            returns=np.concatenate(rets), num_epochs=cfg.num_epochs,
            minibatch_size=cfg.minibatch_size, seed=self.iteration)
        self.server.set_weights(jax.device_get(self.params))
        return {
            "timesteps_total": self.timesteps,
            "episodes_this_iter": len(episodes),
            "episode_return_mean": float(np.mean(ep_returns)),
            **{k: float(v) for k, v in aux.items()},
        }

    def get_weights(self):
        return self.params

    def set_weights(self, weights):
        import jax

        self.params = weights
        self.server.set_weights(jax.device_get(weights))

    def stop(self):
        self.server.shutdown()
