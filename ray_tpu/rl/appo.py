"""APPO: asynchronous PPO — IMPALA-style async sampling + clipped surrogate.

Reference: rllib/algorithms/appo/ (APPO = PPO loss computed on V-trace
corrected advantages over an asynchronous sample pipeline, plus a target
network refreshed periodically to anchor the importance ratios —
appo.py / appo_tf_policy.py). Workers sample with whatever weights they
last received; the learner never blocks the fleet.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict

import numpy as np

import ray_tpu
from ray_tpu.rl.core import Algorithm, CPU_WORKER_ENV
from ray_tpu.rl.ppo import RolloutWorker, policy_forward


@dataclass
class APPOConfig:
    env: str = "CartPole-v1"
    env_config: Dict[str, Any] = field(default_factory=dict)
    num_rollout_workers: int = 2
    rollout_fragment_length: int = 100
    batches_per_iter: int = 4
    lr: float = 5e-4
    gamma: float = 0.99
    clip: float = 0.3
    vtrace_rho_clip: float = 1.0
    vtrace_c_clip: float = 1.0
    vf_coeff: float = 0.5
    entropy_coeff: float = 0.01
    # refresh the ratio-anchoring target network every n learner updates
    # (ref: appo.py target_update_frequency)
    target_update_freq: int = 8
    hidden: int = 64
    seed: int = 0
    # connector factories + network choice, same semantics as PPOConfig
    obs_connectors: Any = None
    network: str = "auto"
    cnn_hidden: int = 512


class APPOTrainer(Algorithm):
    """Async PPO learner. One in-flight sample request per worker; each
    landed fragment gets V-trace advantages (off-policy correction against
    the *target* policy the fragment was sampled near) and one clipped
    PPO update (ref: appo.py training_step)."""

    def _setup(self, cfg: APPOConfig):
        import jax
        import optax

        from ray_tpu.rl.connectors import build_pipeline
        from ray_tpu.rl.core import probe_connected_spec
        from ray_tpu.rl.ppo import init_any_policy

        obs_shape, n_actions = probe_connected_spec(
            cfg.env, cfg.env_config, cfg.obs_connectors, cfg.seed)
        self.pipeline = build_pipeline(cfg.obs_connectors)
        self._conn_abs = None
        self.params = init_any_policy(jax.random.PRNGKey(cfg.seed),
                                      obs_shape, n_actions, cfg)
        self.target = jax.tree_util.tree_map(lambda x: x, self.params)
        self.opt = optax.adam(cfg.lr)
        self.opt_state = self.opt.init(self.params)
        self.workers = [
            RolloutWorker.options(num_cpus=0.5, runtime_env=CPU_WORKER_ENV).remote(
                cfg.env, seed=cfg.seed + i * 1000,
                env_config=cfg.env_config,
                connectors=cfg.obs_connectors)
            for i in range(cfg.num_rollout_workers)]
        self._inflight: Dict[Any, Any] = {}
        self.timesteps = 0
        self.num_updates = 0
        self._update = jax.jit(self._make_update())

    def _make_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.config

        def vtrace(values, rewards, dones, rhos, last_value):
            rho = jnp.minimum(rhos, cfg.vtrace_rho_clip)
            c = jnp.minimum(rhos, cfg.vtrace_c_clip)
            discounts = cfg.gamma * (1.0 - dones)
            next_values = jnp.concatenate([values[1:], last_value[None]])
            deltas = rho * (rewards + discounts * next_values - values)

            def scan_fn(acc, t):
                acc = deltas[t] + discounts[t] * c[t] * acc
                return acc, acc

            T = values.shape[0]
            _, vs_minus_v = jax.lax.scan(scan_fn, jnp.zeros(()),
                                         jnp.arange(T - 1, -1, -1))
            vs = values + vs_minus_v[::-1]
            next_vs = jnp.concatenate([vs[1:], last_value[None]])
            pg_adv = rho * (rewards + discounts * next_vs - values)
            return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)

        def loss_fn(params, target, batch):
            logits, values = policy_forward(params, batch["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(logp_all, batch["actions"][:, None],
                                       -1)[:, 0]
            # ratios anchored on the periodically-refreshed target net,
            # not the behavior policy — the APPO stabilization trick
            t_logits, _ = policy_forward(target, batch["obs"])
            t_logp = jnp.take_along_axis(
                jax.nn.log_softmax(t_logits), batch["actions"][:, None],
                -1)[:, 0]
            behav_rhos = jnp.exp(t_logp - batch["logp"])
            vs, pg_adv = vtrace(values, batch["rewards"], batch["dones"],
                                behav_rhos, batch["last_value"])
            adv = (pg_adv - pg_adv.mean()) / (pg_adv.std() + 1e-8)
            ratio = jnp.exp(logp - jax.lax.stop_gradient(t_logp))
            pg = -jnp.minimum(
                ratio * adv,
                jnp.clip(ratio, 1 - cfg.clip, 1 + cfg.clip) * adv).mean()
            vf = 0.5 * jnp.square(values - vs).mean()
            ent = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
            total = pg + cfg.vf_coeff * vf - cfg.entropy_coeff * ent
            return total, {"pg_loss": pg, "vf_loss": vf, "entropy": ent}

        def update(params, target, opt_state, batch):
            (total, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target, batch)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            aux["total_loss"] = total
            return params, opt_state, aux

        return update

    def _launch(self, worker, params_host):
        ref = worker.sample.remote(params_host,
                                   self.config.rollout_fragment_length,
                                   self._conn_abs)
        self._inflight[ref] = worker

    def training_step(self) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp

        cfg = self.config
        params_host = jax.device_get(self.params)
        for w in self.workers:
            if w not in self._inflight.values():
                self._launch(w, params_host)

        aux = {}
        consumed = 0
        while consumed < cfg.batches_per_iter:
            ready, _ = ray_tpu.wait(list(self._inflight), num_returns=1,
                                    timeout=60.0)
            if not ready:
                break
            for ref in ready:
                if consumed >= cfg.batches_per_iter:
                    break
                worker = self._inflight.pop(ref)
                b = ray_tpu.get(ref)
                delta = b.pop("connector_state", None)
                if delta is not None:
                    self._conn_abs = self.pipeline.merge_pipeline_states(
                        [delta], prev=self._conn_abs)
                batch = {
                    "obs": jnp.asarray(b["obs"]),
                    "actions": jnp.asarray(b["actions"]),
                    "rewards": jnp.asarray(b["rewards"]),
                    "dones": jnp.asarray(b["dones"], jnp.float32),
                    "logp": jnp.asarray(b["logp"]),
                    "last_value": jnp.asarray(b["last_value"]),
                }
                self.params, self.opt_state, aux = self._update(
                    self.params, self.target, self.opt_state, batch)
                self.timesteps += len(b["rewards"])
                consumed += 1
                self.num_updates += 1
                if self.num_updates % cfg.target_update_freq == 0:
                    self.target = jax.tree_util.tree_map(
                        lambda x: x, self.params)
                self._launch(worker, jax.device_get(self.params))

        stats = ray_tpu.get([w.episode_stats.remote() for w in self.workers])
        eps_done = [s for s in stats if s["episodes"]]
        return {
            "timesteps_total": self.timesteps,
            "num_updates": self.num_updates,
            "episode_return_mean": float(np.mean(
                [s["mean_return"] for s in eps_done])) if eps_done else 0.0,
            "episodes_total": sum(s["episodes"] for s in stats),
            "batches_consumed": consumed,
            **{k: float(v) for k, v in aux.items()},
        }

    def get_weights(self):
        return self.params

    def set_weights(self, weights):
        import jax

        self.params = weights
        self.target = jax.tree_util.tree_map(lambda x: x, weights)
