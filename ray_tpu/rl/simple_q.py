"""SimpleQ (vanilla DQN) and RandomAgent baselines.

Reference: rllib/algorithms/simple_q/ — the pedagogical Q-learning
algorithm DQN builds on: single Q net + target net, uniform replay,
epsilon-greedy, no double-Q / dueling / n-step / prioritization — and
rllib/algorithms/random_agent/random_agent.py, the no-learning control
baseline used in sanity benchmarks.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

import numpy as np

import ray_tpu
from ray_tpu.rl.core import Algorithm, CPU_WORKER_ENV, EnvSampler, episode_stats_from
from ray_tpu.rl.dqn import DQNConfig, DQNTrainer


@dataclass
class SimpleQConfig(DQNConfig):
    # the whole point of SimpleQ is that these stay off
    double_q: bool = False
    dueling: bool = False


class SimpleQTrainer(DQNTrainer):
    """ref: rllib/algorithms/simple_q/simple_q.py training_step — the
    DQN loop with the extensions disabled; shares the sampler fleet and
    jitted TD update with DQNTrainer."""

    def _setup(self, cfg: SimpleQConfig):
        assert not cfg.double_q and not cfg.dueling, (
            "SimpleQ is plain Q-learning; use DQNConfig for double/dueling")
        super()._setup(cfg)


@ray_tpu.remote(num_cpus=0.5)
class _RandomWorker(EnvSampler):
    def sample(self, num_steps: int):
        for _ in range(num_steps):
            self.step_env(self.env.action_space.sample())
        return num_steps


@dataclass
class RandomAgentConfig:
    env: str = "CartPole-v1"
    env_config: Dict[str, Any] = None
    num_rollout_workers: int = 1
    rollout_fragment_length: int = 200
    seed: int = 0


class RandomAgentTrainer(Algorithm):
    """ref: rllib/algorithms/random_agent/random_agent.py — uniform
    random actions, no parameters; reports the same episode metrics so
    it slots into tune sweeps as the floor baseline."""

    def _setup(self, cfg: RandomAgentConfig):
        self.workers = [
            _RandomWorker.options(runtime_env=CPU_WORKER_ENV).remote(cfg.env, cfg.seed + i * 1000,
                                 cfg.env_config or {})
            for i in range(cfg.num_rollout_workers)]
        self.timesteps = 0

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        n = sum(ray_tpu.get([w.sample.remote(cfg.rollout_fragment_length)
                             for w in self.workers]))
        self.timesteps += n
        stats = ray_tpu.get([w.episode_stats.remote() for w in self.workers])
        eps_done = [s for s in stats if s["episodes"]]
        return {
            "timesteps_total": self.timesteps,
            "episode_return_mean": float(np.mean(
                [s["mean_return"] for s in eps_done])) if eps_done else 0.0,
            "episodes_total": sum(s["episodes"] for s in stats),
        }

    def get_weights(self):
        return {}

    def set_weights(self, weights):
        pass

    def save(self) -> Dict[str, Any]:
        return {"params": {}, "iteration": self.iteration}
