"""DDPPO: decentralized distributed PPO.

Reference: rllib/algorithms/ddppo/ddppo.py — PPO where experience NEVER
leaves the rollout worker: each worker samples its own fragment, computes
the clipped-surrogate gradient locally, and only GRADIENTS cross the
wire, allreduced across the fleet each SGD iteration (the reference uses
torch.distributed allreduce; here the drastically cheaper star topology —
driver-side mean + weight rebroadcast — carries the same property, since
the driver is the TPU host that applies the update anyway).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rl.core import CPU_WORKER_ENV, Algorithm
from ray_tpu.rl.ppo import RolloutWorker, compute_gae, make_ppo_loss


@ray_tpu.remote(num_cpus=0.5)
class _DDPPOWorker:
    """Sample locally, keep the batch, emit per-SGD-iteration gradients
    (ref: ddppo.py worker loop — `sample_and_update` without the torch
    process group)."""

    def __init__(self, env: str, seed: int, env_config: dict,
                 cfg_dict: dict, connectors=None):
        import jax

        self.inner = RolloutWorker._cls(env, seed, env_config,
                                        connectors=connectors)
        self.cfg = cfg_dict
        self.rng = np.random.default_rng(seed)
        self.batch = None
        self._grad = jax.jit(jax.value_and_grad(
            make_ppo_loss(cfg_dict["clip"], cfg_dict["vf_coeff"],
                          cfg_dict["entropy_coeff"]), has_aux=True))

    def sample(self, params, n_steps: int) -> int:
        """Collect a fragment and precompute advantages; the batch stays
        resident on this worker."""
        b = self.inner.sample(params, n_steps)
        adv, ret = compute_gae(b, self.cfg["gamma"], self.cfg["lam"])
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        self.batch = {"obs": b["obs"], "actions": b["actions"],
                      "logp": b["logp"], "adv": adv.astype(np.float32),
                      "returns": ret.astype(np.float32)}
        return len(adv)

    def grad(self, params):
        """One minibatch gradient on the resident batch."""
        import jax

        n = len(self.batch["adv"])
        mbs = min(self.cfg["minibatch_size"], n)
        idx = self.rng.permutation(n)[:mbs]
        mb = {k: v[idx] for k, v in self.batch.items()}
        (loss, aux), grads = self._grad(params, mb)
        return jax.device_get(grads), {"loss": float(loss),
                                       **{k: float(v)
                                          for k, v in aux.items()}}

    def init_collective(self, rank: int, world: int, backend: str,
                        group: str = "ddppo_grads") -> bool:
        """Join the fleet-wide gradient-allreduce group (the reference's
        torch.distributed process group, as a ray_tpu.collective host
        group — gradients cross rank-to-rank, not through the driver)."""
        from ray_tpu import collective as col

        self._col_group = group
        self._col_rank = rank
        self._col_world = world
        col.init_collective_group(world, rank, group, backend=backend)
        return True

    def grad_reduced(self, params):
        """One minibatch gradient, allreduced across the fleet in place.

        Returns (mean_grads, aux) on rank 0 and (None, aux) elsewhere —
        the driver applies rank 0's result, so the full gradient tree
        crosses the driver wire once instead of num_workers times."""
        from ray_tpu import collective as col

        grads, aux = self.grad(params)
        total = col.allreduce(grads, self._col_group)
        if self._col_rank != 0:
            return None, aux
        import jax

        world = self._col_world
        return jax.tree_util.tree_map(lambda g: g / world, total), aux

    def episode_stats(self):
        return self.inner.episode_stats()


@dataclass
class DDPPOConfig:
    env: str = "CartPole-v1"
    env_config: Dict[str, Any] = field(default_factory=dict)
    num_rollout_workers: int = 2
    rollout_fragment_length: int = 128
    num_sgd_iter: int = 8            # allreduced gradient steps per iter
    minibatch_size: int = 64         # per worker
    lr: float = 3e-4
    gamma: float = 0.99
    lam: float = 0.95
    clip: float = 0.2
    vf_coeff: float = 0.5
    entropy_coeff: float = 0.01
    hidden: int = 64
    # connector factories + network choice, same semantics as PPOConfig.
    # Connector state here is per-worker only (experience never leaves the
    # worker, so there is no central merge point by design).
    obs_connectors: Any = None
    network: str = "auto"
    cnn_hidden: int = 512
    seed: int = 0
    # Host-collective gradient exchange (ray_tpu.collective backend name:
    # "auto"/"gather"/"ring"/"hier"). None keeps the legacy star topology
    # (driver-side mean). With a backend set, gradients allreduce
    # rank-to-rank and only rank 0 ships the mean to the driver —
    # driver ingress drops from num_workers x |grads| to 1 x |grads|.
    collective_backend: Optional[str] = None


class DDPPOTrainer(Algorithm):
    """ref: ddppo.py training_step — the driver never sees a sample:
    workers hold their fragments, each SGD iteration is a fleet-wide
    gradient mean applied once and rebroadcast."""

    def _setup(self, cfg: DDPPOConfig):
        import jax
        import optax

        from ray_tpu.rl.core import probe_connected_spec
        from ray_tpu.rl.ppo import init_any_policy

        obs_shape, n_actions = probe_connected_spec(
            cfg.env, cfg.env_config, cfg.obs_connectors, cfg.seed)
        self.params = init_any_policy(jax.random.PRNGKey(cfg.seed),
                                      obs_shape, n_actions, cfg)
        self.opt = optax.adam(cfg.lr)
        self.opt_state = self.opt.init(self.params)
        cfg_dict = {"gamma": cfg.gamma, "lam": cfg.lam, "clip": cfg.clip,
                    "vf_coeff": cfg.vf_coeff,
                    "entropy_coeff": cfg.entropy_coeff,
                    "minibatch_size": cfg.minibatch_size}
        self.workers = [
            _DDPPOWorker.options(runtime_env=CPU_WORKER_ENV).remote(cfg.env, cfg.seed + i * 1000,
                                cfg.env_config, cfg_dict,
                                cfg.obs_connectors)
            for i in range(cfg.num_rollout_workers)]
        if cfg.collective_backend:
            world = cfg.num_rollout_workers
            ray_tpu.get([w.init_collective.remote(i, world,
                                                  cfg.collective_backend)
                         for i, w in enumerate(self.workers)], timeout=240)
        self.timesteps = 0
        self._apply = jax.jit(self._make_apply())

    def _make_apply(self):
        import jax
        import optax

        def apply(params, opt_state, grads_list):
            mean = jax.tree_util.tree_map(
                lambda *g: sum(g) / len(g), *grads_list)
            upd, opt_state = self.opt.update(mean, opt_state, params)
            return optax.apply_updates(params, upd), opt_state

        return apply

    def training_step(self) -> Dict[str, Any]:
        import jax

        cfg = self.config
        params_host = jax.device_get(self.params)
        ns = ray_tpu.get([w.sample.remote(params_host,
                                          cfg.rollout_fragment_length)
                          for w in self.workers])
        self.timesteps += sum(ns)

        aux = {}
        for _ in range(cfg.num_sgd_iter):
            if cfg.collective_backend:
                # fleet-side allreduce: driver receives ONE gradient tree
                # (rank 0's mean) instead of num_workers of them
                results = ray_tpu.get([w.grad_reduced.remote(params_host)
                                       for w in self.workers])
                grads_list = [results[0][0]]
            else:
                results = ray_tpu.get([w.grad.remote(params_host)
                                       for w in self.workers])
                grads_list = [g for g, _ in results]
            aux = results[0][1]
            self.params, self.opt_state = self._apply(
                self.params, self.opt_state, grads_list)
            params_host = jax.device_get(self.params)

        stats = ray_tpu.get([w.episode_stats.remote() for w in self.workers])
        eps_done = [s for s in stats if s["episodes"]]
        return {
            "timesteps_total": self.timesteps,
            "episode_return_mean": float(np.mean(
                [s["mean_return"] for s in eps_done])) if eps_done else 0.0,
            "episodes_total": sum(s["episodes"] for s in stats),
            **aux,
        }

    def get_weights(self):
        return self.params

    def set_weights(self, weights):
        self.params = weights
