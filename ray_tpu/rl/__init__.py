"""ray_tpu.rl: reinforcement learning — TPU learner, CPU rollout actors.

Reference: rllib/ — Algorithm (algorithms/algorithm.py:813 step,
:1400 training_step) over a WorkerSet of RolloutWorker actors
(evaluation/worker_set.py, rollout_worker.py) and the new API stack's
Learner/LearnerGroup (core/learner/learner_group.py:61). The TPU-native
split (BASELINE.md config 5): env sampling stays on CPU actor fleets;
the policy update is one jitted SPMD step on the TPU mesh.

    from ray_tpu.rl import PPOConfig, PPOTrainer

    trainer = PPOTrainer(PPOConfig(env="CartPole-v1", num_rollout_workers=2))
    for _ in range(10):
        metrics = trainer.train()
"""

from ray_tpu.rl.ppo import PPOConfig, PPOTrainer

__all__ = ["PPOConfig", "PPOTrainer"]
