"""ray_tpu.rl: reinforcement learning — TPU learner, CPU rollout actors.

Reference: rllib/ — Algorithm (algorithms/algorithm.py:813 step,
:1400 training_step) over a WorkerSet of RolloutWorker actors
(evaluation/worker_set.py, rollout_worker.py) and the new API stack's
Learner/LearnerGroup (core/learner/learner_group.py:61). The TPU-native
split (BASELINE.md config 5): env sampling stays on CPU actor fleets;
the policy update is one jitted SPMD step on the TPU mesh.

    from ray_tpu.rl import PPOConfig, PPOTrainer

    trainer = PPOTrainer(PPOConfig(env="CartPole-v1", num_rollout_workers=2))
    for _ in range(10):
        metrics = trainer.train()
"""

from ray_tpu.rl.a2c import A2CConfig, A2CTrainer
from ray_tpu.rl.apex import (ApexDQNConfig, ApexDQNTrainer,
                             PrioritizedReplayActor,
                             PrioritizedReplayBuffer)
from ray_tpu.rl.appo import APPOConfig, APPOTrainer
from ray_tpu.rl.bandit import (BanditConfig, LinearDiscreteBanditEnv,
                               LinTSTrainer, LinUCBTrainer)
from ray_tpu.rl.connectors import (ClipObs, Connector, ConnectorPipeline,
                                   FlattenObs, FrameStack, NormalizeObs)
from ray_tpu.rl.core import Algorithm, ReplayActor, ReplayBuffer
from ray_tpu.rl.ddpg import DDPGConfig, DDPGTrainer
from ray_tpu.rl.dqn import DQNConfig, DQNTrainer
from ray_tpu.rl.es import ARSConfig, ARSTrainer, ESConfig, ESTrainer
from ray_tpu.rl.impala import ImpalaConfig, ImpalaTrainer
from ray_tpu.rl.learner import Learner, LearnerGroup, LearnerSpec
from ray_tpu.rl.multi_agent import (MultiAgentEnv, MultiAgentPPOConfig,
                                    MultiAgentPPOTrainer,
                                    register_multi_agent_env)
from ray_tpu.rl.offline import BCConfig, BCTrainer, CQLConfig, CQLTrainer
from ray_tpu.rl.policy_server import (ExternalPPOConfig, ExternalPPOTrainer,
                                      PolicyClient, PolicyServer)
from ray_tpu.rl.ppo import PPOConfig, PPOTrainer
from ray_tpu.rl.sac import SACConfig, SACTrainer
from ray_tpu.rl.td3 import TD3Config, TD3Trainer
from ray_tpu.rl.pg import PGConfig, PGTrainer
from ray_tpu.rl.a3c import A3CConfig, A3CTrainer
from ray_tpu.rl.marwil import MARWILConfig, MARWILTrainer
from ray_tpu.rl.apex import ApexDDPGConfig, ApexDDPGTrainer
from ray_tpu.rl.ddppo import DDPPOConfig, DDPPOTrainer
from ray_tpu.rl.offline import CRRConfig, CRRTrainer
from ray_tpu.rl.r2d2 import R2D2Config, R2D2Trainer
from ray_tpu.rl.simple_q import (RandomAgentConfig, RandomAgentTrainer,
                                 SimpleQConfig, SimpleQTrainer)
from ray_tpu.rl.qmix import QMIXConfig, QMIXTrainer, TwoStepGame
from ray_tpu.rl.maddpg import LineSpreadEnv, MADDPGConfig, MADDPGTrainer
from ray_tpu.rl.dt import DTConfig, DTTrainer
from ray_tpu.rl.alpha_zero import (AlphaZeroConfig, AlphaZeroTrainer,
                                   TicTacToe)
from ray_tpu.rl.maml import MAMLConfig, MAMLTrainer, PointGoalEnv
from ray_tpu.rl.slateq import SlateQConfig, SlateQTrainer, SlateRecEnv

_REGISTRY = {
    "PPO": (PPOConfig, PPOTrainer),
    "DQN": (DQNConfig, DQNTrainer),
    "SAC": (SACConfig, SACTrainer),
    "IMPALA": (ImpalaConfig, ImpalaTrainer),
    "TD3": (TD3Config, TD3Trainer),
    "A2C": (A2CConfig, A2CTrainer),
    "BC": (BCConfig, BCTrainer),
    "CQL": (CQLConfig, CQLTrainer),
    "MultiAgentPPO": (MultiAgentPPOConfig, MultiAgentPPOTrainer),
    "APPO": (APPOConfig, APPOTrainer),
    "ApexDQN": (ApexDQNConfig, ApexDQNTrainer),
    "DDPG": (DDPGConfig, DDPGTrainer),
    "ES": (ESConfig, ESTrainer),
    "ARS": (ARSConfig, ARSTrainer),
    "BanditLinUCB": (BanditConfig, LinUCBTrainer),
    "BanditLinTS": (BanditConfig, LinTSTrainer),
    "PG": (PGConfig, PGTrainer),
    "A3C": (A3CConfig, A3CTrainer),
    "MARWIL": (MARWILConfig, MARWILTrainer),
    "SimpleQ": (SimpleQConfig, SimpleQTrainer),
    "RandomAgent": (RandomAgentConfig, RandomAgentTrainer),
    "R2D2": (R2D2Config, R2D2Trainer),
    "CRR": (CRRConfig, CRRTrainer),
    "ApexDDPG": (ApexDDPGConfig, ApexDDPGTrainer),
    "DDPPO": (DDPPOConfig, DDPPOTrainer),
    "QMIX": (QMIXConfig, QMIXTrainer),
    "MADDPG": (MADDPGConfig, MADDPGTrainer),
    "DT": (DTConfig, DTTrainer),
    "AlphaZero": (AlphaZeroConfig, AlphaZeroTrainer),
    "MAML": (MAMLConfig, MAMLTrainer),
    "SlateQ": (SlateQConfig, SlateQTrainer),
}


def get_algorithm(name: str):
    """(ConfigCls, TrainerCls) by name (ref: rllib registry.py
    get_algorithm_class)."""
    if name not in _REGISTRY:
        raise ValueError(
            f"unknown algorithm {name!r}; have {sorted(_REGISTRY)}")
    return _REGISTRY[name]


__all__ = [
    "Algorithm", "ReplayBuffer", "ReplayActor", "get_algorithm",
    "PPOConfig", "PPOTrainer", "DQNConfig", "DQNTrainer",
    "SACConfig", "SACTrainer", "ImpalaConfig", "ImpalaTrainer",
    "TD3Config", "TD3Trainer", "A2CConfig", "A2CTrainer",
    "BCConfig", "BCTrainer", "CQLConfig", "CQLTrainer",
    "MultiAgentEnv", "MultiAgentPPOConfig", "MultiAgentPPOTrainer",
    "register_multi_agent_env",
    "PGConfig", "PGTrainer", "A3CConfig", "A3CTrainer",
    "MARWILConfig", "MARWILTrainer",
    "SimpleQConfig", "SimpleQTrainer", "RandomAgentConfig",
    "RandomAgentTrainer", "R2D2Config", "R2D2Trainer",
    "CRRConfig", "CRRTrainer", "ApexDDPGConfig", "ApexDDPGTrainer",
    "DDPPOConfig", "DDPPOTrainer",
    "QMIXConfig", "QMIXTrainer", "TwoStepGame",
    "MADDPGConfig", "MADDPGTrainer", "LineSpreadEnv",
    "DTConfig", "DTTrainer", "AlphaZeroConfig", "AlphaZeroTrainer",
    "TicTacToe", "MAMLConfig", "MAMLTrainer", "PointGoalEnv",
    "SlateQConfig", "SlateQTrainer", "SlateRecEnv",
    "Learner", "LearnerGroup", "LearnerSpec",
    "Connector", "ConnectorPipeline", "NormalizeObs", "FrameStack",
    "FlattenObs", "ClipObs",
    "PolicyServer", "PolicyClient", "ExternalPPOConfig",
    "ExternalPPOTrainer",
    "APPOConfig", "APPOTrainer", "DDPGConfig", "DDPGTrainer",
    "ApexDQNConfig", "ApexDQNTrainer", "PrioritizedReplayBuffer",
    "PrioritizedReplayActor",
    "ESConfig", "ESTrainer", "ARSConfig", "ARSTrainer",
    "BanditConfig", "LinUCBTrainer", "LinTSTrainer",
    "LinearDiscreteBanditEnv",
]
