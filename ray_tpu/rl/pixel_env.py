"""PixelCatcher: a procedurally generated Atari-class pixel environment.

The ALE package is not in the TPU image, so the Atari north star
(BASELINE.json target 5: "PPO Atari — TPU learner + CPU rollout actors")
is exercised on this env instead: RGB uint8 frames at an Atari-like
resolution, discrete actions, rewards that demand reading ball/paddle
positions out of pixels — the same observation/connector/CNN pipeline an
ALE env would use (grayscale -> resize -> scale -> frame-stack ->
Nature-CNN), swap `env="ALE/Pong-v5"` in when ALE is installed.

Mechanics: a ball falls from the top at a random column; the agent slides
a paddle along the bottom (left/stay/right). +1 for a catch, -1 for a
miss; `dense_reward=True` adds a small per-step alignment shaping term
(useful for CI-speed learning tests). An episode is `balls_per_episode`
drops.

Reference: rllib/env/wrappers/atari_wrappers.py documents the pipeline
this env is designed to feed (WarpFrame/FrameStack); the env itself is
original (the reference ships no procedural pixel env).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


class PixelCatcher:
    """gymnasium-shaped env (reset/step/observation_space/action_space)
    without requiring the gymnasium registry — core.make_env constructs
    it via the "module:Class" path."""

    metadata = {"render_modes": []}

    def __init__(self, size: int = 84, paddle_width: int = 13,
                 ball_size: int = 5, fall_speed: int = 4,
                 paddle_speed: int = 4, balls_per_episode: int = 8,
                 dense_reward: bool = False, seed: Optional[int] = None):
        import gymnasium as gym

        self.size = size
        self.paddle_width = paddle_width
        self.ball_size = ball_size
        self.fall_speed = fall_speed
        self.paddle_speed = paddle_speed
        self.balls_per_episode = balls_per_episode
        self.dense_reward = dense_reward
        self._rng = np.random.default_rng(seed)
        self.observation_space = gym.spaces.Box(
            0, 255, (size, size, 3), np.uint8)
        self.action_space = gym.spaces.Discrete(3)
        self._frame = np.zeros((size, size, 3), np.uint8)

    # -- gymnasium API --------------------------------------------------

    def reset(self, *, seed: Optional[int] = None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self.paddle_x = self.size // 2
        self.balls_done = 0
        self._new_ball()
        return self._render(), {}

    def step(self, action: int):
        a = int(action)
        if a == 0:
            self.paddle_x -= self.paddle_speed
        elif a == 2:
            self.paddle_x += self.paddle_speed
        half = self.paddle_width // 2
        self.paddle_x = int(np.clip(self.paddle_x, half,
                                    self.size - 1 - half))

        self.ball_y += self.fall_speed
        reward = 0.0
        if self.dense_reward:
            # alignment shaping: in [-0.05, 0.05] per step
            reward += 0.05 * (1.0 - 2.0 * abs(self.ball_x - self.paddle_x)
                              / self.size)
        terminated = False
        if self.ball_y >= self.size - 3 - self.ball_size:
            caught = abs(self.ball_x - self.paddle_x) <= \
                (half + self.ball_size // 2)
            reward += 1.0 if caught else -1.0
            self.balls_done += 1
            if self.balls_done >= self.balls_per_episode:
                terminated = True
            else:
                self._new_ball()
        return self._render(), reward, terminated, False, {}

    def close(self):
        pass

    # -- internals ------------------------------------------------------

    def _new_ball(self):
        m = self.ball_size // 2 + 1
        self.ball_x = int(self._rng.integers(m, self.size - m))
        self.ball_y = 0

    def _render(self) -> np.ndarray:
        f = self._frame
        f[:] = 0
        s, bs = self.size, self.ball_size
        # paddle: light bar on the bottom rows
        half = self.paddle_width // 2
        f[s - 3:s, self.paddle_x - half:self.paddle_x + half + 1] = \
            (64, 192, 255)
        # ball: bright square
        y0 = int(np.clip(self.ball_y, 0, s - bs))
        x0 = int(np.clip(self.ball_x - bs // 2, 0, s - bs))
        f[y0:y0 + bs, x0:x0 + bs] = (255, 255, 64)
        return f.copy()


def atari_connectors(stack: int = 4, out_size: int = 42):
    """The standard pixel pipeline as connector factories (ref:
    atari_wrappers.py WarpFrame+FrameStack): grayscale -> resize ->
    [0,1] scale -> stack along channels. Returns a list suitable for
    PPOConfig.obs_connectors / ImpalaConfig.obs_connectors."""
    from ray_tpu.rl.connectors import (FrameStack, GrayscaleObs, ResizeObs,
                                       ScaleObs)

    return [GrayscaleObs, lambda: ResizeObs(out_size, out_size),
            lambda: ScaleObs(1.0 / 255.0), lambda: FrameStack(stack)]
