"""AlphaZero: MCTS self-play with a policy/value network.

Reference: rllib/algorithms/alpha_zero/ (alpha_zero.py, mcts.py,
ranked_rewards.py — Silver et al.: rollout workers run PUCT tree search
guided by the current network to generate (state, visit-distribution,
outcome) targets; the learner fits policy cross-entropy + value MSE).
Self-play and the Python tree search stay on CPU actors; the network
update is the jitted TPU step. The built-in env is TicTacToe (the
reference tests use the same, rllib/examples/env/tic_tac_toe.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.rl.core import Algorithm, CPU_WORKER_ENV, ReplayBuffer, mlp_forward, mlp_init


# --- game: TicTacToe ---------------------------------------------------------


class TicTacToe:
    """Two-player zero-sum board game with the minimal interface MCTS
    needs: clone/step/legal_actions/outcome, canonical obs from the
    current player's perspective."""

    def __init__(self):
        self.board = np.zeros(9, np.int8)   # +1 / -1 / 0
        self.player = 1

    def clone(self) -> "TicTacToe":
        g = TicTacToe()
        g.board = self.board.copy()
        g.player = self.player
        return g

    def legal_actions(self) -> np.ndarray:
        return np.flatnonzero(self.board == 0)

    def step(self, action: int):
        assert self.board[action] == 0
        self.board[action] = self.player
        self.player = -self.player

    _LINES = [(0, 1, 2), (3, 4, 5), (6, 7, 8), (0, 3, 6), (1, 4, 7),
              (2, 5, 8), (0, 4, 8), (2, 4, 6)]

    def outcome(self) -> Optional[int]:
        """+1/-1 for the winning MARK, 0 draw, None if ongoing."""
        for a, b, c in self._LINES:
            s = int(self.board[a]) + int(self.board[b]) + int(self.board[c])
            if s == 3:
                return 1
            if s == -3:
                return -1
        return 0 if not (self.board == 0).any() else None

    def obs(self) -> np.ndarray:
        """Canonical: current player's stones, opponent's stones."""
        mine = (self.board == self.player).astype(np.float32)
        theirs = (self.board == -self.player).astype(np.float32)
        return np.concatenate([mine, theirs])

    N_ACTIONS = 9
    OBS_DIM = 18


# --- network -----------------------------------------------------------------


def init_az_net(key, obs_dim: int, n_actions: int, hidden: int):
    import jax

    k1, k2, k3 = jax.random.split(key, 3)
    return {"torso": mlp_init(k1, [obs_dim, hidden, hidden]),
            "pi": mlp_init(k2, [hidden, n_actions], out_scale=0.01),
            "v": mlp_init(k3, [hidden, 1], out_scale=0.01)}


def az_forward(net, obs):
    import jax.numpy as jnp

    h = mlp_forward(net["torso"], obs, final_activation=True)
    return mlp_forward(net["pi"], h), jnp.tanh(
        mlp_forward(net["v"], h))[..., 0]


# --- MCTS (numpy, worker-side) ----------------------------------------------


class _Node:
    __slots__ = ("prior", "visits", "value_sum", "children")

    def __init__(self, prior: float):
        self.prior = prior
        self.visits = 0
        self.value_sum = 0.0
        self.children: Dict[int, "_Node"] = {}

    def q(self) -> float:
        return self.value_sum / self.visits if self.visits else 0.0


def mcts_policy(net, game: TicTacToe, num_sims: int, c_puct: float,
                rng, dirichlet_alpha: float = 0.3,
                root_noise_frac: float = 0.25) -> np.ndarray:
    """PUCT search from `game`; returns the visit distribution over
    actions (ref: rllib mcts.py compute_action)."""

    def evaluate(g: TicTacToe) -> Tuple[np.ndarray, float]:
        out = g.outcome()
        if out is not None:
            # terminal value from the CURRENT player's perspective:
            # out is for the mark; current player is about to move, so a
            # decided game means the PREVIOUS mover won -> value -1
            return np.zeros(g.N_ACTIONS, np.float32), \
                (0.0 if out == 0 else -1.0)
        logits, v = az_forward(net, g.obs()[None])
        p = np.exp(np.asarray(logits)[0] - np.asarray(logits)[0].max())
        legal = np.zeros(g.N_ACTIONS, np.float32)
        legal[g.legal_actions()] = 1.0
        p = p * legal
        p = p / p.sum() if p.sum() > 0 else legal / legal.sum()
        return p, float(np.asarray(v)[0])

    priors, _ = evaluate(game)
    legal = game.legal_actions()
    noise = rng.dirichlet([dirichlet_alpha] * len(legal))
    for i, a in enumerate(legal):
        priors[a] = ((1 - root_noise_frac) * priors[a]
                     + root_noise_frac * noise[i])
    root = _Node(0.0)
    for a in legal:
        root.children[int(a)] = _Node(float(priors[a]))

    for _ in range(num_sims):
        g = game.clone()
        node = root
        path = [root]
        # select
        while node.children:
            total = sum(ch.visits for ch in node.children.values())
            best, best_score = None, -np.inf
            for a, ch in node.children.items():
                u = c_puct * ch.prior * np.sqrt(total + 1) / (1 + ch.visits)
                # child value is from the opponent's perspective
                score = -ch.q() + u
                if score > best_score:
                    best, best_score = a, score
            g.step(best)
            node = node.children[best]
            path.append(node)
        # expand + evaluate
        p, v = evaluate(g)
        if g.outcome() is None:
            for a in g.legal_actions():
                node.children[int(a)] = _Node(float(p[a]))
        # backup: v is from the perspective of the player to move at the
        # leaf; alternate signs up the path
        for n_ in reversed(path):
            n_.visits += 1
            n_.value_sum += v
            v = -v

    visits = np.zeros(game.N_ACTIONS, np.float32)
    for a, ch in root.children.items():
        visits[a] = ch.visits
    return visits / visits.sum()


@ray_tpu.remote(num_cpus=0.5)
class _SelfPlayWorker:
    def __init__(self, seed: int, num_sims: int, c_puct: float,
                 temperature: float):
        import os

        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        self.rng = np.random.default_rng(seed)
        self.num_sims = num_sims
        self.c_puct = c_puct
        self.temperature = temperature
        self.outcomes: List[int] = []

    def play_games(self, net, n_games: int):
        obs_l, pi_l, z_l = [], [], []
        for _ in range(n_games):
            g = TicTacToe()
            traj = []                      # (obs, pi, player)
            while g.outcome() is None:
                pi = mcts_policy(net, g, self.num_sims, self.c_puct,
                                 self.rng)
                traj.append((g.obs(), pi, g.player))
                if self.temperature > 0:
                    t = pi ** (1.0 / self.temperature)
                    a = int(self.rng.choice(g.N_ACTIONS, p=t / t.sum()))
                else:
                    a = int(pi.argmax())
                g.step(a)
            out = g.outcome()
            self.outcomes.append(out)
            for obs, pi, player in traj:
                obs_l.append(obs)
                pi_l.append(pi)
                z_l.append(float(out * player))   # outcome from mover's view
        return {"obs": np.stack(obs_l), "pi": np.stack(pi_l),
                "z": np.asarray(z_l, np.float32)}

    def stats(self):
        o = self.outcomes[-50:]
        return {"games": len(self.outcomes),
                "draw_rate": float(np.mean([x == 0 for x in o]))
                if o else 0.0}


# --- trainer -----------------------------------------------------------------


@dataclass
class AlphaZeroConfig:
    num_rollout_workers: int = 2
    games_per_worker: int = 4
    num_sims: int = 25
    c_puct: float = 1.5
    temperature: float = 1.0
    replay_capacity: int = 10_000
    train_batch_size: int = 128
    updates_per_iter: int = 16
    lr: float = 1e-3
    hidden: int = 64
    seed: int = 0


class AlphaZeroTrainer(Algorithm):
    """ref: rllib/algorithms/alpha_zero/alpha_zero.py training_step —
    self-play games into replay, train pi to the visit counts and v to
    the game outcome."""

    def _setup(self, cfg: AlphaZeroConfig):
        import jax
        import optax

        self.net = init_az_net(jax.random.PRNGKey(cfg.seed),
                               TicTacToe.OBS_DIM, TicTacToe.N_ACTIONS,
                               cfg.hidden)
        self.opt = optax.adam(cfg.lr)
        self.opt_state = self.opt.init(self.net)
        self.buffer = ReplayBuffer(cfg.replay_capacity, cfg.seed)
        self.workers = [
            _SelfPlayWorker.options(runtime_env=CPU_WORKER_ENV).remote(cfg.seed + i * 1000, cfg.num_sims,
                                   cfg.c_puct, cfg.temperature)
            for i in range(cfg.num_rollout_workers)]
        self.games_total = 0
        self._update = jax.jit(self._make_update())

    def _make_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        def loss_fn(net, mb):
            logits, v = az_forward(net, mb["obs"])
            pi_loss = -(mb["pi"] * jax.nn.log_softmax(logits)).sum(-1).mean()
            v_loss = jnp.square(v - mb["z"]).mean()
            return pi_loss + v_loss, {"pi_loss": pi_loss, "v_loss": v_loss}

        def update(net, opt_state, mb):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(net, mb)
            upd, opt_state = self.opt.update(grads, opt_state, net)
            return optax.apply_updates(net, upd), opt_state, \
                {"loss": loss, **aux}

        return update

    def training_step(self) -> Dict[str, Any]:
        import jax

        cfg = self.config
        net_host = jax.device_get(self.net)
        refs = [w.play_games.remote(net_host, cfg.games_per_worker)
                for w in self.workers]
        for b in ray_tpu.get(refs):
            self.buffer.add_batch(b)
        self.games_total += cfg.games_per_worker * len(self.workers)

        aux = {}
        for _ in range(cfg.updates_per_iter):
            # fixed batch size (sampling with replacement while the
            # buffer is small) -> one XLA compilation of _update
            mb = self.buffer.sample(cfg.train_batch_size)
            self.net, self.opt_state, aux = self._update(
                self.net, self.opt_state, mb)
        stats = ray_tpu.get([w.stats.remote() for w in self.workers])
        return {"games_total": self.games_total,
                "draw_rate": float(np.mean([s["draw_rate"]
                                            for s in stats])),
                "buffer_size": len(self.buffer),
                **{k: float(v) for k, v in aux.items()}}

    def get_weights(self):
        return self.net

    def set_weights(self, weights):
        self.net = weights
