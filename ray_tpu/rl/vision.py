"""Vision (CNN) policy network for pixel observations.

Reference: rllib/models/torch/visionnet.py:22 (VisionNetwork — the conv
stack rllib attaches for image observations, defaulting to the Nature-DQN
filters) and rllib/models/utils.py get_filter_config (84x84 -> [32 8x8/4,
64 4x4/2, 64 3x3/1]). TPU shape: the whole network is pure JAX on NHWC
tensors so the jitted learner update runs conv + dense on the MXU in one
compiled function; rollout actors run the same function on CPU.

The params dict carries a "conv" key, which is how
ppo.policy_forward dispatches between the MLP and this network — PPO,
IMPALA, APPO and DDPPO all route through that one entry point, so every
actor-critic algorithm in the zoo gains pixel support from this module.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np

# Nature-DQN filter config (ref: rllib/models/utils.py get_filter_config)
NATURE_FILTERS = ((32, (8, 8), 4), (64, (4, 4), 2), (64, (3, 3), 1))


def conv_out_hw(h: int, w: int,
                filters=NATURE_FILTERS) -> Tuple[int, int]:
    """Spatial dims after the conv stack (VALID padding)."""
    for _, (kh, kw), s in filters:
        h = (h - kh) // s + 1
        w = (w - kw) // s + 1
    return h, w


def init_vision_policy(key, obs_shape: Sequence[int], n_actions: int,
                       hidden: int = 512, filters=NATURE_FILTERS):
    """obs_shape: (H, W, C) AFTER the connector pipeline (e.g. 84x84x4
    for grayscale frame-stack). Returns a params dict compatible with
    ppo.policy_forward's dispatch."""
    import jax
    import jax.numpy as jnp

    H, W, C = obs_shape
    keys = jax.random.split(key, len(filters) + 3)
    conv = []
    cin = C
    # strides stay OUT of the params pytree (static config, not a
    # differentiable leaf); vision_forward reads them from `filters`
    for i, (cout, (kh, kw), _stride) in enumerate(filters):
        fan_in = kh * kw * cin
        conv.append({
            "w": jax.random.normal(keys[i], (kh, kw, cin, cout))
            * (2.0 / fan_in) ** 0.5,
            "b": jnp.zeros((cout,)),
        })
        cin = cout
    oh, ow = conv_out_hw(H, W, filters)
    flat = oh * ow * cin
    if oh <= 0 or ow <= 0:
        raise ValueError(
            f"obs {tuple(obs_shape)} too small for the conv stack "
            f"(got {oh}x{ow} after convs); resize up or shrink filters")

    def dense(k, i, o, scale=None):
        s = (2.0 / i) ** 0.5 if scale is None else scale
        return {"w": jax.random.normal(k, (i, o)) * s,
                "b": jnp.zeros((o,))}

    return {
        "conv": conv,
        "head": dense(keys[-3], flat, hidden),
        # small-init pi head: near-uniform initial policy (standard for
        # pixel PPO; large initial logits collapse exploration)
        "pi": dense(keys[-2], hidden, n_actions, scale=0.01),
        "v": dense(keys[-1], hidden, 1),
    }


def vision_forward(params, obs, filters=NATURE_FILTERS):
    """obs [B, H, W, C] float (already scaled by the connector pipeline)
    -> (logits [B, A], value [B]). `filters` must match the config the
    params were initialized with (strides are static, not params)."""
    import jax
    import jax.numpy as jnp

    x = jnp.asarray(obs)
    for layer, (_cout, _k, stride) in zip(params["conv"], filters):
        x = jax.lax.conv_general_dilated(
            x, layer["w"], window_strides=(stride, stride), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        x = jax.nn.relu(x + layer["b"])
    x = x.reshape(x.shape[0], -1)
    x = jax.nn.relu(x @ params["head"]["w"] + params["head"]["b"])
    logits = x @ params["pi"]["w"] + params["pi"]["b"]
    value = (x @ params["v"]["w"] + params["v"]["b"])[..., 0]
    return logits, value
