"""R2D2: recurrent replay distributed DQN.

Reference: rllib/algorithms/r2d2/ (r2d2.py — recurrent DQN over
fixed-length stored-state sequences with burn-in, double-Q, target
network; "Recurrent Experience Replay in Distributed RL", Kapturowski
et al.). TPU shape: the LSTM unroll is a `lax.scan` inside one jitted
update — burn-in steps warm the hidden state under stop_gradient, the
training segment contributes the TD loss. Sequences (not transitions)
are the replay unit; each carries the LSTM state observed when it was
generated ("stored state" strategy).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

import numpy as np

import ray_tpu
from ray_tpu.rl.core import (CPU_WORKER_ENV, Algorithm, EnvSampler, ReplayBuffer,
                             dense_init, mlp_forward, mlp_init,
                             probe_env_spec)


# --- recurrent Q network -----------------------------------------------------


def init_rqnet(key, obs_dim: int, n_actions: int, hidden: int):
    import jax

    k1, k2, k3 = jax.random.split(key, 3)
    return {
        "enc": mlp_init(k1, [obs_dim, hidden]),
        # one fused LSTM projection: [x, h] -> 4*hidden gates
        "lstm": dense_init(k2, 2 * hidden, 4 * hidden, scale=0.3),
        "q": mlp_init(k3, [hidden, n_actions], out_scale=0.01),
    }


def lstm_step(net, carry, x):
    """One LSTM cell step; carry = (h, c), x = encoded obs [..., H]."""
    import jax
    import jax.numpy as jnp

    h, c = carry
    gates = jnp.concatenate([x, h], -1) @ net["lstm"]["w"] + net["lstm"]["b"]
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    c = jax.nn.sigmoid(i) * jnp.tanh(g) + jax.nn.sigmoid(f + 1.0) * c
    h = jax.nn.sigmoid(o) * jnp.tanh(c)
    return (h, c), h


def rq_unroll(net, obs_seq, h0, c0):
    """Q values over a [B, T, obs] sequence from initial state.
    Returns (q [B, T, A], (h, c) final)."""
    import jax
    import jax.numpy as jnp

    enc = jnp.tanh(mlp_forward(net["enc"], obs_seq))   # [B, T, H]

    def step(carry, x_t):
        carry, h = lstm_step(net, carry, x_t)
        return carry, h

    carry, hs = jax.lax.scan(step, (h0, c0),
                             jnp.swapaxes(enc, 0, 1))  # scan over T
    hs = jnp.swapaxes(hs, 0, 1)                         # [B, T, H]
    return mlp_forward(net["q"], hs), carry


# --- rollout worker ----------------------------------------------------------


@ray_tpu.remote(num_cpus=0.5)
class _R2D2Worker(EnvSampler):
    """Epsilon-greedy recurrent sampler emitting fixed-length sequences
    with their initial LSTM state (ref: r2d2 sequence collection via
    rollout_fragment_length = replay_sequence_length)."""

    def __init__(self, env_name: str, seed: int, hidden: int,
                 env_config: Optional[dict] = None):
        super().__init__(env_name, seed, env_config)
        self.rng = np.random.default_rng(seed)
        self.hidden = hidden
        self.h = np.zeros(hidden, np.float32)
        self.c = np.zeros(hidden, np.float32)

    def sample(self, net, num_seqs: int, seq_len: int, epsilon: float):
        import jax.numpy as jnp

        seqs = {k: [] for k in ("obs", "actions", "rewards", "dones",
                                "h0", "c0")}
        for _ in range(num_seqs):
            h0, c0 = self.h.copy(), self.c.copy()
            obs_l = [np.asarray(self.obs, np.float32)]
            act_l, rew_l, done_l = [], [], []
            for _ in range(seq_len):
                q, (h, c) = rq_unroll(
                    net, jnp.asarray(self.obs, jnp.float32)[None, None],
                    jnp.asarray(self.h)[None], jnp.asarray(self.c)[None])
                # np.array (copy): jax arrays view as read-only
                self.h = np.array(h[0], np.float32)
                self.c = np.array(c[0], np.float32)
                if self.rng.random() < epsilon:
                    action = int(self.env.action_space.sample())
                else:
                    action = int(np.asarray(q)[0, 0].argmax())
                _prev, rew, term, trunc, nobs = self.step_env(action)
                act_l.append(action)
                rew_l.append(rew)
                done_l.append(float(term))
                obs_l.append(np.asarray(nobs, np.float32))
                if term or trunc:
                    self.h = np.zeros(self.hidden, np.float32)
                    self.c = np.zeros(self.hidden, np.float32)
            seqs["obs"].append(np.stack(obs_l))          # [T+1, obs]
            seqs["actions"].append(np.asarray(act_l, np.int32))
            seqs["rewards"].append(np.asarray(rew_l, np.float32))
            seqs["dones"].append(np.asarray(done_l, np.float32))
            seqs["h0"].append(h0)
            seqs["c0"].append(c0)
        return {k: np.stack(v) for k, v in seqs.items()}


# --- trainer -----------------------------------------------------------------


@dataclass
class R2D2Config:
    env: str = "CartPole-v1"
    env_config: Dict[str, Any] = field(default_factory=dict)
    num_rollout_workers: int = 2
    seqs_per_worker: int = 4        # sequences sampled per worker per iter
    burn_in: int = 8                # warm-up steps, no gradient
    train_len: int = 16             # TD-loss steps per sequence
    replay_capacity: int = 2_000    # in sequences
    learning_starts: int = 16       # in sequences
    train_batch_size: int = 16      # sequences per update
    updates_per_iter: int = 8
    lr: float = 1e-3
    gamma: float = 0.99
    target_network_update_freq: int = 40   # in sampled sequences
    epsilon_initial: float = 1.0
    epsilon_final: float = 0.05
    epsilon_timesteps: int = 10_000
    hidden: int = 32
    seed: int = 0


class R2D2Trainer(Algorithm):
    """ref: rllib/algorithms/r2d2/r2d2.py training_step — sample
    sequences, replay-train with burn-in, periodic target sync."""

    def _setup(self, cfg: R2D2Config):
        import jax
        import optax

        obs_dim, n_actions, _, _ = probe_env_spec(cfg.env, cfg.env_config)
        assert n_actions is not None, "R2D2 needs a discrete action space"
        self.net = init_rqnet(jax.random.PRNGKey(cfg.seed), obs_dim,
                              n_actions, cfg.hidden)
        self.target = jax.tree_util.tree_map(lambda x: x, self.net)
        self.opt = optax.adam(cfg.lr)
        self.opt_state = self.opt.init(self.net)
        self.buffer = ReplayBuffer(cfg.replay_capacity, cfg.seed)
        seq_len = cfg.burn_in + cfg.train_len
        self.seq_len = seq_len
        self.workers = [
            _R2D2Worker.options(runtime_env=CPU_WORKER_ENV).remote(cfg.env, cfg.seed + i * 1000, cfg.hidden,
                               cfg.env_config)
            for i in range(cfg.num_rollout_workers)]
        self.timesteps = 0
        self.seqs_sampled = 0
        self._since_target_sync = 0
        self._update = jax.jit(self._make_update())

    def _make_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.config
        B_in, T = cfg.burn_in, cfg.train_len

        def loss_fn(net, target, mb):
            # burn-in: advance both hidden states without gradient
            h0, c0 = mb["h0"], mb["c0"]
            if B_in:
                _, (h, c) = rq_unroll(net, mb["obs"][:, :B_in], h0, c0)
                h, c = (jax.lax.stop_gradient(h),
                        jax.lax.stop_gradient(c))
                _, (ht, ct) = rq_unroll(target, mb["obs"][:, :B_in],
                                        h0, c0)
            else:
                h, c, ht, ct = h0, c0, h0, c0
            # training segment needs T+1 obs for the bootstrap value
            seg = mb["obs"][:, B_in:B_in + T + 1]
            q, _ = rq_unroll(net, seg, h, c)               # [B, T+1, A]
            qt, _ = rq_unroll(target, seg, ht, ct)
            acts = mb["actions"][:, B_in:]
            q_sel = jnp.take_along_axis(q[:, :T], acts[..., None],
                                        -1)[..., 0]
            a_star = q[:, 1:].argmax(-1)                   # double-Q
            q_next = jnp.take_along_axis(qt[:, 1:], a_star[..., None],
                                         -1)[..., 0]
            rew = mb["rewards"][:, B_in:]
            done = mb["dones"][:, B_in:]
            tgt = rew + cfg.gamma * (1 - done) * q_next
            return jnp.square(q_sel - jax.lax.stop_gradient(tgt)).mean()

        def update(net, target, opt_state, mb):
            loss, grads = jax.value_and_grad(loss_fn)(net, target, mb)
            upd, opt_state = self.opt.update(grads, opt_state, net)
            return optax.apply_updates(net, upd), opt_state, loss

        return update

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self.timesteps / max(1, cfg.epsilon_timesteps))
        return cfg.epsilon_initial + frac * (cfg.epsilon_final
                                             - cfg.epsilon_initial)

    def training_step(self) -> Dict[str, Any]:
        import jax

        cfg = self.config
        net_host = jax.device_get(self.net)
        eps = self._epsilon()
        refs = [w.sample.remote(net_host, cfg.seqs_per_worker,
                                self.seq_len, eps)
                for w in self.workers]
        for b in ray_tpu.get(refs):
            self.buffer.add_batch(b)
            n = len(b["rewards"])
            self.seqs_sampled += n
            self._since_target_sync += n
            self.timesteps += n * self.seq_len

        loss = float("nan")
        updates = 0
        if len(self.buffer) >= cfg.learning_starts:
            for _ in range(cfg.updates_per_iter):
                mb = self.buffer.sample(cfg.train_batch_size)
                self.net, self.opt_state, loss = self._update(
                    self.net, self.target, self.opt_state, mb)
                updates += 1
            if self._since_target_sync >= cfg.target_network_update_freq:
                self.target = jax.tree_util.tree_map(lambda x: x, self.net)
                self._since_target_sync = 0
            loss = float(loss)

        stats = ray_tpu.get([w.episode_stats.remote() for w in self.workers])
        eps_done = [s for s in stats if s["episodes"]]
        return {
            "timesteps_total": self.timesteps,
            "episode_return_mean": float(np.mean(
                [s["mean_return"] for s in eps_done])) if eps_done else 0.0,
            "episodes_total": sum(s["episodes"] for s in stats),
            "loss": loss,
            "num_updates": updates,
            "epsilon": eps,
            "buffer_size": len(self.buffer),
        }

    def get_weights(self):
        return self.net

    def set_weights(self, weights):
        self.net = weights
