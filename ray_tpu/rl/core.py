"""Shared RL infrastructure: networks, replay, Algorithm base.

Reference: rllib's Algorithm (rllib/algorithms/algorithm.py:554 setup /
:813 step), ReplayBuffer (rllib/utils/replay_buffers/), and the
RolloutWorker fleet pattern (rllib/evaluation/worker_set.py). The learner
update is a single jitted function per algorithm (the TPU-native shape of
rllib's Learner, core/learner/learner.py) — batched, static shapes, no
Python in the step.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu


# --- tiny pure-JAX nets ------------------------------------------------------


def dense_init(key, i, o, scale: float = None):
    import jax

    s = (2.0 / i) ** 0.5 if scale is None else scale
    return {"w": jax.random.normal(key, (i, o)) * s,
            "b": jax.numpy.zeros((o,))}


def reward_to_go(batch_or_rewards, gamma: float, dones=None):
    """Discounted reward-to-go, resetting at dones (shared by PG/MARWIL;
    ref: postprocessing.compute_advantages with use_critic=False)."""
    import numpy as np

    if dones is None:
        rews = batch_or_rewards["rewards"]
        dones = batch_or_rewards["dones"]
    else:
        rews = batch_or_rewards
    out = np.zeros_like(rews, dtype=np.float32)
    running = 0.0
    for t in range(len(rews) - 1, -1, -1):
        running = rews[t] + gamma * running * (1.0 - dones[t])
        out[t] = running
    return out


def rollout_result(timesteps_total: int, worker_stats, aux) -> dict:
    """The standard on-policy result dict (shared by A2C/A3C/PG)."""
    import numpy as np

    eps_done = [s for s in worker_stats if s["episodes"]]
    return {
        "timesteps_total": timesteps_total,
        "episode_return_mean": float(np.mean(
            [s["mean_return"] for s in eps_done])) if eps_done else 0.0,
        "episodes_total": sum(s["episodes"] for s in worker_stats),
        **{k: float(v) for k, v in aux.items()},
    }


def mlp_init(key, sizes: List[int], out_scale: float = None):
    import jax

    keys = jax.random.split(key, len(sizes) - 1)
    layers = []
    for n, (i, o) in enumerate(zip(sizes[:-1], sizes[1:])):
        last = n == len(sizes) - 2
        layers.append(dense_init(keys[n], i, o,
                                 out_scale if last else None))
    return layers


def mlp_forward(layers, x, final_activation=False):
    import jax.numpy as jnp

    for n, layer in enumerate(layers):
        x = x @ layer["w"] + layer["b"]
        if n < len(layers) - 1 or final_activation:
            x = jnp.tanh(x)
    return x


# --- rollout sampling --------------------------------------------------------


# Rollout actors must never grab the TPU: the learner owns it, and a
# worker that initializes jax on the chip deadlocks the single-chip bench
# box. process_env_vars applies at worker-process spawn, BEFORE jax import
# (runtime_env.py) — EnvSampler's in-process setdefault alone is too late
# when the worker pool prestarted a process that already imported jax.
CPU_WORKER_ENV = {"process_env_vars": {"JAX_PLATFORMS": "cpu",
                                       "PALLAS_AXON_POOL_IPS": ""}}


def make_env(env_name: str, env_config: Optional[dict] = None):
    """Construct an env. "module:Class" names import and instantiate
    directly (no registry round-trip — works in any worker process, e.g.
    "ray_tpu.rl.pixel_env:PixelCatcher"); everything else goes through
    gymnasium.make (ref: rllib env_creator resolution in
    rllib/env/utils.py)."""
    if ":" in env_name:
        import importlib

        mod_name, cls_name = env_name.split(":", 1)
        cls = getattr(importlib.import_module(mod_name), cls_name)
        return cls(**(env_config or {}))
    import gymnasium as gym

    return gym.make(env_name, **(env_config or {}))


class EnvSampler:
    """Shared env-loop plumbing for rollout actors: env construction,
    episode-return accounting, reset handling (ref: rollout_worker.py
    sample loop bookkeeping). Subclasses implement action selection."""

    def __init__(self, env_name: str, seed: int = 0,
                 env_config: Optional[dict] = None):
        import os

        os.environ.setdefault("JAX_PLATFORMS", "cpu")

        self.env = make_env(env_name, env_config)
        self.seed = seed
        self.obs, _ = self.env.reset(seed=seed)
        self.steps = 0
        self.episode_return = 0.0
        self.completed: List[float] = []

    def step_env(self, action):
        """One env step with episode bookkeeping; returns
        (prev_obs, reward, terminated, truncated, next_obs) where next_obs
        is the pre-reset successor (what TD targets need)."""
        prev = self.obs
        nobs, rew, term, trunc, _ = self.env.step(action)
        successor = nobs
        self.episode_return += float(rew)
        self.steps += 1
        if term or trunc:
            self.completed.append(self.episode_return)
            self.episode_return = 0.0
            nobs, _ = self.env.reset()
        self.obs = nobs
        return prev, float(rew), bool(term), bool(trunc), successor

    def episode_stats(self) -> Dict[str, float]:
        return episode_stats_from(self.completed)

    def sample_transitions(self, select_action,
                           num_steps: int) -> Dict[str, np.ndarray]:
        """Collect an off-policy transition batch
        {obs, actions, rewards, dones, next_obs}; action choice is the
        only per-algorithm part (shared by the SAC/TD3 workers)."""
        obs_b, act_b, rew_b, done_b, nobs_b = [], [], [], [], []
        for _ in range(num_steps):
            action = select_action(self.obs)
            prev, rew, term, _trunc, nobs = self.step_env(action)
            obs_b.append(np.asarray(prev, np.float32))
            act_b.append(np.asarray(action, np.float32))
            rew_b.append(rew)
            done_b.append(float(term))
            nobs_b.append(np.asarray(nobs, np.float32))
        return {"obs": np.stack(obs_b), "actions": np.stack(act_b),
                "rewards": np.asarray(rew_b, np.float32),
                "dones": np.asarray(done_b, np.float32),
                "next_obs": np.stack(nobs_b)}


def episode_stats_from(completed: List[float]) -> Dict[str, float]:
    """Windowed episode-return stats shared by every rollout worker."""
    rets = completed[-20:]
    return {"episodes": len(completed),
            "mean_return": float(np.mean(rets)) if rets else 0.0}


# --- replay buffer -----------------------------------------------------------


class ReplayBuffer:
    """Uniform FIFO replay (ref: rllib/utils/replay_buffers/replay_buffer.py).
    Process-local; the off-policy trainers own one in the driver. For a
    distributed variant wrap it in an actor via `as_actor()`."""

    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = capacity
        self._storage: Dict[str, np.ndarray] = {}
        self._idx = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)

    def add_batch(self, batch: Dict[str, np.ndarray]):
        n = len(next(iter(batch.values())))
        if not self._storage:
            for k, v in batch.items():
                v = np.asarray(v)
                self._storage[k] = np.zeros((self.capacity,) + v.shape[1:],
                                            v.dtype)
        for k, v in batch.items():
            v = np.asarray(v)
            idx = (self._idx + np.arange(n)) % self.capacity
            self._storage[k][idx] = v
        self._idx = (self._idx + n) % self.capacity
        self._size = min(self._size + n, self.capacity)

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self._size, batch_size)
        return {k: v[idx] for k, v in self._storage.items()}

    def __len__(self):
        return self._size


@ray_tpu.remote
class ReplayActor:
    """Replay buffer as an actor, for async fill/sample fan-in
    (ref: rllib distributed replay in APEX)."""

    def __init__(self, capacity: int, seed: int = 0):
        self.buf = ReplayBuffer(capacity, seed)

    def add_batch(self, batch):
        self.buf.add_batch(batch)
        return len(self.buf)

    def sample(self, batch_size: int):
        if len(self.buf) < batch_size:
            return None
        return self.buf.sample(batch_size)

    def size(self):
        return len(self.buf)


# --- Algorithm base ----------------------------------------------------------


class Algorithm:
    """Minimal Trainable-compatible base (ref: Algorithm is a Tune
    Trainable; tune.Tuner can drive any subclass via the function API:
    `lambda cfg: loop over algo.train()`)."""

    def __init__(self, config):
        self.config = config
        self.iteration = 0
        self._setup(config)

    def _setup(self, config):
        raise NotImplementedError

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def train(self) -> Dict[str, Any]:
        t0 = time.time()
        result = self.training_step()
        self.iteration += 1
        result.setdefault("training_iteration", self.iteration)
        result.setdefault("time_this_iter_s", time.time() - t0)
        return result

    def save(self) -> Dict[str, Any]:
        import jax

        return {"params": jax.device_get(self.get_weights()),
                "iteration": self.iteration}

    def restore(self, ckpt: Dict[str, Any]):
        self.set_weights(ckpt["params"])
        self.iteration = ckpt.get("iteration", 0)

    def get_weights(self):
        raise NotImplementedError

    def set_weights(self, weights):
        raise NotImplementedError

    def stop(self):
        for w in getattr(self, "workers", []):
            try:
                ray_tpu.kill(w)
            except Exception:
                pass


def probe_connected_spec(env_name: str, env_config: Optional[dict],
                         connectors, seed: int = 0):
    """(obs_shape_after_connectors, n_actions) for a discrete-action env
    — the shared probe used by every actor-critic trainer (PPO/IMPALA/
    APPO/DDPPO) to size its policy net. Always closes the probe env."""
    from ray_tpu.rl.connectors import build_pipeline

    env = make_env(env_name, env_config)
    try:
        obs0, _ = env.reset(seed=seed)
        if not hasattr(env.action_space, "n"):
            raise ValueError(
                f"{env_name} is not discrete-action; this trainer family "
                "requires a Discrete action space")
        n_actions = int(env.action_space.n)
    finally:
        env.close()
    pipeline = build_pipeline(connectors)
    obs_shape = pipeline(np.asarray(obs0, np.float32)).shape
    return obs_shape, n_actions


def probe_env_spec(env_name: str, env_config: Optional[dict] = None):
    """(obs_dim, n_actions | None, act_dim | None, act_high)."""
    env = make_env(env_name, env_config)
    obs_dim = int(np.prod(env.observation_space.shape))
    n_actions = act_dim = act_high = None
    if hasattr(env.action_space, "n"):
        n_actions = int(env.action_space.n)
    else:
        act_dim = int(np.prod(env.action_space.shape))
        act_high = float(np.asarray(env.action_space.high).reshape(-1)[0])
    env.close()
    return obs_dim, n_actions, act_dim, act_high
