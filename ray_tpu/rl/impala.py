"""IMPALA: async rollout fan-in with V-trace off-policy correction.

Reference: rllib/algorithms/impala/ (async sample queue, V-trace from
Espeholt et al. 2018 — rho/c truncated importance weights). Workers sample
with stale weights while the learner updates; ray_tpu.wait() drives the
async fan-in instead of rllib's AsyncRequestsManager.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict

import numpy as np

import ray_tpu
from ray_tpu.rl.core import Algorithm, CPU_WORKER_ENV
from ray_tpu.rl.ppo import RolloutWorker, policy_forward


@dataclass
class ImpalaConfig:
    env: str = "CartPole-v1"
    env_config: Dict[str, Any] = field(default_factory=dict)
    num_rollout_workers: int = 2
    rollout_fragment_length: int = 100
    batches_per_iter: int = 4
    lr: float = 5e-4
    gamma: float = 0.99
    vtrace_rho_clip: float = 1.0
    vtrace_c_clip: float = 1.0
    vf_coeff: float = 0.5
    entropy_coeff: float = 0.01
    hidden: int = 64
    seed: int = 0
    # connector factories + network choice, same semantics as PPOConfig
    # (pixel IMPALA: atari_connectors() + the auto-selected NatureCNN)
    obs_connectors: Any = None
    network: str = "auto"
    cnn_hidden: int = 512


class ImpalaTrainer(Algorithm):
    """Async learner: keeps one in-flight sample request per worker,
    consumes whichever lands first (ref: impala.py training_step)."""

    def _setup(self, cfg: ImpalaConfig):
        import jax
        import optax

        from ray_tpu.rl.connectors import build_pipeline
        from ray_tpu.rl.core import probe_connected_spec
        from ray_tpu.rl.ppo import init_any_policy

        obs_shape, n_actions = probe_connected_spec(
            cfg.env, cfg.env_config, cfg.obs_connectors, cfg.seed)
        self.pipeline = build_pipeline(cfg.obs_connectors)
        self._conn_abs = None  # authoritative merged connector state
        self.params = init_any_policy(jax.random.PRNGKey(cfg.seed),
                                      obs_shape, n_actions, cfg)
        self.opt = optax.adam(cfg.lr)
        self.opt_state = self.opt.init(self.params)
        self.workers = [
            RolloutWorker.options(num_cpus=0.5, runtime_env=CPU_WORKER_ENV).remote(
                cfg.env, seed=cfg.seed + i * 1000,
                env_config=cfg.env_config,
                connectors=cfg.obs_connectors)
            for i in range(cfg.num_rollout_workers)]
        self._inflight: Dict[Any, Any] = {}   # ref -> worker
        self.timesteps = 0
        self._update = jax.jit(self._make_update())

    def _make_update(self):
        import jax
        import jax.numpy as jnp

        cfg = self.config

        def vtrace(values, rewards, dones, rhos, last_value):
            """Truncated importance-sampled value targets (V-trace)."""
            rho = jnp.minimum(rhos, cfg.vtrace_rho_clip)
            c = jnp.minimum(rhos, cfg.vtrace_c_clip)
            discounts = cfg.gamma * (1.0 - dones)
            next_values = jnp.concatenate([values[1:], last_value[None]])
            deltas = rho * (rewards + discounts * next_values - values)

            def scan_fn(acc, t):
                acc = deltas[t] + discounts[t] * c[t] * acc
                return acc, acc

            T = values.shape[0]
            _, vs_minus_v = jax.lax.scan(scan_fn, jnp.zeros(()),
                                         jnp.arange(T - 1, -1, -1))
            vs_minus_v = vs_minus_v[::-1]
            vs = values + vs_minus_v
            next_vs = jnp.concatenate([vs[1:], last_value[None]])
            pg_adv = rho * (rewards + discounts * next_vs - values)
            return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)

        def loss_fn(params, batch):
            logits, values = policy_forward(params, batch["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(logp_all, batch["actions"][:, None],
                                       -1)[:, 0]
            rhos = jnp.exp(logp - batch["logp"])
            vs, pg_adv = vtrace(values, batch["rewards"], batch["dones"],
                                rhos, batch["last_value"])
            pg_loss = -(logp * pg_adv).mean()
            vf_loss = 0.5 * jnp.square(values - vs).mean()
            ent = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
            total = pg_loss + cfg.vf_coeff * vf_loss - cfg.entropy_coeff * ent
            return total, {"pg_loss": pg_loss, "vf_loss": vf_loss,
                           "entropy": ent}

        def update(params, opt_state, batch):
            (total, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            import optax

            params = optax.apply_updates(params, updates)
            aux["total_loss"] = total
            return params, opt_state, aux

        return update

    def _launch(self, worker, params_host):
        # the merged absolute connector state rides along with the weights,
        # same collect/merge/broadcast cycle as PPOTrainer.train
        ref = worker.sample.remote(params_host,
                                   self.config.rollout_fragment_length,
                                   self._conn_abs)
        self._inflight[ref] = worker

    def training_step(self) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp

        cfg = self.config
        params_host = jax.device_get(self.params)
        for w in self.workers:
            if w not in self._inflight.values():
                self._launch(w, params_host)

        aux = {}
        consumed = 0
        while consumed < cfg.batches_per_iter:
            ready, _ = ray_tpu.wait(list(self._inflight), num_returns=1,
                                    timeout=60.0)
            if not ready:
                break
            for ref in ready:
                if consumed >= cfg.batches_per_iter:
                    break
                worker = self._inflight.pop(ref)
                b = ray_tpu.get(ref)
                delta = b.pop("connector_state", None)
                if delta is not None:
                    self._conn_abs = self.pipeline.merge_pipeline_states(
                        [delta], prev=self._conn_abs)
                batch = {
                    "obs": jnp.asarray(b["obs"]),
                    "actions": jnp.asarray(b["actions"]),
                    "rewards": jnp.asarray(b["rewards"]),
                    "dones": jnp.asarray(b["dones"], jnp.float32),
                    "logp": jnp.asarray(b["logp"]),
                    "last_value": jnp.asarray(b["last_value"]),
                }
                self.params, self.opt_state, aux = self._update(
                    self.params, self.opt_state, batch)
                self.timesteps += len(b["rewards"])
                consumed += 1
                # Relaunch immediately with the freshest weights (the
                # IMPALA point: actors never wait for the learner).
                self._launch(worker, jax.device_get(self.params))

        stats = ray_tpu.get([w.episode_stats.remote() for w in self.workers])
        eps_done = [s for s in stats if s["episodes"]]
        return {
            "timesteps_total": self.timesteps,
            "episode_return_mean": float(np.mean(
                [s["mean_return"] for s in eps_done])) if eps_done else 0.0,
            "episodes_total": sum(s["episodes"] for s in stats),
            "batches_consumed": consumed,
            **{k: float(v) for k, v in aux.items()},
        }

    def get_weights(self):
        return self.params

    def set_weights(self, weights):
        self.params = weights
