"""PPO: clipped surrogate objective, GAE, rollout-actor fleet + jitted learner.

Reference: rllib/algorithms/ppo/ (config + training_step) and
rllib/evaluation/rollout_worker.py sampling. Env interface is gymnasium
(available in-image); policy/value nets are small MLPs in pure JAX.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.rl.core import CPU_WORKER_ENV, EnvSampler


# --- policy (pure JAX, shared by learner and rollout workers) ----------------


def init_policy(key, obs_dim: int, n_actions: int, hidden: int = 64):
    import jax

    k1, k2, k3, k4 = jax.random.split(key, 4)

    def dense(k, i, o):
        return {"w": jax.random.normal(k, (i, o)) * (2.0 / i) ** 0.5,
                "b": jax.numpy.zeros((o,))}

    return {
        "torso": [dense(k1, obs_dim, hidden), dense(k2, hidden, hidden)],
        "pi": dense(k3, hidden, n_actions),
        "v": dense(k4, hidden, 1),
    }


def policy_forward(params, obs):
    import jax.numpy as jnp

    if "conv" in params:
        # pixel policy (rl/vision.py NatureCNN); PPO/IMPALA/APPO/DDPPO all
        # route through here, so the whole actor-critic family gains pixel
        # support from the one dispatch
        from ray_tpu.rl.vision import vision_forward

        return vision_forward(params, obs)
    x = obs
    for layer in params["torso"]:
        x = jnp.tanh(x @ layer["w"] + layer["b"])
    logits = x @ params["pi"]["w"] + params["pi"]["b"]
    value = (x @ params["v"]["w"] + params["v"]["b"])[..., 0]
    return logits, value


def init_any_policy(key, obs_shape, n_actions: int, cfg):
    """MLP for flat obs, NatureCNN for [H, W, C] obs (cfg.network
    "auto" | "mlp" | "cnn"; ref: rllib model catalog dispatch,
    rllib/models/catalog.py -> visionnet.py:22)."""
    net = getattr(cfg, "network", "auto")
    if net == "cnn" or (net == "auto" and len(obs_shape) == 3):
        from ray_tpu.rl.vision import init_vision_policy

        return init_vision_policy(key, obs_shape, n_actions,
                                  hidden=getattr(cfg, "cnn_hidden", 512))
    return init_policy(key, int(np.prod(obs_shape)), n_actions, cfg.hidden)


def categorical_sample(logits_row: np.ndarray, rng):
    """Numerically-stable softmax sample -> (action, logp). Shared by the
    single- and multi-agent rollout workers."""
    p = np.exp(logits_row - logits_row.max())
    p = p / p.sum()
    a = int(rng.choice(len(p), p=p))
    return a, float(np.log(p[a] + 1e-9))


# --- rollout worker (CPU actor) ---------------------------------------------


@ray_tpu.remote
class RolloutWorker(EnvSampler):
    """Samples env steps with the latest policy weights
    (ref: rollout_worker.py; sampler.py). Observations pass through the
    configured connector pipeline (ref: rllib agent connectors) exactly
    once each; the policy sees and trains on connected obs."""

    def __init__(self, env_name: str, seed: int = 0,
                 env_config=None, connectors=None):
        from ray_tpu.rl.connectors import build_pipeline

        super().__init__(env_name, seed, env_config)
        self.pipeline = build_pipeline(connectors)
        self.pipeline.on_episode_start()
        self._obs_t = None  # connected view of self.obs

    def connector_state(self):
        return self.pipeline.get_state()

    def set_connector_state(self, state):
        self.pipeline.set_state(state)

    def sample(self, params_host, num_steps: int,
               connector_state=None) -> Dict[str, np.ndarray]:
        import jax.numpy as jnp

        # merged absolute connector state rides along with the weights
        # (no extra sync round-trips); the returned batch carries this
        # fragment's DELTA state for the trainer to merge
        if connector_state is not None:
            self.pipeline.set_state(connector_state)
        rng = np.random.default_rng(self.seed + len(self.completed))
        obs_buf, act_buf, rew_buf, done_buf, logp_buf, val_buf = \
            [], [], [], [], [], []
        if self._obs_t is None:
            self._obs_t = self.pipeline(np.asarray(self.obs, np.float32))
        # params to device ONCE per fragment, forward jitted ONCE per
        # process: per-step eager dispatch dominates CNN rollouts
        # otherwise (~10x on the pixel env)
        import jax

        if not hasattr(self, "_jit_fwd"):
            self._jit_fwd = jax.jit(policy_forward)
        params_dev = jax.tree.map(jnp.asarray, params_host)
        for _ in range(num_steps):
            obs_t = self._obs_t
            logits, value = self._jit_fwd(params_dev,
                                          jnp.asarray(obs_t)[None])
            action, logp = categorical_sample(np.asarray(logits)[0], rng)
            _prev, rew, term, trunc, _nobs = self.step_env(action)
            if term or trunc:
                self.pipeline.on_episode_start()
            self._obs_t = self.pipeline(np.asarray(self.obs, np.float32))
            obs_buf.append(np.asarray(obs_t, np.float32))
            act_buf.append(action)
            rew_buf.append(rew)
            done_buf.append(term or trunc)
            logp_buf.append(logp)
            val_buf.append(float(np.asarray(value)[0]))
        # bootstrap value for the final (connected) state
        _, last_v = self._jit_fwd(params_dev, jnp.asarray(self._obs_t)[None])
        out = {
            "obs": np.stack(obs_buf),
            "actions": np.asarray(act_buf, np.int32),
            "rewards": np.asarray(rew_buf, np.float32),
            "dones": np.asarray(done_buf, np.bool_),
            "logp": np.asarray(logp_buf, np.float32),
            "values": np.asarray(val_buf, np.float32),
            "last_value": float(np.asarray(last_v)[0]),
        }
        if self.pipeline.connectors:
            out["connector_state"] = self.pipeline.get_state()
        return out


# --- GAE + learner -----------------------------------------------------------


def compute_gae(batch: dict, gamma: float, lam: float):
    rew, done, val = batch["rewards"], batch["dones"], batch["values"]
    T = len(rew)
    adv = np.zeros(T, np.float32)
    last_gae = 0.0
    next_v = batch["last_value"]
    for t in reversed(range(T)):
        nonterminal = 0.0 if done[t] else 1.0
        delta = rew[t] + gamma * next_v * nonterminal - val[t]
        last_gae = delta + gamma * lam * nonterminal * last_gae
        adv[t] = last_gae
        next_v = val[t]
    returns = adv + val
    return adv, returns


def make_ppo_loss(clip: float, vf_coeff: float, entropy_coeff: float):
    """The clipped-surrogate loss alone (shared by make_ppo_update and
    the DDPPO worker-side gradient, ddppo.py)."""
    import jax
    import jax.numpy as jnp

    def loss_fn(params, mb):
        logits, value = policy_forward(params, mb["obs"])
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(logp_all, mb["actions"][:, None],
                                   axis=-1)[:, 0]
        ratio = jnp.exp(logp - mb["logp"])
        adv = mb["adv"]
        pg = -jnp.minimum(
            ratio * adv,
            jnp.clip(ratio, 1 - clip, 1 + clip) * adv).mean()
        vf = 0.5 * jnp.square(value - mb["returns"]).mean()
        ent = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
        total = pg + vf_coeff * vf - entropy_coeff * ent
        return total, {"pg_loss": pg, "vf_loss": vf, "entropy": ent}

    return loss_fn


def make_ppo_update(cfg, opt):
    """Build the (un-jitted) clipped-surrogate update shared by
    PPOTrainer and MultiAgentPPOTrainer. cfg needs .clip/.vf_coeff/
    .entropy_coeff; opt is an optax optimizer."""
    import jax
    import optax

    loss_fn = make_ppo_loss(cfg.clip, cfg.vf_coeff, cfg.entropy_coeff)

    def update(params, opt_state, mb):
        (total, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, mb)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        aux["total_loss"] = total
        return params, opt_state, aux

    return update


def run_ppo_epochs(update, params, opt_state, *, obs, actions, logp, adv,
                   returns, num_epochs: int, minibatch_size: int, seed: int):
    """The shared epoch/minibatch drive used by every PPO-family trainer:
    normalize advantages, then num_epochs passes of shuffled FULL
    minibatches (constant shape -> exactly one XLA compilation of
    `update`; a variable-length remainder would recompile per
    iteration). With fewer than minibatch_size rows, indices wrap."""
    adv = (adv - adv.mean()) / (adv.std() + 1e-8)
    n = len(obs)
    mbs = minibatch_size
    rng = np.random.default_rng(seed)
    aux = {}
    for _ in range(num_epochs):
        perm = rng.permutation(n)
        if n < mbs:
            perm = np.resize(perm, mbs)      # wrap: one full minibatch
        for lo in range(0, len(perm) - mbs + 1, mbs):
            idx = perm[lo:lo + mbs]
            mb = {"obs": obs[idx], "actions": actions[idx],
                  "logp": logp[idx], "adv": adv[idx],
                  "returns": returns[idx]}
            params, opt_state, aux = update(params, opt_state, mb)
    return params, opt_state, aux


@dataclass
class PPOConfig:
    env: str = "CartPole-v1"
    env_config: Dict[str, Any] = field(default_factory=dict)
    num_rollout_workers: int = 2
    rollout_fragment_length: int = 200
    num_epochs: int = 4
    minibatch_size: int = 128
    lr: float = 3e-4
    gamma: float = 0.99
    lam: float = 0.95
    clip: float = 0.2
    vf_coeff: float = 0.5
    entropy_coeff: float = 0.01
    hidden: int = 64
    seed: int = 0
    # connector FACTORIES (zero-arg callables) so every worker gets its
    # own stateful instances (ref: rllib connectors_v2 config)
    obs_connectors: Optional[List[Any]] = None
    # "auto": CNN when the connected obs is [H, W, C], MLP otherwise
    # (ref: rllib model catalog picks VisionNetwork for image spaces)
    network: str = "auto"
    cnn_hidden: int = 512


class PPOTrainer:
    """ref: Algorithm.training_step (algorithm.py:1400) — sample via the
    worker fleet, update on device, broadcast new weights."""

    def __init__(self, config: PPOConfig):
        import jax
        import optax

        from ray_tpu.rl.connectors import build_pipeline

        self.cfg = config
        from ray_tpu.rl.core import probe_connected_spec

        # obs shape AFTER the connector pipeline (e.g. FrameStack widens it)
        obs_shape, n_actions = probe_connected_spec(
            config.env, config.env_config, config.obs_connectors,
            config.seed)
        self.pipeline = build_pipeline(config.obs_connectors)
        self.params = init_any_policy(
            jax.random.PRNGKey(config.seed), obs_shape, n_actions, config)
        self.opt = optax.adam(config.lr)
        self.opt_state = self.opt.init(self.params)
        self.workers = [
            RolloutWorker.options(num_cpus=0.5, runtime_env=CPU_WORKER_ENV).remote(
                config.env, seed=config.seed + i * 1000,
                env_config=config.env_config,
                connectors=config.obs_connectors)
            for i in range(config.num_rollout_workers)]
        self._update = jax.jit(self._make_update())
        self.iteration = 0
        self._conn_abs = None  # authoritative merged connector state

    def _make_update(self):
        return make_ppo_update(self.cfg, self.opt)

    def train(self) -> Dict[str, Any]:
        import jax

        t0 = time.time()
        params_host = jax.device_get(self.params)
        refs = [w.sample.remote(params_host, self.cfg.rollout_fragment_length,
                                self._conn_abs)
                for w in self.workers]
        batches = ray_tpu.get(refs)

        # connector state sync (ref: rllib MeanStdFilter collect/merge/
        # broadcast): worker DELTAS arrive inside the sample batches,
        # merge into the authoritative absolute state here, and the next
        # sample() call carries it back — zero extra round-trips
        if self.cfg.obs_connectors:
            deltas = [b.pop("connector_state", None) for b in batches]
            self._conn_abs = self.pipeline.merge_pipeline_states(
                deltas, prev=self._conn_abs)

        obs, acts, logps, advs, rets = [], [], [], [], []
        for b in batches:
            adv, ret = compute_gae(b, self.cfg.gamma, self.cfg.lam)
            obs.append(b["obs"])
            acts.append(b["actions"])
            logps.append(b["logp"])
            advs.append(adv)
            rets.append(ret)
        obs = np.concatenate(obs)
        n = len(obs)
        self.params, self.opt_state, aux = run_ppo_epochs(
            self._update, self.params, self.opt_state,
            obs=obs, actions=np.concatenate(acts),
            logp=np.concatenate(logps), adv=np.concatenate(advs),
            returns=np.concatenate(rets),
            num_epochs=self.cfg.num_epochs,
            minibatch_size=self.cfg.minibatch_size, seed=self.iteration)

        stats = ray_tpu.get([w.episode_stats.remote() for w in self.workers])
        mean_ret = float(np.mean([s["mean_return"] for s in stats
                                  if s["episodes"]])) \
            if any(s["episodes"] for s in stats) else 0.0
        self.iteration += 1
        return {
            "training_iteration": self.iteration,
            "episode_return_mean": mean_ret,
            "episodes_total": sum(s["episodes"] for s in stats),
            "timesteps_this_iter": n,
            "time_this_iter_s": time.time() - t0,
            **{k: float(v) for k, v in aux.items()},
        }

    def stop(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
