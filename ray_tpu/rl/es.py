"""Evolution strategies: ES (OpenAI) and ARS on the rollout-actor fleet.

Reference: rllib/algorithms/es/ (Salimans et al. 2017 — antithetic
Gaussian perturbations, centered-rank fitness shaping, shared noise
regenerated from seeds so only scalars cross the wire) and
rllib/algorithms/ars/ (Mania et al. 2018 — top-k directions scaled by
the std of their returns). Embarrassingly parallel episode evaluation is
the whole workload, so this is the purest expression of the actor-fleet
pattern: the "gradient" is assembled from scalar returns.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List

import numpy as np

import ray_tpu
from ray_tpu.rl.core import CPU_WORKER_ENV, Algorithm, episode_stats_from, probe_env_spec


# --- deterministic flat-vector policy ---------------------------------------


def _layer_shapes(obs_dim: int, out_dim: int, hidden: int):
    sizes = [obs_dim, hidden, hidden, out_dim]
    return [(i, o) for i, o in zip(sizes[:-1], sizes[1:])]


def flat_dim(obs_dim: int, out_dim: int, hidden: int) -> int:
    return sum(i * o + o for i, o in _layer_shapes(obs_dim, out_dim, hidden))


def policy_act(flat: np.ndarray, obs: np.ndarray, obs_dim: int,
               out_dim: int, hidden: int, discrete: bool, act_high: float):
    """Forward the flat parameter vector directly — perturbation math
    stays a single vector add, no tree plumbing."""
    x = obs.astype(np.float32)
    off = 0
    shapes = _layer_shapes(obs_dim, out_dim, hidden)
    for n, (i, o) in enumerate(shapes):
        w = flat[off:off + i * o].reshape(i, o)
        off += i * o
        b = flat[off:off + o]
        off += o
        x = x @ w + b
        if n < len(shapes) - 1:
            x = np.tanh(x)
    if discrete:
        return int(np.argmax(x))
    return np.clip(np.tanh(x) * act_high, -act_high, act_high)


@ray_tpu.remote
class _ESWorker:
    """Evaluates antithetic perturbation pairs; noise is regenerated from
    the seed on both ends so only (seed, return) scalars travel
    (ref: es.py SharedNoiseTable — same trick, seed-keyed)."""

    def __init__(self, env_name: str, env_config, obs_dim, out_dim, hidden,
                 discrete, act_high, max_episode_steps: int):
        import os

        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        import gymnasium as gym

        self.env = gym.make(env_name, **(env_config or {}))
        self.spec = (obs_dim, out_dim, hidden, discrete, act_high)
        self.max_steps = max_episode_steps
        self.completed: List[float] = []
        self._steps = 0

    def _episode(self, flat: np.ndarray, seed: int) -> float:
        obs, _ = self.env.reset(seed=seed)
        total = 0.0
        for _ in range(self.max_steps):
            a = policy_act(flat, np.asarray(obs).reshape(-1), *self.spec)
            obs, rew, term, trunc, _ = self.env.step(a)
            total += float(rew)
            self._steps += 1
            if term or trunc:
                break
        self.completed.append(total)
        return total

    def evaluate(self, flat: np.ndarray, seeds: List[int], sigma: float):
        self._steps = 0
        r_pos, r_neg = [], []
        for s in seeds:
            eps = np.random.default_rng(s).standard_normal(
                len(flat)).astype(np.float32)
            r_pos.append(self._episode(flat + sigma * eps, s))
            r_neg.append(self._episode(flat - sigma * eps, s))
        return {"seeds": seeds, "r_pos": np.asarray(r_pos, np.float32),
                "r_neg": np.asarray(r_neg, np.float32),
                "steps": self._steps}

    def episode_stats(self):
        return episode_stats_from(self.completed)


def _noise(seed: int, dim: int) -> np.ndarray:
    return np.random.default_rng(seed).standard_normal(dim).astype(np.float32)


def _centered_ranks(x: np.ndarray) -> np.ndarray:
    """Fitness shaping: returns -> ranks in [-0.5, 0.5] (ref: es.py
    compute_centered_ranks)."""
    ranks = np.empty(len(x), np.float32)
    ranks[x.argsort()] = np.arange(len(x), dtype=np.float32)
    return ranks / (len(x) - 1) - 0.5


@dataclass
class ESConfig:
    env: str = "CartPole-v1"
    env_config: Dict[str, Any] = field(default_factory=dict)
    num_rollout_workers: int = 2
    episodes_per_iter: int = 16      # antithetic PAIRS per iteration
    sigma: float = 0.1               # perturbation stddev
    lr: float = 0.02
    l2_coeff: float = 0.005
    max_episode_steps: int = 500
    hidden: int = 32
    seed: int = 0


class _EvolutionBase(Algorithm):
    """Shared fleet setup + seed fan-out for ES/ARS."""

    def _setup(self, cfg):
        obs_dim, n_actions, act_dim, act_high = probe_env_spec(
            cfg.env, cfg.env_config)
        self.discrete = n_actions is not None
        out_dim = n_actions if self.discrete else act_dim
        self.dim = flat_dim(obs_dim, out_dim, cfg.hidden)
        rng = np.random.default_rng(cfg.seed)
        self.flat = (rng.standard_normal(self.dim) * 0.05).astype(np.float32)
        self.workers = [
            _ESWorker.options(num_cpus=0.5, runtime_env=CPU_WORKER_ENV).remote(
                cfg.env, cfg.env_config, obs_dim, out_dim, cfg.hidden,
                self.discrete, act_high or 1.0, cfg.max_episode_steps)
            for _ in range(cfg.num_rollout_workers)]
        self._seed_counter = cfg.seed * 1_000_003
        self.timesteps = 0

    def _fan_out(self, n_pairs: int, sigma: float):
        """Distribute n_pairs antithetic evaluations over the fleet;
        returns (seeds, r_pos, r_neg) concatenated in seed order."""
        seeds = [self._seed_counter + i for i in range(n_pairs)]
        self._seed_counter += n_pairs
        chunks = np.array_split(np.asarray(seeds), len(self.workers))
        refs = [w.evaluate.remote(self.flat, list(map(int, c)), sigma)
                for w, c in zip(self.workers, chunks) if len(c)]
        out = ray_tpu.get(refs)
        r_pos = np.concatenate([o["r_pos"] for o in out])
        r_neg = np.concatenate([o["r_neg"] for o in out])
        self.timesteps += sum(o["steps"] for o in out)
        return np.asarray(seeds), r_pos, r_neg

    def _result(self, extra: Dict[str, Any]) -> Dict[str, Any]:
        stats = ray_tpu.get([w.episode_stats.remote() for w in self.workers])
        eps_done = [s for s in stats if s["episodes"]]
        return {
            "episode_return_mean": float(np.mean(
                [s["mean_return"] for s in eps_done])) if eps_done else 0.0,
            "episodes_total": sum(s["episodes"] for s in stats),
            "timesteps_total": self.timesteps,
            **extra,
        }

    def get_weights(self):
        return self.flat

    def set_weights(self, weights):
        self.flat = np.asarray(weights, np.float32)


class ESTrainer(_EvolutionBase):
    """OpenAI-ES: grad = E[centered_rank(R) * eps / sigma], Adam-free
    plain SGD with L2 pull toward 0 (ref: es.py Worker.do_rollouts +
    optimizers.py)."""

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        seeds, r_pos, r_neg = self._fan_out(cfg.episodes_per_iter, cfg.sigma)
        ranks = _centered_ranks(np.concatenate([r_pos, r_neg]))
        u_pos, u_neg = ranks[:len(r_pos)], ranks[len(r_pos):]
        grad = np.zeros(self.dim, np.float32)
        for s, up, un in zip(seeds, u_pos, u_neg):
            grad += (up - un) * _noise(int(s), self.dim)
        grad /= (2 * len(seeds) * cfg.sigma)
        self.flat = ((1 - cfg.l2_coeff * cfg.lr) * self.flat
                     + cfg.lr * grad)
        return self._result({
            "reward_mean_pos": float(r_pos.mean()),
            "reward_mean_neg": float(r_neg.mean()),
            "grad_norm": float(np.linalg.norm(grad)),
        })


@dataclass
class ARSConfig:
    env: str = "CartPole-v1"
    env_config: Dict[str, Any] = field(default_factory=dict)
    num_rollout_workers: int = 2
    num_directions: int = 16         # sampled directions per iteration
    top_directions: int = 8          # b best kept for the update
    sigma: float = 0.1
    step_size: float = 0.02
    max_episode_steps: int = 500
    hidden: int = 32
    seed: int = 0


class ARSTrainer(_EvolutionBase):
    """ARS V1-t: keep the top-b directions by max(r+, r-), scale the step
    by the std of their returns (ref: ars.py)."""

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        seeds, r_pos, r_neg = self._fan_out(cfg.num_directions, cfg.sigma)
        scores = np.maximum(r_pos, r_neg)
        top = np.argsort(scores)[-cfg.top_directions:]
        sigma_r = np.concatenate([r_pos[top], r_neg[top]]).std() + 1e-8
        grad = np.zeros(self.dim, np.float32)
        for i in top:
            grad += (r_pos[i] - r_neg[i]) * _noise(int(seeds[i]), self.dim)
        self.flat = self.flat + (
            cfg.step_size / (cfg.top_directions * sigma_r)) * grad
        return self._result({
            "reward_mean_top": float(scores[top].mean()),
            "sigma_r": float(sigma_r),
        })
